"""Device mesh construction.

The reference's entire distribution story is single-process
``nn.DataParallel`` over the GPUs of one host (SURVEY.md §2.2). The
TPU-native replacement is a named 2-D ``jax.sharding.Mesh``:

* ``dp`` — data parallel: the episode batch axis is sharded; gradients are
  all-reduced over ICI (XLA inserts the psum under GSPMD, or `shard_map`
  calls it explicitly).
* ``tp`` — tensor parallel: the NTN's bilinear slice axis (and, for BERT,
  attention heads / MLP hidden) shard here. Not needed for parity
  (SURVEY.md §2.2 says the reference has no TP) but it falls out of the
  design for free and covers the BERT-encoder scaling case.

On a multi-host pod, call :func:`maybe_initialize_distributed` first; the
mesh then spans ``jax.devices()`` across hosts with ICI inside a slice and
DCN between slices (axis order puts ``dp`` outermost = DCN-friendly;
``tp`` innermost = ICI-only).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    dp: int | None = None, tp: int = 1, sp: int = 1, pp: int = 1,
    ep: int = 1, devices=None,
) -> Mesh:
    """Build a (dp, pp, ep, tp, sp) mesh. ``dp=None`` -> use the rest.

    Axis roles:

    * ``dp`` — episodes sharded, gradients all-reduced (outermost: its
      collective is one allreduce per step, DCN-tolerant on pods).
    * ``pp`` — pipeline stages (parallel/pipeline.py): layer-stacked params
      shard here; activations hop stage-to-stage via ppermute.
    * ``ep`` — MoE experts (models/moe.py): expert-stacked params shard
      here; the dispatch/combine einsums become all-to-alls.
    * ``tp`` — tensor parallel (NTN slices, MLP column/row splits).
    * ``sp`` — sequence parallel, innermost so ring attention's per-hop
      ppermute of k/v blocks rides neighbor ICI links.

    Size-1 axes are free — PartitionSpecs that never mention them behave
    exactly as on a smaller mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    other = tp * sp * pp * ep
    if dp is None:
        if n % other != 0:
            raise ValueError(
                f"{n} devices not divisible by pp*ep*tp*sp={other}"
            )
        dp = n // other
    if dp * other > n:
        raise ValueError(
            f"dp*pp*ep*tp*sp={dp * other} exceeds {n} available devices"
        )
    grid = np.asarray(devices[: dp * other]).reshape(dp, pp, ep, tp, sp)
    return Mesh(grid, axis_names=("dp", "pp", "ep", "tp", "sp"))
