"""Device mesh construction.

The reference's entire distribution story is single-process
``nn.DataParallel`` over the GPUs of one host (SURVEY.md §2.2). The
TPU-native replacement is a named 2-D ``jax.sharding.Mesh``:

* ``dp`` — data parallel: the episode batch axis is sharded; gradients are
  all-reduced over ICI (XLA inserts the psum under GSPMD, or `shard_map`
  calls it explicitly).
* ``tp`` — tensor parallel: the NTN's bilinear slice axis (and, for BERT,
  attention heads / MLP hidden) shard here. Not needed for parity
  (SURVEY.md §2.2 says the reference has no TP) but it falls out of the
  design for free and covers the BERT-encoder scaling case.

On a multi-host pod, call :func:`maybe_initialize_distributed` first; the
mesh then spans ``jax.devices()`` across hosts with ICI inside a slice and
DCN between slices (axis order puts ``dp`` outermost = DCN-friendly;
``tp`` innermost = ICI-only).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    dp: int | None = None, tp: int = 1, sp: int = 1, devices=None
) -> Mesh:
    """Build a (dp, tp, sp) mesh. ``dp=None`` -> use all remaining devices.

    ``sp`` is the sequence-parallel axis consumed by ``parallel/ring.py``
    (ring attention); it is innermost so the per-hop ppermute of k/v blocks
    rides neighbor ICI links. A size-1 sp axis is free — PartitionSpecs that
    never mention it behave exactly as on a 2-D mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        if n % (tp * sp) != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp={tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp > n:
        raise ValueError(
            f"dp*tp*sp={dp * tp * sp} exceeds {n} available devices"
        )
    grid = np.asarray(devices[: dp * tp * sp]).reshape(dp, tp, sp)
    return Mesh(grid, axis_names=("dp", "tp", "sp"))
