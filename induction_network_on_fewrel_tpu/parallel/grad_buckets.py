"""Bucketed gradient collectives: explicit, hoisted, named dp psums.

Under plain GSPMD the dense-param gradient all-reduces are partitioner-
inserted at each dot-general transpose: metadata-bearing but scattered,
and printed wherever the partitioner leaves them. COMMS_r09's whole-step
window walk showed their dependent tails (the global-norm clip couples
EVERY update op to EVERY gradient reduction through the norm scalar), so
the only real lever is the other side of the window: make each reduction
*ready* — and printed — while earlier layers' backward is still
computing, the way DDP-style bucketed overlap works and the way PR 6
hoisted the compact-demb psum out of its shard_map body.

This module is that hoist, generalized:

* the fwd+bwd runs per-shard inside ``shard_map`` (no collective inside
  — the body emits partial gradients stacked on a dp-sharded leading
  axis, exactly the compact-demb "partials" half);
* the cross-shard reductions are free-floating means over the stacked
  axis OUTSIDE the body, grouped into reverse-topological buckets, each
  under its own ``jax.named_scope("grad/bucket_k")`` — GSPMD lowers each
  bucket to its own psum whose only consumer is the clip/update chain,
  so the scheduler (and XLA's async-collective pass on TPU) can fly
  bucket 0's all-reduce while bucket 3's backward still computes.

Reverse-topological means output-to-input: the relation/NTN head's
gradients are ready first in the backward, the word-embedding rows last
— so bucket 0 is the head and the last bucket is the table, mirroring
the model graph (models/induction.py: embedding -> encoder ->
induction/query_proj -> relation).

Numerics: the global gradient is the mean over shards of per-shard
means (equal shard sizes — shard_map enforces divisibility), identical
to the GSPMD global mean up to float reassociation; parity is pinned at
1e-5 in tests/test_comms.py, the same band as the compact-demb path.
The MoE balance aux is a product of GLOBAL-batch statistics, so the
resolution refuses MoE configs (same reason as the explicit shard_map
step). Lives in its own module (not parallel/sharding.py) because both
train/steps.py and parallel/sharding.py need it and sharding already
imports steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from induction_network_on_fewrel_tpu.parallel.compat import (
    shard_map as compat_shard_map,
)

# Leaf-path fragment -> backward stage, output-to-input (reverse
# topological): grads for stage-0 leaves are ready first in the backward,
# so their bucket's all-reduce can fly earliest. Unmatched paths land in
# the middle stage. "lazy_embed" is the compact [U, D] rows collection
# leaf the token-cache lazy step grafts in (train/lazy_embed.py) — input
# side, last stage, same as the dense table.
_STAGES: tuple[tuple[str, int], ...] = (
    ("relation", 0),
    ("induction", 1),
    ("query_proj", 1),
    ("att_", 2),
    ("encoder", 3),
    ("embedding", 4),
    ("lazy_embed", 4),
)
_N_STAGES = 5
_DEFAULT_STAGE = 2


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
        for p in path
    )


def bucket_index(path: str, n_buckets: int) -> int:
    """Bucket for a param-leaf path: stage scaled into [0, n_buckets)."""
    stage = _DEFAULT_STAGE
    for frag, s in _STAGES:
        if frag in path:
            stage = s
            break
    return min(stage * n_buckets // _N_STAGES, n_buckets - 1)


def grad_buckets_for(cfg, mesh: Mesh | None) -> int:
    """Resolve ``cfg.grad_bucketing`` against the mesh: the bucket count
    when the explicit bucketed-psum spelling applies, else 0 (monolithic
    partitioner-inserted psums — the A/B baseline arm).

    Applies only on pure-dp meshes (tp/sp/pp/ep params stay sharded and
    the shard_map's replicated param specs would force reshards), never
    under MoE (per-shard balance aux diverges from the global objective,
    same refusal as the explicit shard_map step), and "auto" resolves ON
    only on TPU AND only for the lazy-embed production path — the dense
    word-table arms keep the compact-demb spelling
    (parallel/sharding.demb_impl_for), which is mutually exclusive with
    the outer shard_map here and which bucketing cannot replicate for a
    genuinely dense table cotangent (its per-leaf mean would all-reduce
    the full [M, D] table: 80 MB/step at the flagship vocab, the exact
    round-6 regression). The bucket restructure is numerics-neutral
    anywhere, but flipping the default spelling is the chip A/B's call
    (models/build.resolve_runtime_backends records the projection;
    BASELINE.md round 21 queues the wall-clock arm). "on" forces it on
    any backend and any embed_optimizer — the CPU-mesh parity tests and
    the ledger's bucketed legs use that arm.
    """
    knob = getattr(cfg, "grad_bucketing", "off")
    if knob == "off" or mesh is None:
        return 0
    if "dp" not in mesh.axis_names or mesh.shape["dp"] <= 1:
        return 0
    if any(mesh.shape.get(ax, 1) > 1 for ax in ("tp", "sp", "pp", "ep")):
        return 0
    if getattr(cfg, "moe_experts", 0) > 0:
        return 0
    if knob == "auto" and (
        jax.default_backend() != "tpu"
        or getattr(cfg, "embed_optimizer", "shared") != "lazy"
    ):
        return 0
    return max(1, int(getattr(cfg, "grad_bucket_count", 4)))


def make_bucketed_value_and_grad(
    loss_fn_of, mesh: Mesh, n_buckets: int, frozen=None
):
    """The bucketed explicit spelling of a dp ``value_and_grad``.

    ``loss_fn_of(params, batch) -> (loss, aux)`` must be the LOCAL-shard
    objective (mean over its own examples — the standard per-example
    loss). Returns ``fn(params, batch) -> (grads, aux)`` taking the
    GLOBAL dp-sharded batch pytree (every array leaf's leading axis is
    the episode axis) and replicated params; grads/aux match what
    ``jax.grad(..., has_aux=True)`` returns on the global batch, up to
    float reassociation.

    ``frozen(path_str) -> bool`` marks param leaves the forward never
    reads (the dense word table riding the lazy compact step's p_fwd so
    flax finds the declared param). Their gradient is identically zero,
    and ``jax.grad`` would prove it — but only AFTER this wrapper stacked
    the zeros per shard and bucket-meaned them, which GSPMD lowers to a
    real all-reduce of the full leaf (80 MB/step at the flagship vocab).
    Frozen leaves are excluded from differentiation inside the shard_map
    and get exact ``zeros_like`` outside it: same gradient tree, no
    stacking, no collective.
    """

    def _split(params):
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        frz = [bool(frozen and frozen(_path_str(p))) for p, _ in flat]
        return flat, treedef, frz

    def local_grads(params, batch):
        flat, treedef, frz = _split(params)
        static = [v for (_, v), f in zip(flat, frz) if f]
        diff = [v for (_, v), f in zip(flat, frz) if not f]

        def lf(diff_leaves):
            it_d, it_s = iter(diff_leaves), iter(static)
            leaves = [next(it_s) if f else next(it_d) for f in frz]
            return loss_fn_of(
                jax.tree_util.tree_unflatten(treedef, leaves), batch
            )

        grads_diff, aux = jax.grad(lf, has_aux=True)(diff)
        # [1, ...] per shard -> stacked [dp, ...] on a dp-sharded leading
        # axis: the "partials" half, no collective in the body.
        return (
            [g[None] for g in grads_diff],
            jax.tree.map(lambda m: jnp.asarray(m)[None], aux),
        )

    # in/out specs are tree PREFIXES: P() replicates the whole params
    # tree, P("dp") shards every batch/output leaf's leading axis.
    sharded = compat_shard_map(
        local_grads, mesh=mesh,
        in_specs=(P(), P("dp")),
        out_specs=(P("dp"), P("dp")),
        check_vma=False,
    )

    def fn(params, batch):
        flat, treedef, frz = _split(params)
        with jax.named_scope("grad/bucket_partials"):
            stacked, aux_s = sharded(params, batch)
        paths = [
            _path_str(path) for (path, _), f in zip(flat, frz) if not f
        ]
        buckets = [bucket_index(p, n_buckets) for p in paths]
        reduced: list = [None] * len(stacked)
        for k in range(n_buckets):
            members = [i for i, b in enumerate(buckets) if b == k]
            if not members:
                continue
            # Free-floating mean over the dp-stacked axis: GSPMD lowers
            # it to this bucket's all-reduce, metadata-named here so
            # tools/comms_ledger.py attributes it per bucket.
            with jax.named_scope(f"grad/bucket_{k}"):
                for i in members:
                    reduced[i] = jnp.mean(stacked[i], axis=0)
        it = iter(reduced)
        leaves = [
            jnp.zeros_like(v) if f else next(it)
            for (_, v), f in zip(flat, frz)
        ]
        grads = jax.tree_util.tree_unflatten(treedef, leaves)
        aux = jax.tree.map(lambda m: jnp.mean(m, axis=0), aux_s)
        return grads, aux

    return fn
