"""jax API compatibility shims for the parallel layer.

``jax.shard_map`` is the stable name of what older jax (<= 0.4.x) exposes
only as ``jax.experimental.shard_map.shard_map`` — with ``check_vma``
spelled ``check_rep``. Every shard_map call site in this package goes
through :func:`shard_map` below, so the multichip paths run on both API
generations instead of dying with AttributeError on the older one.
"""

from __future__ import annotations

import jax


def axis_size(axis_name) -> int:
    """Static mesh-axis size inside a shard_map body: ``jax.lax.axis_size``
    where it exists, else the size recorded in the trace's axis frame
    (``jax.core.axis_frame`` — on 0.4.x it returns the size itself). Both
    are STATIC ints, so scan trip counts and ppermute rings built from the
    result stay compile-time constants."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return int(getattr(frame, "size", frame))


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when available, else the experimental spelling
    (mapping ``check_vma`` -> its old name ``check_rep``). Same contract;
    usable with ``functools.partial`` as a decorator exactly like the
    stable API."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
