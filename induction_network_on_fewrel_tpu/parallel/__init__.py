from induction_network_on_fewrel_tpu.parallel.mesh import make_mesh  # noqa: F401
from induction_network_on_fewrel_tpu.parallel.sharding import (  # noqa: F401
    batch_shardings,
    make_sharded_eval_step,
    make_sharded_train_step,
    state_shardings,
)
from induction_network_on_fewrel_tpu.parallel.distributed import (  # noqa: F401
    maybe_initialize_distributed,
)
from induction_network_on_fewrel_tpu.parallel.pipeline import (  # noqa: F401
    make_gpipe,
)
