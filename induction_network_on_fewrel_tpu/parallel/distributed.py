"""Multi-host initialization hook (SURVEY.md §5.8).

The reference has no multi-process communication layer at all; its analog
here is ``jax.distributed.initialize()``, which wires the hosts of a TPU pod
into one JAX process group: parameter/gradient collectives ride ICI inside a
slice, host coordination and cross-slice traffic ride DCN. No NCCL/MPI/Gloo.

Call this once at process start (the CLIs do). It is a no-op off-pod, so
single-host code paths never pay for it.
"""

from __future__ import annotations

import os

import jax

_POD_ENV_VARS = (
    # Set by TPU pod runtimes / launchers; presence implies a multi-host job.
    "COORDINATOR_ADDRESS",
    "TPU_WORKER_ID",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def maybe_initialize_distributed(
    force: bool = False, timeout_s: int | None = 300
) -> bool:
    """Initialize jax.distributed when running as one process of a pod job.

    Returns True if distributed mode was initialized. Safe to call twice
    (second call is a no-op). ``force=True`` initializes unconditionally
    (useful with explicit --coordinator flags).

    Failure detection (SURVEY.md §5.3): the coordination barrier gets a
    bounded ``timeout_s`` and a failed/timed-out rendezvous is re-raised as
    a clean RuntimeError naming the likely causes, instead of an opaque
    gRPC traceback from deep inside the client.
    """
    # jax.distributed.is_initialized landed after 0.4.37; on older jax the
    # global client handle is the only signal. Without this fallback every
    # CLI entrypoint dies at import-adjacent time on such versions.
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return True
    elif getattr(jax.distributed, "global_state", None) is not None and (
        jax.distributed.global_state.client is not None
    ):
        return True
    if force or any(v in os.environ for v in _POD_ENV_VARS):
        kwargs = {}
        if timeout_s is not None:
            kwargs["initialization_timeout"] = timeout_s
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:  # surface a clean, actionable error
            present = {v: os.environ[v] for v in _POD_ENV_VARS
                       if v in os.environ}
            bound = (
                f" (barrier bound: {timeout_s}s)" if timeout_s is not None
                else ""
            )
            raise RuntimeError(
                f"multi-host initialization failed{bound}: {e}. "
                f"Likely causes: a peer host crashed before the rendezvous, "
                f"the coordinator address is unreachable, or this process "
                f"was launched with pod env vars set ({present}) outside a "
                f"real pod job."
            ) from e
        return True
    return False
