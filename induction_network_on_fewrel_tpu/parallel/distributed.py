"""Multi-host initialization hook (SURVEY.md §5.8).

The reference has no multi-process communication layer at all; its analog
here is ``jax.distributed.initialize()``, which wires the hosts of a TPU pod
into one JAX process group: parameter/gradient collectives ride ICI inside a
slice, host coordination and cross-slice traffic ride DCN. No NCCL/MPI/Gloo.

Call this once at process start (the CLIs do). It is a no-op off-pod, so
single-host code paths never pay for it.
"""

from __future__ import annotations

import os

import jax

_POD_ENV_VARS = (
    # Set by TPU pod runtimes / launchers; presence implies a multi-host job.
    "COORDINATOR_ADDRESS",
    "TPU_WORKER_ID",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def maybe_initialize_distributed(force: bool = False) -> bool:
    """Initialize jax.distributed when running as one process of a pod job.

    Returns True if distributed mode was initialized. Safe to call twice
    (second call is a no-op). ``force=True`` initializes unconditionally
    (useful with explicit --coordinator flags).
    """
    if jax.distributed.is_initialized():
        return True
    if force or any(v in os.environ for v in _POD_ENV_VARS):
        jax.distributed.initialize()
        return True
    return False
