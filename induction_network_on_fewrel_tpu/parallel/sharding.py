"""Sharding rules + mesh-sharded train/eval steps.

Two equivalent multi-chip paths are provided (tested equal to the
single-device step in tests/test_parallel.py):

* **GSPMD (default)** — ``jax.jit`` with ``NamedSharding`` on state and
  batch; XLA partitions the whole fwd+bwd+update program and inserts the
  gradient all-reduce over ICI itself. Params are replicated over ``dp``
  and selectively sharded over ``tp`` (NTN slice axis); the episode batch
  axis is sharded over ``dp``.
* **shard_map** — explicit per-device program with ``jax.lax.pmean`` on
  gradients over the ``dp`` axis: the TPU-native spelling of the
  reference's DataParallel gradient reduction (SURVEY.md §2.2). Kept both
  as an escape hatch for when GSPMD's choices need overriding and as the
  explicit-collectives form.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from induction_network_on_fewrel_tpu.parallel.compat import (
    shard_map as compat_shard_map,
)

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.models.losses import (
    accuracy,
    episode_metrics,
    metric_keys,
)
from induction_network_on_fewrel_tpu.parallel.grad_buckets import (
    grad_buckets_for,
    make_bucketed_value_and_grad,
)
from induction_network_on_fewrel_tpu.train.steps import (
    LOSS_FNS,
    loss_and_metrics,
    make_update_body,
)

_BATCH_KEYS = ("word", "pos1", "pos2", "mask")

# --- partition rules -------------------------------------------------------

_TP_RULES: tuple[tuple[str, P], ...] = (
    # NTN bilinear tensor M[h, C, C]: shard the slice axis h.
    ("tensor_slices", P("tp", None, None)),
    # MoE expert-stacked weights [E, d, f] and biases [E, f]
    # (models/moe.py): the expert axis shards over ep; GSPMD turns the
    # dispatch/combine einsums into the token all-to-all.
    ("experts_up_bias", P("ep", None)),
    ("experts_down_bias", P("ep", None)),
    ("experts_up", P("ep", None, None)),
    ("experts_down", P("ep", None, None)),
    # Layer-stacked transformer (models/pipeline_transformer.py): the
    # leading layer axis shards over pp — each pipeline stage holds only
    # its own layers' weights and optimizer state. Two entries, one per
    # leaf rank (weights [NL, d, f], biases/LN [NL, d]).
    ("stack_", P("pp", None, None)),
    ("stack_", P("pp", None)),
    # Transformer blocks (models/bert.py, models/transformer.py):
    # Megatron-style — MLP up-projection column-sharded, down-projection
    # row-sharded. Bare substrings so both "intermediate/kernel" (bert) and
    # "intermediate_3/kernel" (transformer) match; the rank check keeps
    # biases replicated.
    ("intermediate", P(None, "tp")),
    ("mlp_out", P("tp", None)),
)


def _spec_for_path(path: str, leaf) -> P:
    for frag, spec in _TP_RULES:
        if frag in path and len(spec) == getattr(leaf, "ndim", 0):
            return spec
    return P()  # replicated (dp sees full params; XLA psums their grads)


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path
    )


def state_shardings(state: Any, mesh: Mesh, zero_opt: bool = False):
    """NamedShardings for a TrainState pytree. Works on real arrays or
    ``jax.eval_shape`` ShapeDtypeStructs (only structure/rank are read);
    opt-state leaves mirror the params rule via their own paths.

    ``zero_opt`` (ZeRO-1-style, SURVEY.md §2.2 "ZeRO/FSDP" row): Adam
    moment leaves (mu/nu) shard their leading axis over ``dp`` instead of
    replicating — each dp rank holds 1/dp of the optimizer state (the
    dominant HBM term beyond params: 2x params for Adam) and GSPMD inserts
    the reduce-scatter/all-gather around the update. Params themselves stay
    replicated (the tp/pp/ep rules still apply where they match), so
    forward/backward are unchanged; only the update's memory/communication
    layout moves. Per leaf, the first axis whose size divides dp evenly is
    sharded (``jax.device_put`` rejects uneven shards); leaves with no such
    axis (biases, odd-sized tables) stay replicated — best-effort coverage,
    which on BERT-base shards every kernel/moment matrix."""
    dp = mesh.shape["dp"] if "dp" in mesh.axis_names else 1

    def _effectively_replicated(spec) -> bool:
        # A spec whose named axes all have mesh size 1 (e.g. the tp rule on
        # a tp=1 mesh) is replication in practice — without this check the
        # largest BERT/transformer moment matrices (intermediate/mlp_out,
        # tensor_slices) would silently dodge the dp sharding.
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None and mesh.shape.get(ax, 1) > 1:
                    return False
        return True

    def assign(path, leaf):
        p = _path_str(path)
        spec = _spec_for_path(p, leaf)
        # Moment leaves: optax Adam's mu/nu under opt_state, plus the
        # lazy-embed table moments (LazyEmbedTrainState.emb_m/emb_v — the
        # [vocab, word_dim] pair that dominates optimizer HBM on the
        # 400k-vocab flagship; masked out of opt_state by design, so the
        # path rule above would miss them).
        is_moment = ("opt_state" in p and ("/mu/" in p or "/nu/" in p)) or (
            p.endswith("emb_m") or p.endswith("emb_v")
        )
        if (
            zero_opt
            and dp > 1
            and is_moment
            and _effectively_replicated(spec)
        ):
            for ax, size in enumerate(getattr(leaf, "shape", ())):
                if size >= dp and size % dp == 0:
                    axes = [None] * leaf.ndim
                    axes[ax] = "dp"
                    spec = P(*axes)
                    break
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(assign, state)


def shard_state(state: Any, mesh: Mesh, zero_opt: bool = False):
    """Place a (restored or freshly built) state onto the mesh shardings.

    Orbax restores commit arrays to a single device; jit with in_shardings
    refuses committed args with mismatched placement, so reshard explicitly.
    ``zero_opt`` must match the step factories' setting (state_shardings).
    """
    return jax.device_put(state, state_shardings(state, mesh, zero_opt=zero_opt))


def episode_batch_shardings(mesh: Mesh):
    """(support, query, label) shardings: episode axis over dp; the token
    (sequence) axis over sp when the mesh has one.

    Declaring the sequence split AT THE JIT BOUNDARY matters under sequence
    parallelism: ring attention consumes [.., L, ..] sharded over sp, and a
    dp-only input sharding forces the partitioner into an "involuntary full
    rematerialization" replicate-then-reshard of the narrow int8/int16
    mask/pos leaves (observed in MULTICHIP_r01) — handing it the target
    layout up front removes the reshard entirely.

    Token batches only — the feature-cache path has its own index-mode
    shardings (train/feature_cache.py ``_shard_cached``).
    """
    sp = (
        "sp"
        if "sp" in mesh.axis_names and mesh.shape["sp"] > 1
        else None
    )
    sup = {k: NamedSharding(mesh, P("dp", None, None, sp)) for k in _BATCH_KEYS}
    qry = {k: NamedSharding(mesh, P("dp", None, sp)) for k in _BATCH_KEYS}
    lab = NamedSharding(mesh, P("dp", None))
    return sup, qry, lab


def batch_shardings(mesh: Mesh, tree: Any):
    """Generic: leading (episode) axis over dp, everything else replicated."""

    def assign(leaf):
        ndim = getattr(leaf, "ndim", 0)
        spec = P(*(("dp",) + (None,) * (ndim - 1))) if ndim else P()
        return NamedSharding(mesh, spec)

    return jax.tree.map(assign, tree)


# --- compact demb (ZeRO-style sparse embedding gradient) -------------------


def make_compact_demb_lookup(mesh: Mesh):
    """Mesh-aware word-table lookup whose BACKWARD keeps demb local.

    The embedding gather's matmul-gradient backward (ops/segsum.py) is
    local arithmetic per token, but its chunked spelling flattens the
    token dims — merging the dp-sharded episode dim into its neighbors,
    which GSPMD cannot shard, so the partitioner replicated the cotangent
    and ids first: at the flagship shape a 26.1 MB/step/device
    ``[L, M, word_dim]`` f32 all-gather, 77% of the wire payload
    (COMMS_r06; the ZeRO sparse-gradient observation of Rajbhandari et
    al., 2020 applied to the induction encoder's word table). This
    wrapper is the explicit spelling of the fix:

    * forward: the plain gather, with a ``with_sharding_constraint``
      pinning the gathered ``[.., D]`` activation batch-sharded over dp —
      the ``[L, M, word_dim]`` activation stays sharded END TO END and
      XLA can never materialize the replicated form;
    * backward (custom VJP): ``shard_map`` over the mesh — each dp shard
      runs the chunked segment-sum on its LOCAL tokens only (the flatten
      is harmless per shard), then ONE ``psum`` reduces the compact
      ``[U, D]`` touched-row gradient across dp. The psum is wrapped in
      ``jax.named_scope("demb/compact_allreduce")`` so the collective's
      HLO metadata names this op — tools/comms_ledger.py attributes it.

    Returns ``lookup(table, ids, batch_dim)`` (batch_dim = which ids dim
    carries the dp-sharded episode rows: 1 for time-major [L, M], else
    0), or None when the mesh has no dp axis > 1 (nothing to keep local).
    Numerics: forward values are IDENTICAL to the plain gather; the
    gradient sums the same per-token terms grouped per shard first —
    float-associativity differences only (parity at 1e-5 in
    tests/test_comms.py, same band as the dense path).
    """
    if "dp" not in mesh.axis_names or mesh.shape["dp"] <= 1:
        return None
    import jax.numpy as jnp
    import numpy as np

    from induction_network_on_fewrel_tpu.ops.segsum import (
        MATMUL_GRAD_MAX_ROWS,
        _segment_sum_matmul,
    )

    def _local_segment_sum(cot_l, ids_l, num_rows):
        """Per-shard demb: the one-hot-matmul form below the scatter-vs-
        matmul crossover (ops/segsum.py), the native scatter-add above it
        (real corpora run 40-60k rows; at that size the O(T*U*D) one-hot
        matmul loses — the crossover is about backward FLOPs and is
        orthogonal to KEEPING the sum local, which is this wrapper's
        job). Both are exact sums of the same per-token terms."""
        if num_rows <= MATMUL_GRAD_MAX_ROWS:
            return _segment_sum_matmul(cot_l, ids_l, num_rows)
        cot2 = cot_l.reshape(-1, cot_l.shape[-1]).astype(jnp.float32)
        return jnp.zeros(
            (num_rows, cot_l.shape[-1]), jnp.float32
        ).at[ids_l.reshape(-1)].add(cot2)

    def lookup(table, ids, batch_dim: int):
        num_rows, table_dtype = table.shape[0], table.dtype

        def batch_spec(ndim: int) -> P:
            axes: list = [None] * ndim
            axes[batch_dim] = "dp"
            return P(*axes)

        @jax.custom_vjp
        def gather(tbl, idx):
            return tbl[idx]

        def gather_fwd(tbl, idx):
            out = jax.lax.with_sharding_constraint(
                tbl[idx], NamedSharding(mesh, batch_spec(idx.ndim + 1))
            )
            return out, idx

        def gather_bwd(idx, cot):
            def local_segsum(cot_l, ids_l):
                # Per-shard tokens only -> partial [U, D], stacked on a
                # dp-sharded leading axis. NO collective here: this is
                # the START half of the demb reduction.
                return _local_segment_sum(cot_l, ids_l, num_rows)[None]

            # Round-8 overlap restructure: the round-7 spelling ran the
            # [U, D] psum INSIDE the shard_map body, so the all-reduce
            # executed inline at emb-backward time with its result bound
            # to the region's output — zero scheduling freedom, part of
            # the ~22% un-overlapped comms measured in round 6. Now the
            # shard_map emits only the per-shard partials (start) and the
            # cross-shard reduction is a free-floating sum over the
            # dp-sharded axis (done) that GSPMD lowers to the SAME
            # compact [U, D] all-reduce — but as an op whose only
            # consumer is the word-table update at the end of the step.
            # Everything between the partials and that update — the
            # episode head's parameter-gradient matmuls (independent of
            # the demb chain by dataflow), the main-param Adam update,
            # the dp grad all-reduce — is schedulable while the
            # reduction is in flight, and XLA's async-collective pass
            # can split it into a start/done pair it latency-hides
            # (chip wall-clock A/B queued in BASELINE.md round 8; the
            # ledger reports the attributed row + async spelling).
            with jax.named_scope("demb/compact_partials"):
                partials = compat_shard_map(
                    local_segsum, mesh=mesh,
                    in_specs=(batch_spec(cot.ndim), batch_spec(idx.ndim)),
                    out_specs=P("dp", None, None), check_vma=False,
                )(cot, idx)  # [dp, U, D], leading axis dp-sharded
            with jax.named_scope("demb/compact_allreduce"):
                dtable = jnp.sum(partials, axis=0)
            return (
                dtable.astype(table_dtype),
                np.zeros(idx.shape, jax.dtypes.float0),
            )

        gather.defvjp(gather_fwd, gather_bwd)
        return gather(table, ids)

    return lookup


def demb_impl_for(cfg: ExperimentConfig, mesh: Mesh | None):
    """Resolve cfg.compact_demb against the mesh: the compact-demb lookup
    when it applies (mesh with dp > 1, knob not "off"), else None (the
    embedding keeps its mesh-free lookups). "auto" and "on" are the same
    resolution — the path is numerics-neutral graph restructuring, valid
    on any backend including the 8-virtual-device CPU mesh."""
    if mesh is None or getattr(cfg, "compact_demb", "auto") == "off":
        return None
    if grad_buckets_for(cfg, mesh) > 0:
        # The bucketed explicit backward (parallel/grad_buckets.py) runs
        # the WHOLE fwd+bwd per shard, so the demb segment-sum is local
        # by construction and its [U, D] row gradient reduces in the last
        # bucket's named psum — the compact wrapper's shard_map would
        # nest inside the outer one (illegal) and is redundant there.
        return None
    if "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        # Sequence parallelism shards the TOKEN axis of ids/cotangent; the
        # compact path's shard_map declares only the dp sharding and would
        # force an sp->replicated reshard of the cotangent — while the
        # reshape-free single-chunk segment-sum (ops/segsum.py) contracts
        # BOTH sharded dims natively at the shapes the sp legs run. Keep
        # the generic path there.
        return None
    return make_compact_demb_lookup(mesh)


# --- GSPMD steps -----------------------------------------------------------


def _zero1_update_shardings(cfg: ExperimentConfig, st_sh):
    """Param shardings for the explicit zero1 delta re-gather (round-8
    attribution payoff, train/steps.make_update_body): under --zero_opt
    the Adam moment math runs dp-sharded and the param deltas must come
    back to the params' layout — spelling that reshard as a traced
    with_sharding_constraint gives the all-gathers HLO metadata the
    ledger can attribute. None everywhere else (plain apply_gradients);
    the lazy table body keeps its own spelling either way."""
    if not getattr(cfg, "zero_opt", False) or cfg.embed_optimizer == "lazy":
        return None
    return st_sh.params


def make_sharded_train_step(model, cfg: ExperimentConfig, mesh: Mesh, state_example):
    """jit train step partitioned over ``mesh`` via NamedSharding.

    ``state_example``: a real TrainState or ``jax.eval_shape`` result —
    only tree structure and leaf ranks are read.
    """
    st_sh = state_shardings(
        state_example, mesh, zero_opt=getattr(cfg, "zero_opt", False)
    )
    repl = NamedSharding(mesh, P())
    sup_sh, qry_sh, lab_sh = episode_batch_shardings(mesh)
    body = make_update_body(
        model, cfg, update_shardings=_zero1_update_shardings(cfg, st_sh),
        mesh=mesh,
    )

    def step(state, support, query, label):
        return body(state, (support, query, label))

    return jax.jit(
        step,
        in_shardings=(st_sh, sup_sh, qry_sh, lab_sh),
        out_shardings=(st_sh, {"loss": repl, "accuracy": repl}),
        donate_argnums=(0,),
    )


def make_sharded_multi_train_step(
    model, cfg: ExperimentConfig, mesh: Mesh, state_example
):
    """Mesh-sharded twin of train.steps.make_multi_train_step: one dispatch
    scans ``steps_per_call`` stacked episode batches (leading axis S on every
    batch array, sharded ``P(None, 'dp', ...)`` — the scan axis is never
    partitioned), with the same GSPMD state shardings as the per-step path.
    Dispatch/transfer amortization and multi-chip scaling compose this way:
    XLA still inserts the gradient all-reduce over ICI inside every scan
    iteration."""
    st_sh = state_shardings(
        state_example, mesh, zero_opt=getattr(cfg, "zero_opt", False)
    )
    repl = NamedSharding(mesh, P())
    sup_sh, qry_sh, lab_sh = episode_batch_shardings(mesh)
    stack = lambda sh: jax.tree.map(
        lambda s: NamedSharding(mesh, P(None, *s.spec)), sh,
        is_leaf=lambda x: isinstance(x, NamedSharding),
    )
    body = make_update_body(
        model, cfg, update_shardings=_zero1_update_shardings(cfg, st_sh),
        mesh=mesh,
    )

    def multi_step(state, support_s, query_s, label_s):
        return jax.lax.scan(body, state, (support_s, query_s, label_s))

    return jax.jit(
        multi_step,
        in_shardings=(st_sh, stack(sup_sh), stack(qry_sh), stack(lab_sh)),
        out_shardings=(st_sh, {"loss": repl, "accuracy": repl}),
        donate_argnums=(0,),
    )


def make_sharded_eval_step(model, cfg: ExperimentConfig, mesh: Mesh, state_example):
    st_sh = state_shardings(state_example, mesh)
    repl = NamedSharding(mesh, P())
    sup_sh, qry_sh, lab_sh = episode_batch_shardings(mesh)

    def step(params, support, query, label):
        logits = model.apply(params, support, query)
        return {
            "loss": LOSS_FNS[cfg.loss](logits, label),
            **episode_metrics(logits, label, cfg.na_rate > 0),
        }

    return jax.jit(
        step,
        in_shardings=(st_sh.params, sup_sh, qry_sh, lab_sh),
        out_shardings={k: repl for k in metric_keys(cfg)},
    )


# --- explicit shard_map data-parallel step ---------------------------------


def make_shard_map_train_step(model, cfg: ExperimentConfig, mesh: Mesh):
    """Pure-dp explicit-collective step: each device computes grads on its
    episode shard, then reduces over 'dp' — the literal TPU analog of
    DataParallel's gradient reduction. Params replicated; updates identical
    on every device by construction.

    Two spellings of the reduction. With ``cfg.grad_bucketing`` resolved
    OFF: the legacy in-body ``lax.pmean`` — the all-reduce executes
    inline at backward time with its result bound to the region's
    output, zero scheduling freedom (the round-7 demb shape of the same
    problem). Resolved ON: the psums are HOISTED out of the shard_map
    body — the body emits per-shard partials and the cross-shard means
    run outside, one named reverse-topological bucket at a time
    (parallel/grad_buckets.py), with the optimizer update also outside —
    so each bucket's all-reduce is a free-floating op the scheduler can
    fly while later buckets' backward computes. Identical updates either
    way (1e-5 parity, tests/test_comms.py)."""
    if cfg.moe_experts > 0:
        # The MoE balance aux is a product of GLOBAL-batch statistics
        # (E·Σ f_e·p_e); a per-shard product pmean'd over dp is a different
        # objective (mean of products != product of means). The GSPMD path
        # partitions the global computation and stays exact — use it.
        raise ValueError(
            "the explicit shard_map step does not support MoE "
            "(per-shard load-balance aux diverges from the global "
            "objective); use the GSPMD sharded step"
        )

    n_buckets = grad_buckets_for(cfg, mesh)
    if n_buckets:
        def loss_fn_of(params, batch):
            support, query, label = batch
            return loss_and_metrics(
                model, params, support, query, label, cfg.loss
            )

        bucketed = make_bucketed_value_and_grad(loss_fn_of, mesh, n_buckets)

        def hoisted(state, support, query, label):
            grads, metrics = bucketed(
                state.params, (support, query, label)
            )
            return state.apply_gradients(grads=grads), metrics

        return jax.jit(hoisted, donate_argnums=(0,))

    @partial(
        compat_shard_map,
        mesh=mesh,
        in_specs=(
            P(),
            {k: P("dp", None, None, None) for k in _BATCH_KEYS},
            {k: P("dp", None, None) for k in _BATCH_KEYS},
            P("dp", None),
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def sharded(state, support, query, label):
        def loss_fn(params):
            return loss_and_metrics(model, params, support, query, label, cfg.loss)

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        grads = jax.lax.pmean(grads, "dp")
        metrics = jax.lax.pmean(metrics, "dp")
        new_state = state.apply_gradients(grads=grads)
        return new_state, metrics

    return jax.jit(sharded, donate_argnums=(0,))


# --- GSPMD adversarial (DANN) step -----------------------------------------


def make_sharded_adv_train_step(
    model, disc, cfg: ExperimentConfig, mesh: Mesh,
    state_example, disc_state_example,
):
    """Mesh-sharded twin of train.steps.make_adv_train_step: episode batch
    AND the unlabeled (source, target) instance batches shard over ``dp``;
    both TrainStates follow the standard partition rules. XLA inserts the
    gradient all-reduces — the domain game stays one program."""
    from induction_network_on_fewrel_tpu.models.base import FewShotModel
    from induction_network_on_fewrel_tpu.models.losses import cross_entropy_loss
    from induction_network_on_fewrel_tpu.ops import gradient_reversal
    import jax.numpy as jnp

    st_sh = state_shardings(
        state_example, mesh, zero_opt=getattr(cfg, "zero_opt", False)
    )
    dst_sh = state_shardings(disc_state_example, mesh)
    repl = NamedSharding(mesh, P())
    sup_sh, qry_sh, lab_sh = episode_batch_shardings(mesh)
    inst_sh = {k: NamedSharding(mesh, P("dp", None)) for k in _BATCH_KEYS}
    lam = cfg.adv_lambda
    aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0

    def encode(params, batch):
        return model.apply(
            params, batch["word"], batch["pos1"], batch["pos2"], batch["mask"],
            method=FewShotModel.encode,
        )

    def step(state, disc_state, support, query, label, src, tgt):
        def loss_fn(params, disc_params):
            # Few-shot objective (incl. any sown MoE aux) from the shared
            # loss_and_metrics — single source of aux handling.
            fs_loss, fs_metrics = loss_and_metrics(
                model, params, support, query, label, cfg.loss, aux_w
            )
            feat = jnp.concatenate(
                [encode(params, src), encode(params, tgt)], axis=0
            )
            dom_label = jnp.concatenate(
                [jnp.zeros(src["word"].shape[0], jnp.int32),
                 jnp.ones(tgt["word"].shape[0], jnp.int32)]
            )
            dom_logits = disc.apply(disc_params, gradient_reversal(feat, lam))
            dom_loss = cross_entropy_loss(dom_logits[None], dom_label[None])
            metrics = {
                **fs_metrics,
                "domain_loss": dom_loss,
                "domain_accuracy": accuracy(dom_logits[None], dom_label[None]),
            }
            return fs_loss + dom_loss, metrics

        grads, metrics = jax.grad(loss_fn, argnums=(0, 1), has_aux=True)(
            state.params, disc_state.params
        )
        return (
            state.apply_gradients(grads=grads[0]),
            disc_state.apply_gradients(grads=grads[1]),
            metrics,
        )

    metric_sh = {k: repl for k in
                 ("loss", "accuracy", "domain_loss", "domain_accuracy")}
    return jax.jit(
        step,
        in_shardings=(st_sh, dst_sh, sup_sh, qry_sh, lab_sh, inst_sh, inst_sh),
        out_shardings=(st_sh, dst_sh, metric_sh),
        donate_argnums=(0, 1),
    )
