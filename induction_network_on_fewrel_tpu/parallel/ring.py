"""Ring attention: sequence-parallel exact attention over an ``sp`` mesh axis.

The reference never needs sequence parallelism (sentences are <=128 tokens,
SURVEY.md §5.7), but this framework treats long-context as first-class: when
a sequence no longer fits one chip's HBM (or its O(L²) attention one chip's
FLOP budget), the sequence axis shards over the mesh's ``sp`` axis and
attention runs as a ring (Liu et al. 2023, "Ring Attention with Blockwise
Transformers"):

* every device keeps its local query block resident;
* key/value (+ key-padding-mask) blocks travel around the ring via
  ``lax.ppermute`` over ICI, one hop per step, so after ``sp`` steps every
  query block has attended to every key block;
* softmax never materializes globally — the flash-attention online
  (running-max, running-denominator) recurrence folds each arriving block
  into the accumulator, keeping memory O(L·L/sp) per device;
* compute and the ppermute transfer overlap: XLA double-buffers the ring
  (the next block is in flight while the current one multiplies on the MXU).

Exactness (vs. blockwise-approximate schemes) is tested against dense
attention on an 8-virtual-device CPU mesh in tests/test_ring.py, forward
and gradient.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from induction_network_on_fewrel_tpu.parallel.compat import (
    axis_size as compat_axis_size,
    shard_map as compat_shard_map,
)

_NEG = -1e30


def dense_attention(q, k, v, kv_mask=None):
    """Reference O(L²) attention. q,k,v: [B, H, L, D]; kv_mask: [B, L]."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, _NEG)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def ring_attention_local(q, k, v, kv_mask, axis_name: str):
    """Per-device ring attention body — call inside shard_map.

    q, k, v: [B, H, Lc, D] local chunks (sequence axis sharded over
    ``axis_name``); kv_mask: [B, Lc] key-padding mask chunk that travels
    with k/v. Returns the local output chunk [B, H, Lc, D].
    """
    n = compat_axis_size(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    B, H, Lc, D = q.shape
    q32 = q.astype(jnp.float32)

    m0 = jnp.full((B, H, Lc), _NEG, jnp.float32)        # running max
    l0 = jnp.zeros((B, H, Lc), jnp.float32)             # running denominator
    acc0 = jnp.zeros((B, H, Lc, D), jnp.float32)        # unnormalized out
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, _):
        m, l, acc, k_blk, v_blk, msk = carry
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        s = jnp.where(msk[:, None, None, :] > 0, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        # Rotate k/v/mask one hop around the ring (ICI neighbor exchange).
        k_blk, v_blk, msk = jax.lax.ppermute(
            (k_blk, v_blk, msk), axis_name, perm
        )
        return (m_new, l, acc, k_blk, v_blk, msk), None

    (m, l, acc, *_), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v, kv_mask), None, length=n
    )
    return (acc / (l[..., None] + 1e-30)).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "sp", batch_axis: str | None = None):
    """Global-view ring attention: q,k,v [B,H,L,D] sharded on L over ``axis``.

    Returns a jittable fn(q, k, v, kv_mask) -> [B,H,L,D]. When composing with
    data parallelism, pass ``batch_axis`` so the batch dimension's sharding
    is declared too (each dp group runs its own independent ring; no
    collectives cross dp).
    """
    b = batch_axis

    @partial(
        compat_shard_map,
        mesh=mesh,
        in_specs=(
            P(b, None, axis, None),
            P(b, None, axis, None),
            P(b, None, axis, None),
            P(b, axis),
        ),
        out_specs=P(b, None, axis, None),
        check_vma=False,
    )
    def fn(q, k, v, kv_mask):
        return ring_attention_local(q, k, v, kv_mask, axis)

    return fn
