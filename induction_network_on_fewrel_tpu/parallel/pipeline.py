"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.2 "PP: NO" — its
model fits one GPU many times over), but this framework treats every
parallelism axis as first-class. The encoder depth shards over ``pp``:
device s holds layers [s·NL/S, (s+1)·NL/S) of the layer-stacked transformer
(models/pipeline_transformer.py), and an episode batch flows through as
``m`` microbatches in the classic GPipe schedule:

  tick t: stage s processes microbatch (t - s); activations hop to stage
  s+1 over ICI via ``lax.ppermute``. After m + S - 1 ticks every microbatch
  has crossed every stage; the last stage's outputs are psum-broadcast back.

TPU-shaped choices:

* The whole schedule is ONE ``lax.scan`` inside ``shard_map`` — fixed trip
  count, static shapes, no data-dependent control flow; XLA pipelines the
  per-tick block compute against the neighbor ppermute.
* The bubble fraction is the textbook (S-1)/(m+S-1); callers pick
  ``microbatches`` >= S to amortize it. Throughput parity with the
  sequential executor is NOT the point on one host — HBM capacity per
  device is: each device materializes only 1/S of the layer weights and
  optimizer state (they are sharded P('pp', ...), never all-gathered).
* Reverse-mode AD just works: scan + ppermute are differentiable, so the
  backward pass is the mirrored pipeline (cotangents hop s+1 -> s), no
  hand-written schedule.

Exactness vs. the single-device sequential scan is pinned (forward AND
training trajectory) in tests/test_pipeline.py on the 8-virtual-CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from induction_network_on_fewrel_tpu.parallel.compat import (
    axis_size as compat_axis_size,
    shard_map as compat_shard_map,
)


def gpipe_local(block_fn: Callable, stacked_local, x: jnp.ndarray,
                mask: jnp.ndarray, axis: str, microbatches: int):
    """Per-device GPipe body — call inside shard_map.

    stacked_local: this stage's slice of the layer-stacked params (leading
    axis NL/S). x: [M, L, d] (replicated); mask: [M, L]. Returns [M, L, d].
    """
    S = compat_axis_size(axis)
    stage = jax.lax.axis_index(axis)
    m = microbatches
    M, L, d = x.shape
    if M % m != 0:
        # ValueError (not assert): survives python -O, and the CLI surfaces
        # it with per-flag guidance before tracing ever starts.
        raise ValueError(
            f"pipeline batch rows per dp shard ({M}) must divide evenly "
            f"into pp_microbatches ({m}); adjust --batch_size/--pp_microbatches"
        )
    mb = M // m

    xs = x.reshape(m, mb, L, d)
    ms = mask.reshape(m, mb, L)

    def stage_apply(act, act_mask):
        def body(carry, layer):
            return block_fn(layer, carry, act_mask), None

        out, _ = jax.lax.scan(body, act, stacked_local)
        return out

    # Activations and their masks travel together (stage s at tick t holds
    # microbatch t - s, so the mask must ride along the ring).
    shift = [(i, i + 1) for i in range(S - 1)]  # stage s -> s+1, no wrap

    def tick(carry, t):
        act, act_mask = carry
        j = jnp.clip(t, 0, m - 1)
        inj = jax.lax.dynamic_index_in_dim(xs, j, 0, keepdims=False)
        inj_m = jax.lax.dynamic_index_in_dim(ms, j, 0, keepdims=False)
        first = stage == 0
        cur = jnp.where(first, inj, act)
        cur_m = jnp.where(first, inj_m, act_mask)
        out = stage_apply(cur, cur_m)
        nxt = jax.lax.ppermute((out, cur_m), axis, shift)
        return nxt, out

    init = (jnp.zeros((mb, L, d), x.dtype), jnp.zeros((mb, L), mask.dtype))
    _, ys = jax.lax.scan(tick, init, jnp.arange(m + S - 1))

    # Microbatch j finishes on the last stage at tick j + S - 1.
    done = jax.lax.slice_in_dim(ys, S - 1, S - 1 + m, axis=0)  # [m, mb, L, d]
    last = (stage == S - 1).astype(done.dtype)
    out = jax.lax.psum(done * last, axis)
    return out.reshape(M, L, d)


def make_gpipe(mesh: Mesh, axis: str = "pp", microbatches: int = 4,
               batch_axis: str | None = None) -> Callable:
    """Build a pipeline executor for PipelinedTransformerEncoder.

    Returns ``(block_fn, stacked, x, mask) -> x`` with the stacked layer
    axis sharded over ``axis`` and the schedule of :func:`gpipe_local`
    running per stage. ``batch_axis`` declares the episode-batch sharding
    when composing with data parallelism (each dp group runs its own
    independent pipeline).
    """
    b = batch_axis

    def executor(block_fn, stacked, x, mask):
        spec_stack = jax.tree.map(
            lambda leaf: P(axis, *(None,) * (leaf.ndim - 1)), stacked
        )

        @partial(
            compat_shard_map,
            mesh=mesh,
            in_specs=(spec_stack, P(b, None, None), P(b, None)),
            out_specs=P(b, None, None),
            check_vma=False,
        )
        def run(stacked_local, x_l, mask_l):
            return gpipe_local(
                block_fn, stacked_local, x_l, mask_l, axis, microbatches
            )

        return run(stacked, x, mask)

    return executor
