"""Per-host data feeding for multi-host pods (SURVEY.md §2.2 DP row).

On a pod, every process runs this same program over its own addressable
devices. The reference's DataParallel has no analog for this (single
process); the TPU-native shape is:

1. **Stream partitioning** — each process samples ONLY the episodes its
   devices own. The global batch axis is sharded ``P('dp', ...)``; this
   module computes, from the mesh's device->process ownership, which
   contiguous row range of the global batch belongs to the calling process
   (``local_episode_range``). Each process builds its sampler with that
   local batch size and a process-strided seed (``process_seed``) so hosts
   draw disjoint episode streams — the samplers are pure functions of
   (seed, batch index), so the global stream is deterministic for a given
   process layout.
2. **Global array assembly** — ``GlobalBatchAssembler`` turns the local
   numpy rows into global ``jax.Array``s via
   ``jax.make_array_from_process_local_data``: every process contributes
   its shard, no host ever materializes (or transfers) the full global
   batch, and jit consumes the result without any resharding.

Single-process runs take the identical code path (local == global), which
is how the integration is tested on the 8-virtual-device CPU mesh; a real
pod changes only ``jax.process_count()``.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.obs.spans import span
from induction_network_on_fewrel_tpu.parallel.sharding import (
    episode_batch_shardings,
)


def episode_ranges_by_process(
    mesh: Mesh, global_batch: int, process_of=None
) -> dict[int, tuple[int, int]]:
    """{process_index: (start_row, num_rows)} of the global episode axis
    under ``P('dp')`` sharding.

    Pure function of the mesh layout — ``process_of`` (device -> process
    index, default the real attribute) is injectable so the multi-process
    partition math is unit-testable on a single-process CPU mesh.
    Episode rows are contiguous per process for standard pod meshes
    (devices enumerate process-major); a scrambled layout raises rather
    than silently feeding interleaved rows.
    """
    process_of = process_of or (lambda d: d.process_index)
    sharding = NamedSharding(mesh, P("dp"))
    dp = mesh.shape.get("dp", 1)
    if global_batch % max(dp, 1):
        raise ValueError(
            f"global batch {global_batch} must divide over dp={dp}"
        )
    rows: dict[int, set] = {}
    for dev, idx in sharding.devices_indices_map((global_batch,)).items():
        sl = idx[0]
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else global_batch
        rows.setdefault(process_of(dev), set()).update(range(start, stop))
    out = {}
    for pid, owned in rows.items():
        lo, hi = min(owned), max(owned) + 1
        if len(owned) != hi - lo:
            raise ValueError(
                f"process {pid} owns non-contiguous episode rows {sorted(owned)}; "
                f"per-host feeding needs a process-major 'dp' device order"
            )
        out[pid] = (lo, hi - lo)
    return out


def local_episode_range(mesh: Mesh, global_batch: int) -> tuple[int, int]:
    """(start_row, num_rows) of the global episode batch THIS process owns."""
    return episode_ranges_by_process(mesh, global_batch)[jax.process_index()]


_MASK64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """One splitmix64 output — the same mixer the C++ sampler uses for its
    own (seed, batch) expansion (native/episode_sampler.cpp:35)."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


def process_seed(seed: int) -> int:
    """Per-process sampler stream seed, splitmix64 domain-separated.

    Process 0 keeps the base seed unchanged (single-process runs remain
    bit-identical to the non-pod path); process p > 0 gets a splitmix64
    avalanche of (seed, p). What this guarantees: distinct, decorrelated
    64-bit seeds per process (and, through the samplers' own splitmix64 /
    PCG64 seed expansion, statistically independent episode streams). What
    it does NOT guarantee: provably disjoint stream trajectories — no seed
    derivation can (both RNG state spaces are finite). That is also not
    needed: episodes are iid draws, so any assignment of independent
    streams to hosts yields the same global distribution."""
    pid = jax.process_index()
    if pid == 0:
        return seed
    # Absorb seed and pid through two dependent splitmix64 rounds (the
    # second input depends on the first's avalanche, so (seed, pid) pairs
    # cannot cancel additively the way a linear stride could).
    return _splitmix64(_splitmix64(seed & _MASK64) ^ (pid & _MASK64))


class GlobalBatchAssembler:
    """Local (support, query, label) numpy rows -> global jax.Arrays.

    Uses ``jax.make_array_from_process_local_data`` against the SAME
    episode-batch shardings the sharded steps declare (parallel/sharding);
    jit then consumes the arrays with zero resharding. ``index_mode``
    switches to the cached-path layout (int32 index batches, generic
    leading-axis-over-dp specs).
    """

    def __init__(self, mesh: Mesh, global_batch: int, index_mode: bool = False):
        self.mesh = mesh
        self.global_batch = global_batch
        if index_mode:
            self._shardings = None  # generic leading-dp, built per-leaf
        else:
            self._shardings = episode_batch_shardings(mesh)

    def _leaf_sharding(self, leaf):
        ndim = np.ndim(leaf)
        return NamedSharding(
            self.mesh, P(*(("dp",) + (None,) * (ndim - 1))) if ndim else P()
        )

    def _assemble_leaf(self, sharding, local):
        global_shape = (self.global_batch,) + tuple(local.shape[1:])
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(local), global_shape
        )

    def __call__(self, support, query, label):
        if self._shardings is None:
            asm = lambda x: self._assemble_leaf(self._leaf_sharding(x), x)
            return (
                jax.tree.map(asm, support),
                jax.tree.map(asm, query),
                asm(label),
            )
        sup_sh, qry_sh, lab_sh = self._shardings
        sup = {k: self._assemble_leaf(sup_sh[k], v) for k, v in support.items()}
        qry = {k: self._assemble_leaf(qry_sh[k], v) for k, v in query.items()}
        return sup, qry, self._assemble_leaf(lab_sh, label)

    def _assemble_stacked_leaf(self, base_sharding, local):
        """[S, B_local, ...] -> global [S, B_global, ...]: the scan axis is
        never partitioned, dp moves to axis 1 — the exact input layout the
        fused sharded steps declare (sharding.make_sharded_multi_train_step
        and the cached _shard stacked specs)."""
        spec = P(None, *base_sharding.spec)
        sharding = NamedSharding(self.mesh, spec)
        global_shape = (
            local.shape[0], self.global_batch, *local.shape[2:]
        )
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(local), global_shape
        )

    def assemble_stacked(self, support, query, label):
        """Stacked twin of __call__ for steps_per_call-fused batches."""
        if self._shardings is None:
            asm = lambda x: self._assemble_stacked_leaf(
                self._leaf_sharding(x[0]), x
            )
            return (
                jax.tree.map(asm, support),
                jax.tree.map(asm, query),
                asm(label),
            )
        sup_sh, qry_sh, lab_sh = self._shardings
        sup = {
            k: self._assemble_stacked_leaf(sup_sh[k], v)
            for k, v in support.items()
        }
        qry = {
            k: self._assemble_stacked_leaf(qry_sh[k], v)
            for k, v in query.items()
        }
        return sup, qry, self._assemble_stacked_leaf(lab_sh, label)


class _AssembledBatch:
    """Duck-types the pass-through branch of batch_to_model_inputs."""

    def __init__(self, support, query, label):
        self.support, self.query, self.label = support, query, label


class PerHostSampler:
    """Wraps a process-LOCAL sampler; every ``sample_batch`` returns the
    assembled GLOBAL batch. ``batch_size`` reports the global size (the
    training framework computes episode counts from it)."""

    def __init__(self, local_sampler, assembler: GlobalBatchAssembler):
        self.local = local_sampler
        self.assembler = assembler
        self.batch_size = assembler.global_batch

    @property
    def total_q(self):
        return self.local.total_q

    @property
    def return_indices(self):
        return getattr(self.local, "return_indices", True)

    def sample_batch(self):
        # Feed-latency spans (obs/spans.py): per-host sampling vs global
        # assembly are the two halves of pod feed cost — separating them
        # tells a slow-feed investigation whether the sampler or the
        # make_array_from_process_local_data path is the term that grew.
        with span("hostfeed/sample"):
            sup, qry, lab = batch_to_model_inputs(self.local.sample_batch())
        with span("hostfeed/assemble"):
            return _AssembledBatch(*self.assembler(sup, qry, lab))

    def sample_fused(self, s: int):
        """S stacked local batches assembled into global [S, B_global, ...]
        arrays — keeps steps_per_call fusion available on pods."""
        local = self.local
        with span("hostfeed/sample", steps=s):
            if hasattr(local, "sample_fused"):
                sup, qry, lab = local.sample_fused(s)
            else:
                batches = [
                    batch_to_model_inputs(local.sample_batch())
                    for _ in range(s)
                ]
                sup, qry, lab = jax.tree.map(
                    lambda *xs: np.stack(xs), *batches
                )
        with span("hostfeed/assemble", steps=s):
            return self.assembler.assemble_stacked(sup, qry, lab)

    def __iter__(self):
        while True:
            yield self.sample_batch()

    # --- datapipe cursor protocol: position lives in the LOCAL sampler
    # (assembly is stateless); the cursor's layout fingerprint — not this
    # state — is what guards against cross-layout resumes.

    def feed_state(self) -> dict:
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            capture_sampler_state,
        )

        return {
            "kind": "perhost",
            "local": capture_sampler_state(self.local),
        }

    def restore_feed_state(self, state: dict) -> None:
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            restore_sampler_state,
        )

        restore_sampler_state(self.local, state["local"])

    def close(self):
        if hasattr(self.local, "close"):
            self.local.close()
