"""Reference-compatible CLI (SURVEY.md §5.6): flag names preserved from the
``train.py``/``test.py`` family (--N --K --Q --encoder --model --max_length
--na_rate --lr --train_iter --val_step --load_ckpt --save_ckpt --only_test),
plus the mandated ``--device={tpu,cpu}`` and mesh flags (--dp --tp).

The parsed flags become a frozen ExperimentConfig (serialized into the ckpt
dir), so a run is always reproducible from its checkpoint directory alone.
"""

from __future__ import annotations

import argparse
import os
import sys

from induction_network_on_fewrel_tpu.config import (
    ADAPT_KNOBS,
    ExperimentConfig,
    resolve_adapt_policy,
)


def build_arg_parser(train: bool = True) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native induction network on FewRel episodes"
    )
    # episode geometry (reference flag names)
    p.add_argument("--trainN", type=int, default=None, help="N-way during training (defaults to --N)")
    p.add_argument("--N", type=int, default=5, help="N-way at eval")
    p.add_argument("--K", type=int, default=5, help="K-shot")
    p.add_argument("--Q", type=int, default=5, help="queries per class")
    p.add_argument("--na_rate", type=int, default=0, help="NOTA negatives ratio (FewRel 2.0)")
    p.add_argument("--nota_head", default="scalar", choices=["scalar", "stats"],
                   help="NOTA threshold head: one global learned logit, or a "
                        "per-query learned affine over class-score statistics")
    p.add_argument("--batch_size", type=int, default=4, help="episodes per step")
    # model
    p.add_argument("--model", default="induction",
                   choices=["induction", "proto", "proto_hatt", "siamese",
                            "gnn", "snail", "metanet", "pair"],
                   help="few-shot model (pair = BERT-PAIR, needs --encoder bert)")
    p.add_argument("--proto_metric", default="euclid", choices=["euclid", "dot"], help="proto similarity")
    p.add_argument("--gnn_dim", type=int, default=64, help="features added per GNN block")
    p.add_argument("--gnn_blocks", type=int, default=2)
    p.add_argument("--snail_tc_filters", type=int, default=128)
    p.add_argument("--encoder", default="bilstm",
                   choices=["cnn", "bilstm", "bert", "transformer"])
    p.add_argument("--tfm_layers", type=int, default=4)
    p.add_argument("--tfm_model", type=int, default=256)
    p.add_argument("--tfm_heads", type=int, default=4)
    p.add_argument("--tfm_ff", type=int, default=1024)
    p.add_argument("--moe_experts", type=int, default=0,
                   help="MoE: route every --moe_every-th transformer block "
                        "through this many experts (0 = dense MLP)")
    p.add_argument("--moe_top_k", type=int, default=2)
    p.add_argument("--moe_capacity", type=float, default=2.0,
                   help="expert buffer capacity factor")
    p.add_argument("--moe_every", type=int, default=2)
    p.add_argument("--moe_group_size", type=int, default=512,
                   help="tokens per MoE routing group (memory knob)")
    p.add_argument("--moe_aux_weight", type=float, default=1e-2,
                   help="load-balance aux loss weight")
    p.add_argument("--max_length", type=int, default=40)
    p.add_argument("--hidden_size", type=int, default=230)
    p.add_argument(
        "--vocab_size", type=int, default=400002,
        help="word-embedding rows incl. UNK/BLANK (sets the synthetic GloVe "
             "size when no --glove file is given; overridden by a loaded "
             "vocab's true size)",
    )
    p.add_argument("--lstm_hidden", type=int, default=128)
    # The encoder's runtime backend knobs all resolve TPU-aware in ONE
    # place: models/build.resolve_runtime_backends (its docstring carries
    # the full resolution table — help texts here stay short and point at
    # it instead of restating stale copies). None of these are
    # architecture fields: params and checkpoints are identical across
    # every setting.
    p.add_argument(
        "--lstm_backend", default="auto",
        choices=["auto", "scan", "pallas", "interpret"],
        help="LSTM recurrence impl; auto = the fused Pallas kernel on a "
             "real TPU backend, lax.scan elsewhere (resolution table: "
             "models/build.resolve_runtime_backends)",
    )
    p.add_argument(
        "--attn_backend", default="auto",
        choices=["auto", "xla", "pallas", "interpret"],
        help="self-attention impl; auto = the two-pass XLA form on every "
             "backend (the fused one-pass kernel measured 0.97-0.98x of "
             "it on this chip, BASELINE.md round 5 — kept selectable for "
             "A/Bs on other silicon). Under --bf16 the backends shift "
             "metrics within bf16 tolerance, not bitwise (pinned in "
             "tests/test_attn.py::test_encoder_attn_backend_equivalence). "
             "Resolution table: models/build.resolve_runtime_backends",
    )
    p.add_argument(
        "--remat_attn", default="on", choices=["on", "off"],
        help="recompute-in-backward attention: save only the [M] softmax "
             "stats, rebuild the [L,M,A] projection in the kernel backward "
             "(attn bwd 213 -> 134 MB/step, ROOFLINE_r06). 'on' engages "
             "TPU-only, the same auto shape as --lstm_backend and "
             "--lstm_cs_window/--lstm_residuals (one table, one home: "
             "models/build.resolve_runtime_backends); parity in "
             "tests/test_attn.py",
    )
    p.add_argument(
        "--lstm_cs_window", type=int, default=8,
        help="windowed-cs remat in the fused BiLSTM backward (round 8): "
             "save one (h, c) checkpoint pair per this many timesteps "
             "instead of the full cell-state residual stream, recompute "
             "in-window states in VMEM (kernel fwd 146 -> 97, bwd 227 -> "
             "113 MB/step at W=8, ROOFLINE_r08). 0 = the round-6 "
             "full-residual design (the A/B twin). Kernel lstm paths "
             "only; parity at every W in tests/test_lstm.py (resolution "
             "table: models/build.resolve_runtime_backends)",
    )
    p.add_argument(
        "--lstm_residuals", default="auto", choices=["auto", "f32", "bf16"],
        help="storage dtype of the BiLSTM residual streams/checkpoints; "
             "auto = follow --compute dtype (bf16 on the flagship). VMEM "
             "carries and the in-window recompute stay f32; drift is "
             "policed by --grad_probe_every (resolution table: "
             "models/build.resolve_runtime_backends)",
    )
    p.add_argument("--induction_dim", type=int, default=100)
    p.add_argument("--routing_iters", type=int, default=3)
    p.add_argument("--ntn_slices", type=int, default=100)
    p.add_argument("--bert_frozen", action="store_true", help="freeze BERT backbone")
    p.add_argument("--bert_layers", type=int, default=12)
    p.add_argument("--bert_hidden", type=int, default=768)
    p.add_argument("--bert_heads", type=int, default=12)
    p.add_argument("--bert_intermediate", type=int, default=3072)
    p.add_argument("--bert_vocab", default=None, help="vocab.txt for WordPiece (hash fallback if absent)")
    p.add_argument("--bert_vocab_size", type=int, default=30522, help="embedding rows in hash-fallback mode")
    p.add_argument("--bert_weights", default=None, help=".npz of bert-base-uncased weights")
    p.add_argument("--bert_remat", action="store_true", help="rematerialize BERT layers (HBM headroom)")
    # optimization
    p.add_argument(
        "--feature_cache", action="store_true",
        help="frozen-encoder feature cache: encode the dataset once, train "
             "the episode head on gathered features (bert frozen only)",
    )
    p.add_argument(
        "--token_cache", action="store_true",
        help="device-resident token cache: upload the tokenized dataset "
             "once, stream only episode indices per step (any encoder, "
             "full training semantics; ~3-4x e2e on tunneled backends)",
    )
    p.add_argument(
        "--zero_opt", action="store_true",
        help="ZeRO-1-style optimizer-state sharding: Adam moments shard "
             "over the dp mesh axis (1/dp of the optimizer HBM per device; "
             "identical update trajectory)",
    )
    p.add_argument(
        "--divergence_guard", default="none", choices=["none", "stop"],
        help="on a >2x val-accuracy collapse (the MSE-sigmoid saturation "
             "dead zone — unrecoverable): 'none' logs it, 'stop' restores "
             "the best checkpoint and ends the run",
    )
    p.add_argument("--loss", default="mse", choices=["mse", "ce"])
    p.add_argument("--optimizer", default="adam", choices=["adam", "adamw", "sgd"])
    p.add_argument("--embed_optimizer", default="shared",
                   choices=["shared", "lazy", "sgd", "frozen"],
                   help="word-embedding table optimizer: shared = main "
                        "optimizer (reference parity: dense Adam + weight "
                        "decay on the whole 400k-row table every step; the "
                        "DEFAULT), lazy = dense Adam's EXACT trajectory "
                        "with weight decay excluded on the table (standard "
                        "embedding practice; parity pinned at 1e-6 in "
                        "tests/test_lazy_embed.py) at per-step cost "
                        "proportional to touched rows — with --token_cache "
                        "measured ~2x shared's throughput (13.1k vs 6.5k "
                        "eps/s/chip interleaved, BASELINE.md round 4); on "
                        "the synthetic overfit corpus the wd-free table "
                        "trains to lower val than shared (0.47-0.56 vs "
                        "0.70-0.78 — the regularization, not the laziness; "
                        "re-evaluate on real FewRel), sgd = stateless "
                        "scatter update, frozen = fixed GloVe")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--weight_decay", type=float, default=1e-5)
    p.add_argument("--lr_step_size", type=int, default=2000)
    p.add_argument("--grad_clip", type=float, default=10.0)
    if train:
        p.add_argument("--train_iter", type=int, default=10000)
        p.add_argument("--val_iter", type=int, default=1000)
        p.add_argument("--val_step", type=int, default=1000)
        p.add_argument(
            "--force", action="store_true",
            help="run configs BASELINE.md documents as degenerate "
                 "(e.g. --loss mse with --na_rate >= 3)",
        )
    # On both parsers: test.py's eval loop fuses batches per dispatch too
    # (a 3000-episode test at per-batch dispatch pays hundreds of ~50 ms
    # tunnel round-trips that fused eval amortizes).
    p.add_argument(
        "--steps_per_call", type=int, default=1,
        help="optimizer steps (or eval batches) fused into one dispatch "
             "(lax.scan); identical results, amortized host/transfer latency",
    )
    p.add_argument(
        "--eval_steps_per_call", type=int, default=0,
        help="eval batches fused per dispatch at val/test boundaries "
             "(0 = auto: min(steps_per_call, 16) — right-sizes boundary "
             "evals instead of padding small val splits to the training "
             "scan width)",
    )
    p.add_argument(
        "--metric_window_calls", type=int, default=4,
        help="fused train calls between metric fetches (each fetch is a "
             "real device sync on tunneled backends)",
    )
    p.add_argument(
        "--ckpt_stage", default="auto", choices=["auto", "off"],
        help="checkpoint tmpfs staging: orbax writes to /dev/shm and the "
             "async saver thread drains to --save_ckpt (auto falls back to direct "
             "writes without /dev/shm or on multi-host runs)",
    )
    p.add_argument(
        "--ckpt_delta", default="auto", choices=["auto", "off"],
        help="delta ring checkpoints: recovery-ring saves write base + "
             "touched-row deltas for the lazy embedding table/moments "
             "(auto = on for --embed_optimizer lazy states; the ~240 MB "
             "table+moment d2h per boundary shrinks to the rows that "
             "actually changed). Best-checkpoint saves stay full; "
             "resume-from-delta is trajectory-equal (tests/test_ckpt_delta.py)",
    )
    p.add_argument("--test_iter", type=int, default=3000)
    # data
    p.add_argument("--train_file", default=None, help="FewRel-schema JSON; synthetic if omitted")
    p.add_argument("--val_file", default=None)
    p.add_argument("--test_file", default=None)
    if train:
        # FewRel 2.0 adversarial domain adaptation (DANN, one jitted step).
        p.add_argument("--adv", nargs="?", const="synthetic", default=None,
                       metavar="TARGET_FILE",
                       help="adversarial adaptation against this unlabeled "
                            "target-domain FewRel-schema JSON (e.g. pubmed); "
                            "bare --adv uses a synthetic target domain")
        p.add_argument("--adv_lambda", type=float, default=1.0,
                       help="gradient-reversal scale on the encoder")
        p.add_argument("--adv_dis_hidden", type=int, default=256)
        p.add_argument("--adv_batch", type=int, default=32,
                       help="unlabeled instances per domain per step")
    p.add_argument("--glove", default=None, help="GloVe json (word2id or combined)")
    p.add_argument("--glove_mat", default=None, help=".npy matrix for word2id json")
    # host data pipeline
    p.add_argument(
        "--sampler", default="auto", choices=["auto", "native", "python"],
        help="episode sampler backend: native = C++ prefetching pipeline",
    )
    p.add_argument("--prefetch", type=int, default=4, help="native sampler ring-buffer depth (0 = sync)")
    p.add_argument("--sampler_threads", type=int, default=2, help="native sampler worker threads")
    p.add_argument(
        "--prefetch_depth", type=int, default=2,
        help="datapipe producer-pipeline depth (units of steps_per_call "
             "batches on fused index paths): a background thread samples/"
             "assembles ahead into a bounded queue so host feed overlaps "
             "train/dispatch; the pipeline cursor rides in every "
             "checkpoint and resume replays the exact episode stream. "
             "0 = the synchronous path (bitwise-identical stream)",
    )
    p.add_argument(
        "--mixture", default="",
        help="episode-mixture schedule (datapipe/mixture.py): "
             "'source:w[@idx][,w@idx...];...' where a source is 'train' or "
             "a FewRel-schema JSON path, e.g. "
             "'train:1.0;pubmed.json:0.0@0,1.0@4000' (DA ramp). Weights "
             "interpolate linearly over the batch index; the per-batch "
             "source pick is deterministic from (seed, batch index) and "
             "resumes exactly from the checkpoint cursor. Live token path "
             "only",
    )
    if train:
        p.add_argument(
            "--feed_fault", default="",
            help="input-pipeline fault injection (debug drills): "
                 "'slow:SECONDS', 'stall:INDEX', 'poison:INDEX' "
                 "(comma-separable) — exercises the watchdog's feed_stall/"
                 "feed_poisoned detectors (RUNBOOK §10)",
        )
        p.add_argument(
            "--chaos", default="",
            help="unified chaos-injection plan (obs/chaos.py, RUNBOOK "
                 "§17): comma-separated POINT@AT[*COUNT][:ARG] "
                 "directives over the named fault points — e.g. "
                 "'ckpt.bitflip@1:ring_delta' corrupts the 2nd delta "
                 "ring save. Deterministic; every fired fault emits a "
                 "kind='fault' record; the containment layer "
                 "(quarantine + ring-walk fallback) is what a drill "
                 "asserts on. '' = off (zero-cost)",
        )
        # Self-healing adaptation policy (obs/adapt.py, ISSUE 14,
        # RUNBOOK §19): resolved in ONE home
        # (config.resolve_adapt_policy, shared with serve.py). A train
        # run stamps the policy into the checkpoint's config.json, so a
        # serving controller fine-tuning FROM this artifact inherits it
        # without re-spelling the knobs.
        p.add_argument(
            "--adapt", action="store_true",
            help="stamp a self-healing adaptation policy into this "
                 "run's checkpoints: a serving-side controller "
                 "(serve.py --adapt) fine-tuning from the artifact "
                 "inherits the budgets below (RUNBOOK §19)",
        )
        p.add_argument("--adapt_retries", type=int, default=None,
                       help="adaptation flap damper: failed loops "
                            "before the permanent adapt_exhausted "
                            "CRITICAL + tenant quarantine")
        p.add_argument("--adapt_backoff_s", type=float, default=None,
                       help="base retry backoff seconds (doubles per "
                            "failed attempt)")
        p.add_argument("--adapt_cooldown_s", type=float, default=None,
                       help="post-success trigger suppression seconds")
        p.add_argument("--adapt_step_budget", type=int, default=None,
                       help="fine-tune optimizer-step budget")
        p.add_argument("--adapt_wall_s", type=float, default=None,
                       help="fine-tune wall-clock budget seconds "
                            "(breach = timeout-kill + checkpoint "
                            "cleanup)")
        p.add_argument("--adapt_verify_s", type=float, default=None,
                       help="post-publish verification window seconds "
                            "(drift re-trip inside it rolls back)")
        p.add_argument("--adapt_canary", default=None,
                       help="pre-publish canary plan: 'leg:floor[,leg:"
                            "floor...]' accuracy bars "
                            "(tools/scenarios.run_canary) or 'off'")
    # device / parallelism
    p.add_argument("--device", default="tpu", choices=["tpu", "cpu"])
    p.add_argument(
        "--compile_cache", default="auto", metavar="DIR|off",
        help="persistent XLA compilation cache dir. Warm restarts then "
             "skip the backend compile of the fused step (measured round "
             "5: first call 14.2s cold -> 7.7s warm on the flagship "
             "program; tracing/lowering still runs). 'auto' = "
             "~/.cache/induction_tpu_xla; 'off' disables.",
    )
    p.add_argument(
        "--compact_demb", default="auto", choices=["auto", "on", "off"],
        help="dp-sharded embedding gradient: keep the demb segment-sum "
             "local per shard and all-reduce only the compact [U, D] "
             "touched-row gradient, instead of GSPMD replicating the "
             "[L, M, word_dim] embedding cotangent (26 MB/step/device at "
             "the flagship shape — COMMS_r07). 'off' restores the dense "
             "behavior for A/Bs; identical params/checkpoints either way",
    )
    p.add_argument(
        "--grad_bucketing", default="auto", choices=["auto", "on", "off"],
        help="bucketed gradient collectives on pure-dp meshes: per-shard "
             "fwd+bwd in shard_map, then one named, hoisted all-reduce per "
             "reverse-topological bucket (grad/bucket_0 = relation head "
             "... last = embedding) so each bucket's reduction can fly "
             "while earlier layers' backward computes (COMMS_r10). "
             "'auto' = TPU only; 'on' forces the bucketed arm anywhere; "
             "'off' = monolithic GSPMD psums. Identical params either way",
    )
    p.add_argument("--grad_bucket_count", type=int, default=4,
                   help="bucket count when --grad_bucketing resolves on")
    p.add_argument(
        "--async_collectives", default="auto", choices=["auto", "on", "off"],
        help="async-collective / latency-hiding-scheduler spelling "
             "(resolved on TPU like --lstm_backend auto; CPU records the "
             "projection only — chip A/B queued in BASELINE.md round 21)",
    )
    p.add_argument("--dp", type=int, default=0, help="data-parallel mesh axis (0 = all devices)")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel mesh axis")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel mesh axis: ring attention over "
                        "the token axis (transformer encoder only)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel mesh axis: transformer layer "
                        "stages with microbatched GPipe schedule")
    p.add_argument("--pp_microbatches", type=int, default=4,
                   help="GPipe microbatches per step (bubble = (pp-1)/(m+pp-1))")
    p.add_argument("--tfm_stacked", action="store_true",
                   help="layer-stacked transformer params (pp-restorable "
                        "checkpoints; implied by --pp > 1)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel mesh axis (requires --moe_experts)")
    p.add_argument("--fp16", action="store_true", help="(reference flag) alias for bf16 compute")
    p.add_argument("--bf16", action="store_true", help="bfloat16 matmuls on the MXU")
    # checkpoints / run dir
    p.add_argument("--save_ckpt", default="./checkpoint", help="checkpoint directory")
    p.add_argument("--load_ckpt", default=None, help="checkpoint directory to restore")
    if train:
        p.add_argument("--resume", action="store_true", help="resume latest state from --save_ckpt")
        p.add_argument("--only_test", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--run_dir", default=None, help="metrics/log dir (defaults to --save_ckpt)")
    # observability / sanitizers (SURVEY.md §5.1-5.2)
    if train:
        p.add_argument("--profile", default=None, metavar="DIR",
                       help="write a TensorBoard XPlane trace of steps "
                            "2..2+profile_steps to DIR")
        p.add_argument("--tensorboard", default=None, metavar="DIR",
                       help="also mirror train/val scalars to TensorBoard "
                            "event files in DIR (metrics.jsonl is always "
                            "written)")
        p.add_argument("--profile_steps", type=int, default=10)
        p.add_argument("--debug_nans", action="store_true",
                       help="checkify the train step: raise on NaN/inf/OOB "
                            "(debug runs; costs fusion boundaries)")
        p.add_argument("--fault_step", type=int, default=0,
                       help="failure injection: crash once the step counter "
                            "reaches N on a FRESH run (resumed runs ignore "
                            "it, so crash -> --resume completes; exercises "
                            "the recovery ring; debug)")
        p.add_argument("--watchdog", action="store_true",
                       help="run-health watchdog (obs/health.py): NaN/Inf "
                            "scalars, throughput regression, routing "
                            "collapse -> kind='health' events; critical "
                            "events dump flight_recorder.json to --run_dir")
        p.add_argument("--grad_probe_every", type=int, default=0,
                       help="every K steps, log grad global-norm + "
                            "grad-cosine vs an all-f32 reference backward "
                            "on the same batch (bf16-backward soak "
                            "visibility; 0 = off)")
        p.add_argument("--nan_inject_step", type=int, default=0,
                       help="telemetry-failure injection: corrupt the "
                            "LOGGED loss with NaN once past step N "
                            "(training unaffected; exercises the watchdog "
                            "trip + flight-recorder dump; debug)")
        p.add_argument("--perf", action="store_true",
                       help="performance-attribution observability "
                            "(obs/perf.py + obs/compile.py): per-window "
                            "step-time decomposition (kind='perf' segments "
                            "tile the window), XLA compile forensics "
                            "(kind='compile' with fn/shapes/elapsed/"
                            "trigger + the steady-recompile gate), and "
                            "named-cause classification of slow windows "
                            "with auto-captured diagnostics (RUNBOOK §16)")
    return p


def _check_degenerate(loss: str, na_rate: int, force: bool) -> None:
    """BASELINE.md round-2 finding: MSE loss at na_rate >= 3 falls into the
    all-NOTA optimum and stays (train accuracy pinned at the NOTA
    fraction). Training runs must opt in explicitly with --force;
    eval-only invocations compute no training loss and are exempt."""
    if loss == "mse" and na_rate >= 3 and not force:
        raise ValueError(
            f"--loss mse with --na_rate {na_rate} is a known-degenerate "
            f"combination (BASELINE.md: the sigmoid-MSE objective's all-NOTA "
            f"optimum dominates at high NOTA rates and training collapses "
            f"to it). Use --loss ce, lower --na_rate, or pass --force to "
            f"run it anyway"
        )


def config_from_args(args: argparse.Namespace) -> ExperimentConfig:
    if getattr(args, "feature_cache", False) and getattr(args, "token_cache", False):
        # Checked here, not in make_trainer: the feature-cache block runs
        # first there and would encode whole splits through the backbone
        # (minutes on a tunneled TPU) before the conflict surfaced.
        raise ValueError(
            "--token_cache and --feature_cache are exclusive (the feature "
            "cache already runs in index mode)"
        )
    # Degenerate-config guard — on the raw flags here, and AGAIN in
    # train_main on the checkpoint-merged config (_merge_ckpt_architecture
    # can flip loss back to mse from a restored config.json).
    if (
        getattr(args, "train_iter", 0)
        and not getattr(args, "only_test", False)
    ):
        _check_degenerate(
            args.loss, args.na_rate, getattr(args, "force", False)
        )
    compute = "bfloat16" if (args.bf16 or args.fp16) else "float32"
    train_iter = getattr(args, "train_iter", 0)
    val_iter = getattr(args, "val_iter", 1000)
    val_step = getattr(args, "val_step", 0)
    cfg = ExperimentConfig(
        train_n=args.trainN or args.N,
        n=args.N, k=args.K, q=args.Q, na_rate=args.na_rate,
        nota_head=args.nota_head,
        batch_size=args.batch_size, max_length=args.max_length,
        vocab_size=getattr(args, "vocab_size", 400002),
        model=args.model, proto_metric=args.proto_metric,
        gnn_dim=args.gnn_dim, gnn_blocks=args.gnn_blocks,
        snail_tc_filters=args.snail_tc_filters,
        encoder=args.encoder, hidden_size=args.hidden_size,
        lstm_hidden=args.lstm_hidden, lstm_backend=args.lstm_backend,
        attn_backend=args.attn_backend,
        remat_attn=getattr(args, "remat_attn", "on") == "on",
        lstm_cs_window=getattr(args, "lstm_cs_window", 8),
        lstm_residuals=getattr(args, "lstm_residuals", "auto"),
        tfm_layers=args.tfm_layers, tfm_model=args.tfm_model,
        tfm_heads=args.tfm_heads, tfm_ff=args.tfm_ff,
        moe_experts=args.moe_experts, moe_top_k=args.moe_top_k,
        moe_capacity=args.moe_capacity, moe_every=args.moe_every,
        moe_group_size=args.moe_group_size,
        moe_aux_weight=args.moe_aux_weight,
        induction_dim=args.induction_dim,
        routing_iters=args.routing_iters, ntn_slices=args.ntn_slices,
        bert_frozen=args.bert_frozen, bert_layers=args.bert_layers,
        bert_hidden=getattr(args, "bert_hidden", 768),
        bert_heads=getattr(args, "bert_heads", 12),
        bert_intermediate=getattr(args, "bert_intermediate", 3072),
        bert_vocab_size=args.bert_vocab_size, bert_vocab_path=args.bert_vocab,
        bert_remat=args.bert_remat, bert_weights=args.bert_weights,
        loss=args.loss, optimizer=args.optimizer,
        embed_optimizer=args.embed_optimizer, lr=args.lr,
        weight_decay=args.weight_decay, lr_step_size=args.lr_step_size,
        grad_clip=args.grad_clip, train_iter=train_iter,
        val_iter=val_iter, val_step=val_step, test_iter=args.test_iter,
        steps_per_call=getattr(args, "steps_per_call", 1),
        eval_steps_per_call=getattr(args, "eval_steps_per_call", 0),
        metric_window_calls=getattr(args, "metric_window_calls", 4),
        ckpt_stage=getattr(args, "ckpt_stage", "auto"),
        ckpt_delta=getattr(args, "ckpt_delta", "auto"),
        feature_cache=getattr(args, "feature_cache", False),
        token_cache=getattr(args, "token_cache", False),
        divergence_guard=getattr(args, "divergence_guard", "none"),
        fault_step=getattr(args, "fault_step", 0),
        watchdog=getattr(args, "watchdog", False),
        grad_probe_every=getattr(args, "grad_probe_every", 0),
        nan_inject_step=getattr(args, "nan_inject_step", 0),
        perf=getattr(args, "perf", False),
        zero_opt=getattr(args, "zero_opt", False),
        compact_demb=getattr(args, "compact_demb", "auto"),
        grad_bucketing=getattr(args, "grad_bucketing", "auto"),
        grad_bucket_count=getattr(args, "grad_bucket_count", 4),
        async_collectives=getattr(args, "async_collectives", "auto"),
        device=args.device, compute_dtype=compute, seed=args.seed,
        dp=args.dp, tp=args.tp, sp=args.sp, pp=args.pp, ep=args.ep,
        pp_microbatches=args.pp_microbatches,
        tfm_stacked=args.tfm_stacked or args.pp > 1,
        sampler=args.sampler, prefetch=args.prefetch,
        sampler_threads=args.sampler_threads,
        prefetch_depth=getattr(args, "prefetch_depth", 2),
        mixture=getattr(args, "mixture", ""),
        feed_fault=getattr(args, "feed_fault", ""),
        chaos=getattr(args, "chaos", ""),
        adapt=getattr(args, "adapt", False),
        # Adapt knobs left unset keep the dataclass defaults; the whole
        # policy is validated in ONE home (config.resolve_adapt_policy)
        # right below, so a bad knob fails at run start, not when the
        # first drift CRITICAL tries to use it.
        **{
            k: v for k, v in (
                (k, getattr(args, k, None)) for k in ADAPT_KNOBS
            ) if v is not None
        },
        adv=getattr(args, "adv", None) is not None,
        adv_lambda=getattr(args, "adv_lambda", 1.0),
        adv_dis_hidden=getattr(args, "adv_dis_hidden", 256),
        adv_batch=getattr(args, "adv_batch", 32),
    )
    resolve_adapt_policy(cfg)   # fail-fast knob validation (no-op when off)
    return cfg


def select_device(cfg: ExperimentConfig, compile_cache: str = "auto") -> None:
    """Apply --device (and the persistent compile cache) before any jax
    backend init.

    --device=cpu must use the config-update path: this image's axon
    sitecustomize overrides jax_platforms, so the env var alone would still
    dial the TPU tunnel (see tests/conftest.py).
    """
    import jax

    if cfg.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if compile_cache != "off":
        path = (
            os.path.expanduser("~/.cache/induction_tpu_xla")
            if compile_cache == "auto" else compile_cache
        )
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # The flagship fused program compiles in ~13 s — always worth
            # caching. The 0.5 s threshold is deliberate: it admits every
            # program whose compile is actually felt (the fused step, the
            # boundary evals) while still excluding trivial sub-0.5 s
            # utility programs, which would churn cache entries for no
            # measurable wall-clock win.
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        except Exception as e:  # noqa: BLE001 — cache is an optimization
            print(f"compile cache disabled ({e})", file=sys.stderr)


def load_vocab(args, cfg: ExperimentConfig):
    """Load GloVe once (it can be hundreds of MB); callers share the result."""
    from induction_network_on_fewrel_tpu.data import make_synthetic_glove
    from induction_network_on_fewrel_tpu.data.glove import load_glove

    if args.glove:
        return load_glove(args.glove, args.glove_mat)
    # Honor cfg geometry (vocab_size AND word_dim) so a checkpoint-merged
    # architecture (e.g. trained on 300-d GloVe) is not silently re-pinned
    # to the synthetic fallback's defaults at test time.
    return make_synthetic_glove(
        vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
    )


def load_data(args, cfg: ExperimentConfig, split: str):
    """Dataset for a split; synthetic schema-faithful fixtures when no file
    is given (no FewRel/GloVe on disk in this sandbox)."""
    from induction_network_on_fewrel_tpu.data import (
        load_fewrel_json,
        make_synthetic_fewrel,
    )

    path = {"train": args.train_file, "val": args.val_file, "test": args.test_file}[split]
    if path:
        return load_fewrel_json(path)
    seed = {"train": 0, "val": 1, "test": 2}[split]
    return make_synthetic_fewrel(
        num_relations=max(cfg.train_n, cfg.n) * 2,
        instances_per_relation=max(cfg.k + cfg.q + 5, 20),
        vocab_size=cfg.vocab_size - 2,
        seed=seed,
    )


def _wire_index_cache(cfg, model, cache_mesh, state, only_test,
                      train_ds, val_ds, train_sampler, val_sampler,
                      build_table, factories, feeder=None, local_batch=None,
                      seed_fn=lambda s: s):
    """Shared wiring for the index-transfer cache paths (feature cache and
    token cache): build per-split device-resident tables, swap the live
    samplers for index samplers with identical episode statistics, and bind
    the cached step factories to each split's table. Per step only
    [B,N,K]+[B,TQ] int32 indices cross the host->device boundary; the
    gather runs inside the jitted step.

    ``build_table(ds) -> (device table, per-relation sizes)`` — the table is
    opaque here (a [M,H] feature array or a token dict); every cached step
    takes it as one argument. ``factories``: "train"/"multi"/"eval"/
    "multi_eval" step factories, each
    ``(model, cfg, mesh, state_example) -> jitted fn`` ("multi"/"multi_eval"
    are only invoked when cfg.steps_per_call > 1).

    Returns (train_sampler, val_sampler, train_step, eval_step, fused_step,
    fused_eval, test_eval_factory) — fused_eval is bound to the VAL table
    (test evals must not reuse it; see _test_accuracy).

    Multi-host pods (parallel/hostfeed.py): ``local_batch`` sizes each
    process's index sampler to the episode rows it owns, ``seed_fn``
    strides the sampler streams per process, and ``feeder`` wraps each
    sampler so batches assemble into global arrays.
    """
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )

    if cache_mesh is not None and cfg.batch_size % cache_mesh.shape["dp"]:
        raise ValueError(
            f"--batch_size {cfg.batch_size} must be divisible by the "
            f"data-parallel mesh axis dp={cache_mesh.shape['dp']}"
        )
    _eval = factories["eval"](model, cfg, cache_mesh, state)
    train_step = eval_step = fused_step = fused_eval = None
    # Same backend policy as the live samplers: training uses the C++
    # index sampler under "auto" (measured 200-300x the Python index
    # sampler — host assembly was the cached paths' bottleneck); eval
    # pins to "python" unless a backend was chosen explicitly, so eval
    # streams are reproducible whether or not a toolchain is present.
    eval_backend = "python" if cfg.sampler == "auto" else cfg.sampler
    bsz = local_batch or cfg.batch_size
    wrap = feeder or (lambda s: s)
    if not only_test:
        table_tr, sizes_tr = build_table(train_ds)
        table_va, sizes_va = build_table(val_ds)
        for s in (train_sampler, val_sampler):
            if hasattr(s, "close"):
                s.close()
        train_sampler = wrap(make_index_sampler(
            sizes_tr, cfg.train_n, cfg.k, cfg.q, batch_size=bsz,
            na_rate=cfg.na_rate, seed=seed_fn(cfg.seed), backend=cfg.sampler,
        ))
        val_sampler = wrap(make_index_sampler(
            sizes_va, cfg.n, cfg.k, cfg.q, batch_size=bsz,
            na_rate=cfg.na_rate, seed=seed_fn(cfg.seed + 1),
            backend=eval_backend,
        ))
        _train = factories["train"](model, cfg, cache_mesh, state)
        train_step = lambda st, si, qi, l: _train(st, table_tr, si, qi, l)
        eval_step = lambda p, si, qi, l: _eval(p, table_va, si, qi, l)
        if cfg.steps_per_call > 1:
            _multi = factories["multi"](model, cfg, cache_mesh, state)
            fused_step = lambda st, si, qi, l: _multi(st, table_tr, si, qi, l)
            # Fused eval: one dispatch per steps_per_call val batches (the
            # per-batch cached eval costs a full tunnel round-trip each).
            # Pods keep per-batch eval: the trainer's eval loop stacks
            # host-side batches with np.stack, which global jax.Arrays
            # (the per-host assembler's output) do not support.
            if feeder is None:
                _multi_ev = factories["multi_eval"](model, cfg, cache_mesh, state)
                fused_eval = lambda p, si, qi, l: _multi_ev(p, table_va, si, qi, l)

    def test_eval(test_ds):
        """(sampler, eval_step, fused_eval) for a test split: its own
        device-resident table bound to the shared cached eval step, plus —
        when steps_per_call > 1 — a fused instance bound to the SAME test
        table (never the val-bound one above; binding per table is what
        keeps the val/test split drift hazard closed)."""
        table_te, sizes_te = build_table(test_ds)
        ts = wrap(make_index_sampler(
            sizes_te, cfg.n, cfg.k, cfg.q, batch_size=bsz,
            na_rate=cfg.na_rate, seed=seed_fn(cfg.seed + 2),
            backend=eval_backend,
        ))
        fused_te = None
        if cfg.steps_per_call > 1 and feeder is None:  # pods: per-batch eval
            _multi_te = factories["multi_eval"](model, cfg, cache_mesh, state)
            fused_te = lambda p, si, qi, l: _multi_te(p, table_te, si, qi, l)
        return ts, (lambda p, si, qi, l: _eval(p, table_te, si, qi, l)), fused_te

    return (train_sampler, val_sampler, train_step, eval_step, fused_step,
            fused_eval, test_eval)


def _cache_table_put(cache_mesh):
    """Device placement for cache tables: replicated NamedSharding on a
    mesh (a bare device_put would force a whole-table reshard copy every
    step), plain device_put on a single device."""
    import jax

    if cache_mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        return lambda x: jax.device_put(
            x, NamedSharding(cache_mesh, PartitionSpec())
        )
    return jax.device_put


def make_trainer(args, cfg: ExperimentConfig, only_test: bool = False):
    """Wire data, model, (possibly mesh-sharded) steps, ckpt, and logger."""
    import jax

    from induction_network_on_fewrel_tpu.data import GloveTokenizer
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
    from induction_network_on_fewrel_tpu.parallel import (
        make_mesh,
        make_sharded_eval_step,
        make_sharded_train_step,
        maybe_initialize_distributed,
    )
    from induction_network_on_fewrel_tpu.native import make_sampler
    from induction_network_on_fewrel_tpu.train import FewShotTrainer
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    maybe_initialize_distributed()

    train_ds = load_data(args, cfg, "train")
    val_ds = load_data(args, cfg, "val")
    if cfg.encoder == "bert":
        from induction_network_on_fewrel_tpu.data.bert_tokenizer import BertTokenizer

        vocab = None  # the BERT path owns its embedding; GloVe is not loaded
        tok = BertTokenizer(
            cfg.max_length, vocab_path=cfg.bert_vocab_path,
            vocab_size=cfg.bert_vocab_size,
        )
        # A vocab.txt resets the tokenizer's vocab size; the embedding table
        # must match or out-of-range ids gather garbage silently on TPU.
        cfg = cfg.replace(bert_vocab_size=tok.vocab_size)
    else:
        vocab = load_vocab(args, cfg)
        # A real GloVe file decides vocab size and word dim; the embedding
        # table must match or out-of-range ids gather garbage silently.
        if (cfg.vocab_size, cfg.word_dim) != (vocab.vocab_size, vocab.word_dim):
            cfg = cfg.replace(
                vocab_size=vocab.vocab_size, word_dim=vocab.word_dim
            )
        tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    # Cache runs (token or feature) replace these samplers with index
    # samplers right after drawing one init-shape batch — don't spin up the
    # native prefetching pipeline (threads + 16 queued batches) for that.
    caching = cfg.token_cache or cfg.feature_cache
    live_backend = "python" if caching else cfg.sampler
    live_prefetch = 0 if caching else cfg.prefetch
    train_sampler = make_sampler(
        train_ds, tok, cfg.train_n, cfg.k, cfg.q, cfg.batch_size,
        na_rate=cfg.na_rate, seed=cfg.seed, backend=live_backend,
        prefetch=live_prefetch, num_threads=cfg.sampler_threads,
    )
    # Eval streams must be reproducible across machines: under "auto" the
    # backend would depend on whether a g++ toolchain is present (native and
    # numpy samplers draw different RNG streams), so eval pins to "python"
    # unless the user explicitly chose a backend. Synchronous (prefetch=0):
    # eval is bursty and queued-ahead batches would be wasted work.
    eval_backend = "python" if cfg.sampler == "auto" else cfg.sampler
    val_sampler = make_sampler(
        val_ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size,
        na_rate=cfg.na_rate, seed=cfg.seed + 1, backend=eval_backend,
        prefetch=0, num_threads=1,
    )
    n_dev = len(jax.devices())
    use_mesh = (
        (cfg.dp == 0 and n_dev > 1) or cfg.dp > 1 or cfg.tp > 1
        or cfg.sp > 1 or cfg.pp > 1 or cfg.ep > 1
    )
    if cfg.embed_optimizer == "lazy":
        # The lazy exact-parity table update (train/lazy_embed.py) serves
        # the single-device paths and the token-cache path on a mesh (its
        # precomputed-remap body partitions under GSPMD like any other
        # cached step; tested equal to single-device in
        # tests/test_lazy_embed.py). The live-mesh/adversarial/
        # feature-cache step factories keep the dense reference path;
        # refuse with guidance instead of tracing into a state tree those
        # factories were not built for.
        reasons = {
            "a device mesh on the LIVE token path (combine --dp/--tp/... "
            "with --token_cache instead)": use_mesh and not cfg.token_cache,
            "--adv (the DANN step)": cfg.adv,
            "--feature_cache (head-only state, no word table)":
                cfg.feature_cache,
            "--encoder bert (owns its embedding; no GloVe table)":
                cfg.encoder == "bert",
        }
        for what, hit in reasons.items():
            if hit:
                raise ValueError(
                    f"--embed_optimizer lazy does not combine with {what}; "
                    f"use --embed_optimizer shared there"
                )
        if not cfg.token_cache:
            # Legal but measured SLOWER than dense: the live body pays a
            # per-step sort/dedup that the cached body's precomputed remap
            # avoids (interleaved A/B at the reference shape, BASELINE.md
            # round 4: live-lazy 1,076 vs live-shared 1,384 eps/s/chip;
            # cached-lazy 13,129 vs cached-shared 6,466). Warn, don't
            # refuse — the trajectory is still exact.
            import warnings

            warnings.warn(
                "--embed_optimizer lazy WITHOUT --token_cache is measured "
                "~20% slower than the dense default (the per-step dedup "
                "costs more than the sparse update saves on the live "
                "path; BASELINE.md round 4). Add --token_cache to get the "
                "fast precomputed-remap lazy body (~2x dense), or drop "
                "--embed_optimizer lazy",
                stacklevel=2,
            )
    train_step = eval_step = fused_step = fused_eval = state = mesh = None
    attn_impl = pipeline_impl = None
    if use_mesh:
        mesh = make_mesh(dp=(cfg.dp or None), tp=cfg.tp, sp=cfg.sp,
                         pp=cfg.pp, ep=cfg.ep)
        if cfg.sp > 1:
            if cfg.encoder != "transformer":
                raise ValueError(
                    "--sp (ring attention) requires --encoder transformer; "
                    f"the {cfg.encoder} encoder has no sequence-parallel path"
                )
            from induction_network_on_fewrel_tpu.parallel.ring import (
                make_ring_attention,
            )

            attn_impl = make_ring_attention(
                mesh, batch_axis="dp" if mesh.shape["dp"] > 1 else None
            )
        if cfg.ep > 1:
            if cfg.moe_experts <= 0 or cfg.encoder != "transformer":
                raise ValueError(
                    "--ep (expert parallelism) requires --encoder "
                    "transformer with --moe_experts > 0"
                )
            if cfg.moe_experts % cfg.ep != 0:
                raise ValueError(
                    f"--moe_experts ({cfg.moe_experts}) must be divisible "
                    f"by --ep ({cfg.ep})"
                )
        if cfg.pp > 1:
            if cfg.encoder != "transformer":
                raise ValueError(
                    "--pp (pipeline parallelism) requires --encoder "
                    "transformer (stages are transformer layers)"
                )
            if cfg.tfm_layers % cfg.pp != 0:
                raise ValueError(
                    f"--tfm_layers ({cfg.tfm_layers}) must be divisible by "
                    f"--pp ({cfg.pp}) pipeline stages"
                )
            # Every encoder call's per-dp-shard row count must split evenly
            # into GPipe microbatches — caught here with flag guidance
            # instead of a trace-time error deep in gpipe_local (advisor
            # finding, round 1).
            dp_sz = mesh.shape["dp"]
            mb = cfg.pp_microbatches
            # Train shapes are validated even under --only_test: init_state
            # below always traces a train-shaped batch to build the model,
            # so a non-divisible train config would crash mid-trace anyway.
            row_counts = {
                "train support": cfg.batch_size * cfg.train_n * cfg.k,
                "train query": cfg.batch_size
                * (cfg.train_n * cfg.q + cfg.na_rate * cfg.q),
                "eval support": cfg.batch_size * cfg.n * cfg.k,
                "eval query": cfg.batch_size * cfg.total_q,
            }
            for what, rows in row_counts.items():
                if rows % dp_sz != 0 or (rows // dp_sz) % mb != 0:
                    raise ValueError(
                        f"{what} rows ({rows}) must divide evenly across "
                        f"dp={dp_sz} shards and then into "
                        f"--pp_microbatches ({mb}); adjust --batch_size, "
                        f"--pp_microbatches, or the episode shape flags"
                    )
            from induction_network_on_fewrel_tpu.parallel.pipeline import (
                make_gpipe,
            )

            pipeline_impl = make_gpipe(
                mesh, microbatches=cfg.pp_microbatches,
                batch_axis="dp" if mesh.shape["dp"] > 1 else None,
            )
    cache_feeder = cache_local_batch = None
    cache_seed_fn = lambda s: s  # noqa: E731 — identity off-pod
    if jax.process_count() > 1:
        # Multi-host pod: every process runs this same function. Feed each
        # host ONLY its own episode rows (parallel/hostfeed.py) — disjoint
        # per-process sampler streams assembled into global arrays with
        # jax.make_array_from_process_local_data. Without this every host
        # would sample the identical global batch (replicated, not
        # sharded, inputs).
        if not use_mesh:
            raise ValueError(
                "multi-host run without a device mesh; pass --dp 0 (all "
                "devices) or explicit mesh axes"
            )
        if cfg.adv:
            raise ValueError(
                "per-host data feeding does not cover --adv yet (the DANN "
                "domain samplers stream separate unlabeled instances); "
                "drop --adv on pods"
            )
        from induction_network_on_fewrel_tpu.parallel.hostfeed import (
            GlobalBatchAssembler,
            PerHostSampler,
            local_episode_range,
            process_seed,
        )

        _, local_b = local_episode_range(mesh, cfg.batch_size)
        if caching:
            # The cache paths replace the samplers in _wire_index_cache;
            # hand them the per-host pieces instead of rebuilding here.
            cache_local_batch = local_b
            cache_seed_fn = process_seed
            cache_feeder = lambda s: PerHostSampler(
                s, GlobalBatchAssembler(mesh, cfg.batch_size, index_mode=True)
            )
        else:
            for s in (train_sampler, val_sampler):
                if hasattr(s, "close"):
                    s.close()
            train_sampler = PerHostSampler(
                make_sampler(
                    train_ds, tok, cfg.train_n, cfg.k, cfg.q, local_b,
                    na_rate=cfg.na_rate, seed=process_seed(cfg.seed),
                    backend=live_backend, prefetch=live_prefetch,
                    num_threads=cfg.sampler_threads,
                ),
                GlobalBatchAssembler(mesh, cfg.batch_size),
            )
            val_sampler = PerHostSampler(
                make_sampler(
                    val_ds, tok, cfg.n, cfg.k, cfg.q, local_b,
                    na_rate=cfg.na_rate, seed=process_seed(cfg.seed + 1),
                    backend=eval_backend, prefetch=0, num_threads=1,
                ),
                GlobalBatchAssembler(mesh, cfg.batch_size),
            )
    demb_impl = None
    if use_mesh:
        from induction_network_on_fewrel_tpu.parallel.sharding import (
            demb_impl_for,
        )

        demb_impl = demb_impl_for(cfg, mesh)
    model = build_model(
        cfg, glove_init=vocab.vectors if vocab is not None else None,
        attn_impl=attn_impl, pipeline_impl=pipeline_impl,
        demb_impl=demb_impl,
    )
    cache_test_eval = None  # set by either index-cache path below
    # Real corpus distinct-row count (token-cache lazy build_table fills
    # it from the train split's uids) — the kind="comms" demb term's
    # honest bound; stays empty on paths that don't know it.
    corpus_rows: dict = {}
    if cfg.feature_cache:
        # Frozen-encoder feature cache (train/feature_cache.py): encode both
        # splits once with the frozen backbone, then swap the token samplers
        # for feature samplers — training runs the episode head only.
        if cfg.encoder != "bert" or not cfg.bert_frozen:
            raise ValueError(
                "--feature_cache requires --encoder bert with the frozen "
                "backbone (a trainable encoder would be silently frozen)"
            )
        if cfg.model == "pair":
            raise ValueError(
                "--feature_cache cannot serve --model pair: it scores "
                "token-level sentence pairs through the backbone"
            )
        if cfg.adv:
            raise ValueError(
                "--feature_cache excludes --adv: the domain game trains "
                "the encoder, which the cache freezes out of the step"
            )
        from induction_network_on_fewrel_tpu.train.feature_cache import (
            encode_dataset,
            make_cached_eval_step,
            make_cached_multi_eval_step,
            make_cached_multi_train_step,
            make_cached_train_step,
            make_encode_fn,
        )

        import numpy as np

        from induction_network_on_fewrel_tpu.models.build import (
            encoder_output_dim,
        )

        sup_t, qry_t, _ = batch_to_model_inputs(train_sampler.sample_batch())
        full_params = model.init(jax.random.key(cfg.seed), sup_t, qry_t)
        # Pretrained weights must be in the backbone BEFORE any split is
        # encoded — the cached train state is head-only, so this is the only
        # point where they can enter (train_main skips its own injection).
        # cfg.bert_weights (an ARCHITECTURE_FIELD) rides in the checkpoint's
        # config.json, so test-time runs rebuild the same backbone.
        if cfg.bert_weights:
            from induction_network_on_fewrel_tpu.models.bert import (
                load_hf_weights,
            )

            enc = load_hf_weights(
                {"params": full_params["params"]["encoder"]}, cfg.bert_weights
            )
            full_params["params"]["encoder"] = enc["params"]
            print(f"feature cache: encoding with BERT weights from "
                  f"{cfg.bert_weights}", file=sys.stderr)
        encode_fn = make_encode_fn(model)  # one compile for all splits
        cache_mesh = mesh if use_mesh else None  # built above with attn_impl
        _put = _cache_table_put(cache_mesh)
        # Head-only state (flax lazy param creation: init on feature-shaped
        # inputs builds no backbone params, so the optimizer never sees the
        # frozen 110M either). Zero arrays suffice — init reads shapes, not
        # values — which keeps the only_test path free of train/val encodes.
        H = encoder_output_dim(cfg)
        state = init_state(
            model, cfg,
            np.zeros((cfg.batch_size, cfg.train_n, cfg.k, H), np.float32),
            np.zeros((cfg.batch_size, cfg.total_q, H), np.float32),
        )
        if cache_mesh is not None:
            from induction_network_on_fewrel_tpu.parallel.sharding import (
                shard_state,
            )

            state = shard_state(state, cache_mesh, zero_opt=cfg.zero_opt)

        def build_table(ds):
            """Encode a split with the cache's backbone -> one flat device
            feature table + per-relation row counts."""
            blocks = encode_dataset(model, full_params, ds, tok,
                                    encode_fn=encode_fn)
            table = _put(np.concatenate(blocks).astype(np.float32))
            return table, [b.shape[0] for b in blocks]

        (train_sampler, val_sampler, train_step, eval_step, fused_step,
         fused_eval, cache_test_eval) = _wire_index_cache(
            cfg, model, cache_mesh, state, only_test, train_ds, val_ds,
            train_sampler, val_sampler, build_table,
            {"train": make_cached_train_step,
             "multi": make_cached_multi_train_step,
             "eval": make_cached_eval_step,
             "multi_eval": make_cached_multi_eval_step},
            feeder=cache_feeder, local_batch=cache_local_batch,
            seed_fn=cache_seed_fn,
        )
    if cfg.token_cache:
        # Device-resident token cache (train/token_cache.py): upload the
        # tokenized dataset once, stream only episode indices per step. Same
        # model, same episode statistics, same state tree — only the
        # host->device transport changes (~3-4x e2e on the tunneled v5e).
        if cfg.model == "pair" or cfg.adv:
            raise ValueError(
                "--token_cache does not serve --model pair or --adv "
                "(pair consumes token pairs; the DANN domain samplers "
                "stream separate unlabeled instances)"
            )
        from induction_network_on_fewrel_tpu.train.token_cache import (
            make_token_cached_eval_step,
            make_token_cached_multi_eval_step,
            make_token_cached_multi_train_step,
            make_token_cached_train_step,
            tokenize_dataset,
        )

        cache_mesh = mesh if use_mesh else None
        if cache_mesh is not None and cfg.batch_size % cache_mesh.shape["dp"]:
            # Checked here too (not only in _wire_index_cache): the full
            # model init below is the expensive part of this path.
            raise ValueError(
                f"--batch_size {cfg.batch_size} must be divisible by the "
                f"data-parallel mesh axis dp={cache_mesh.shape['dp']}"
            )
        _tput = _cache_table_put(cache_mesh)
        sup_t, qry_t, _ = batch_to_model_inputs(train_sampler.sample_batch())
        state = init_state(model, cfg, sup_t, qry_t)
        if cache_mesh is not None:
            from induction_network_on_fewrel_tpu.parallel.sharding import (
                shard_state,
            )

            state = shard_state(state, cache_mesh, zero_opt=cfg.zero_opt)

        def build_table(ds):
            """Tokenize a split once -> device-resident token dict + sizes.

            Lazy-embed runs also carry the precomputed corpus remap
            (winv per token row + the static uids vector) so the cached
            lazy body never dedups at step time."""
            tab, sizes = tokenize_dataset(ds, tok)
            if cfg.embed_optimizer == "lazy":
                from induction_network_on_fewrel_tpu.train.lazy_embed import (
                    augment_token_table,
                )

                tab, uids = augment_token_table(tab)
                # First call is the TRAIN split: its corpus row count is
                # the real demb [U, D] bound the kind="comms" telemetry
                # should use instead of the synthetic-fixture default
                # (utils/roofline.touched_rows).
                corpus_rows.setdefault("train", int(uids.shape[0]))
                tab = {**tab, "uids": uids}
            return {k: _tput(v) for k, v in tab.items()}, sizes

        (train_sampler, val_sampler, train_step, eval_step, fused_step,
         fused_eval, cache_test_eval) = _wire_index_cache(
            cfg, model, cache_mesh, state, only_test, train_ds, val_ds,
            train_sampler, val_sampler, build_table,
            {"train": make_token_cached_train_step,
             "multi": make_token_cached_multi_train_step,
             "eval": make_token_cached_eval_step,
             "multi_eval": make_token_cached_multi_eval_step},
            feeder=cache_feeder, local_batch=cache_local_batch,
            seed_fn=cache_seed_fn,
        )

    if use_mesh and not cfg.feature_cache and not cfg.token_cache:
        dp = mesh.shape["dp"]
        if cfg.batch_size % dp != 0:
            raise ValueError(
                f"--batch_size {cfg.batch_size} must be divisible by the "
                f"data-parallel mesh axis dp={dp} (episodes are sharded "
                f"over dp); try --batch_size {((cfg.batch_size // dp) + 1) * dp} "
                f"or --dp {cfg.batch_size}"
            )
        sup, qry, _ = batch_to_model_inputs(train_sampler.sample_batch())
        # The sharded steps are traced against this exact state's pytree
        # metadata, so the same object is injected into the trainer.
        state = init_state(model, cfg, sup, qry)
        train_step = make_sharded_train_step(model, cfg, mesh, state)
        eval_step = make_sharded_eval_step(model, cfg, mesh, state)
        if cfg.steps_per_call > 1 and not cfg.adv:
            from induction_network_on_fewrel_tpu.parallel.sharding import (
                make_sharded_multi_train_step,
            )

            fused_step = make_sharded_multi_train_step(model, cfg, mesh, state)

    # --- datapipe/ (ISSUE 4): mixture schedule + producer pipeline -------
    if cfg.mixture and not only_test:
        # Mixtures interleave LIVE token-path samplers over same-geometry
        # corpora; the cached paths bind index samplers to one device
        # table each, and per-host pods would need per-source local
        # sizing — refuse both with guidance instead of mis-sampling.
        if caching:
            raise ValueError(
                "--mixture does not combine with --token_cache/"
                "--feature_cache (cached index samplers are bound to one "
                "device table per split); drop the cache flags"
            )
        if jax.process_count() > 1:
            raise ValueError(
                "--mixture is single-process for now (per-host mixture "
                "feeding needs per-source local sizing); drop --mixture "
                "on pods"
            )
        from induction_network_on_fewrel_tpu.data import (
            load_fewrel_json,
            make_synthetic_fewrel,
        )
        from induction_network_on_fewrel_tpu.datapipe import (
            MixtureSampler,
            MixtureSchedule,
        )

        schedule = MixtureSchedule.parse(cfg.mixture)
        children = []
        for i, name in enumerate(schedule.names):
            if name == "train":
                # Rebuilt prefetch-free like every other child (same seed
                # keeps its stream identity): the already-built sampler
                # carries the native C++ prefetch ring, and children must
                # not stack prefetchers under the datapipe producer.
                if hasattr(train_sampler, "close"):
                    train_sampler.close()
                children.append((name, make_sampler(
                    train_ds, tok, cfg.train_n, cfg.k, cfg.q,
                    cfg.batch_size, na_rate=cfg.na_rate, seed=cfg.seed,
                    backend=live_backend, prefetch=0, num_threads=1,
                )))
                continue
            if name.startswith("synthetic"):
                _, _, sseed = name.partition(":")
                src_ds = make_synthetic_fewrel(
                    num_relations=max(cfg.train_n, cfg.n) * 2,
                    instances_per_relation=max(cfg.k + cfg.q + 5, 20),
                    vocab_size=cfg.vocab_size - 2,
                    seed=int(sseed or 83),
                )
            else:
                src_ds = load_fewrel_json(name)
            # Child streams are seeded per SOURCE POSITION (stable across
            # runs with the same spec — required for cursor resume). No
            # native prefetch inside children: the datapipe producer is
            # the pipeline; stacked prefetchers would hide the cursor.
            children.append((name, make_sampler(
                src_ds, tok, cfg.train_n, cfg.k, cfg.q, cfg.batch_size,
                na_rate=cfg.na_rate, seed=cfg.seed + 1000 + i,
                backend=live_backend, prefetch=0, num_threads=1,
            )))
        train_sampler = MixtureSampler(children, schedule, seed=cfg.seed)

    if not only_test:
        from induction_network_on_fewrel_tpu.datapipe import (
            FeedFaults,
            PipelineFeed,
        )

        # Production unit: whole fused [S,B,...] stacks when the trainer
        # will consume them that way (index samplers under
        # steps_per_call fusion), else single batches.
        unit = (
            cfg.steps_per_call
            if (
                cfg.steps_per_call > 1
                and hasattr(train_sampler, "sample_fused")
                and getattr(train_sampler, "return_indices", True)
            ) else 1
        )
        train_sampler = PipelineFeed(
            train_sampler,
            prefetch_depth=cfg.prefetch_depth,
            unit=unit,
            # Double-buffered device puts: producer-side H2D on
            # single-device fused paths (mesh paths assemble global
            # arrays in the sampler already; per-batch token dicts are
            # stacked host-side by the trainer and must stay numpy).
            device_put=(mesh is None and unit > 1),
            faults=FeedFaults.parse(cfg.feed_fault),
            stream_tag=f"mixture={cfg.mixture};seed={cfg.seed}",
        )

    adv_pieces = None
    if cfg.adv and not only_test:
        from induction_network_on_fewrel_tpu.data import (
            load_fewrel_json,
            make_synthetic_fewrel,
        )
        from induction_network_on_fewrel_tpu.models.adversarial import (
            DomainDiscriminator,
        )
        from induction_network_on_fewrel_tpu.models.build import encoder_output_dim
        from induction_network_on_fewrel_tpu.sampling import InstanceSampler
        from induction_network_on_fewrel_tpu.train.framework import AdvPieces
        from induction_network_on_fewrel_tpu.train.steps import (
            init_disc_state,
            make_adv_train_step,
        )

        if args.adv != "synthetic":
            tgt_ds = load_fewrel_json(args.adv)
        else:
            # Synthetic "other domain": disjoint token statistics (seed) so
            # the discriminator has a real signal to separate.
            tgt_ds = make_synthetic_fewrel(
                num_relations=max(cfg.train_n, cfg.n) * 2,
                instances_per_relation=max(cfg.k + cfg.q + 5, 20),
                vocab_size=cfg.vocab_size - 2,
                seed=97,
            )
        disc = DomainDiscriminator(hidden=cfg.adv_dis_hidden)
        disc_state = init_disc_state(disc, cfg, encoder_output_dim(cfg))
        if use_mesh:
            from induction_network_on_fewrel_tpu.parallel.sharding import (
                make_sharded_adv_train_step,
                shard_state,
            )

            dp = mesh.shape["dp"]
            if cfg.adv_batch % dp != 0:
                raise ValueError(
                    f"--adv_batch {cfg.adv_batch} must be divisible by the "
                    f"data-parallel mesh axis dp={dp}"
                )
            disc_state = shard_state(disc_state, mesh)
            adv_step = make_sharded_adv_train_step(
                model, disc, cfg, mesh, state, disc_state
            )
        else:
            adv_step = make_adv_train_step(model, disc, cfg)
        adv_multi = None
        if cfg.steps_per_call > 1 and not use_mesh:
            # Fused DANN dispatch (single-device; the mesh DANN step keeps
            # per-step dispatch — its fused twin would need sharded stacked
            # specs like make_sharded_multi_train_step's).
            from induction_network_on_fewrel_tpu.train.steps import (
                make_adv_multi_train_step,
            )

            adv_multi = make_adv_multi_train_step(model, disc, cfg)
        adv_pieces = AdvPieces(
            step=adv_step,
            disc_state=disc_state,
            src_sampler=InstanceSampler(train_ds, tok, cfg.adv_batch, seed=cfg.seed + 31),
            tgt_sampler=InstanceSampler(tgt_ds, tok, cfg.adv_batch, seed=cfg.seed + 32),
            multi_step=adv_multi,
        )

    run_dir = args.run_dir or args.save_ckpt
    watchdog = recorder = None
    if cfg.watchdog:
        # Telemetry spine (obs/): the recorder retains the last-N window
        # and dumps on crash/SIGTERM/watchdog trip; the watchdog consumes
        # every metrics record via a logger hook (wired by the trainer).
        from induction_network_on_fewrel_tpu.obs import (
            FlightRecorder,
            HealthWatchdog,
        )

        recorder = FlightRecorder(out_dir=run_dir)
        recorder.install_sigterm_handler()
        watchdog = HealthWatchdog(recorder=recorder)
    logger = MetricsLogger(
        run_dir, tensorboard_dir=getattr(args, "tensorboard", None)
    )
    if cfg.chaos:
        # Unified chaos injection (ISSUE 12, obs/chaos.py): one plan
        # drives every layer's named fault points; fired faults emit
        # kind="fault" records through this run's logger.
        from induction_network_on_fewrel_tpu.obs.chaos import ChaosRegistry

        reg = ChaosRegistry.parse(cfg.chaos, logger=logger)
        if reg is not None:
            reg.install()
            print(f"chaos plan armed: {cfg.chaos}", file=sys.stderr)
    perf_obs = compile_watcher = None
    if cfg.perf:
        # Performance-attribution observability (ISSUE 11): the perf
        # observer decomposes each metric window (kind="perf"); the
        # compile watcher stamps every XLA compile (kind="compile") and
        # holds the loop to the steady-state zero-recompile invariant.
        # Perf criticals ride the watchdog's emitter when one exists
        # (same health stream, same flight-recorder dump); diagnostics
        # auto-capture into the run dir (profile off: the RUNBOOK §14
        # profiler/thread caveat applies here too).
        from induction_network_on_fewrel_tpu.obs import (
            CompileWatcher,
            DiagnosticsCapture,
            PerfObserver,
            bind_health,
        )

        capture = None
        if run_dir is not None:
            # recorder=None on purpose: with --watchdog on, the perf
            # critical already dumps the flight recorder through the
            # watchdog emitter below — the capture adds the span snapshot
            # (its guaranteed artifact) instead of dumping twice.
            capture = DiagnosticsCapture(
                out_dir=run_dir, recorder=None, profile=False
            )
        floor_ms = None
        if cfg.encoder == "bilstm":
            # The shared roofline projection at nominal v5e — the same
            # formulas the ledger and bench stamp (utils/roofline.py),
            # recorded next to every measured window.
            from induction_network_on_fewrel_tpu.utils.roofline import (
                projected_floor_ms,
            )

            floor_ms = projected_floor_ms(
                cfg, corpus_rows=corpus_rows.get("train")
            )
        compile_watcher = CompileWatcher(logger=logger).install()
        if watchdog is not None:
            bind_health(compile_watcher, watchdog._emit)
        perf_obs = PerfObserver(
            logger=logger,
            compile_watcher=compile_watcher,
            capture=capture,
            on_event=watchdog._emit if watchdog is not None else None,
            floor_ms=floor_ms,
        )
    trainer = FewShotTrainer(
        model, cfg, train_sampler, val_sampler,
        ckpt_dir=None if only_test else args.save_ckpt,
        logger=logger,
        train_step=train_step, eval_step=eval_step, fused_step=fused_step,
        fused_eval=fused_eval,
        initial_state=state,
        mesh=mesh, adv=adv_pieces,
        profile_dir=getattr(args, "profile", None),
        profile_steps=getattr(args, "profile_steps", 10),
        watchdog=watchdog, recorder=recorder,
        comms_u_rows=corpus_rows.get("train"),
        comms_compact=demb_impl is not None,
        perf=perf_obs, compile_watcher=compile_watcher,
    )
    if getattr(args, "debug_nans", False):
        from induction_network_on_fewrel_tpu.utils.debug import checkify_step

        trainer.train_step = checkify_step(trainer.train_step)
        if trainer._fused_step is not None:
            trainer._fused_step = checkify_step(trainer._fused_step)
        if trainer.adv is not None:
            trainer.adv.step = checkify_step(trainer.adv.step)
            if trainer.adv.multi_step is not None:
                trainer.adv.multi_step = checkify_step(trainer.adv.multi_step)
    trainer.vocab, trainer.tokenizer = vocab, tok
    # Cached-mode test evaluation factory (None on the live-token path): the
    # test split needs its own device table — features (encoded with the
    # cache's backbone) or raw tokens.
    trainer.cached_test_eval = cache_test_eval
    return trainer


def make_test_sampler(args, cfg: ExperimentConfig, tok):
    from induction_network_on_fewrel_tpu.native import make_sampler

    test_ds = load_data(args, cfg, "test")
    # Same reproducibility rule as the val sampler: "auto" pins to python.
    return make_sampler(
        test_ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size,
        na_rate=cfg.na_rate, seed=cfg.seed + 2,
        backend="python" if cfg.sampler == "auto" else cfg.sampler,
        prefetch=0, num_threads=1,
    )


def _test_accuracy(args, cfg: ExperimentConfig, trainer, state) -> dict:
    """Evaluate on the test split, via the feature-cache path when active
    (the cached eval step reads int32 indices into a test-split table; the
    token sampler's dicts would not even trace). Returns the full metric
    dict — accuracy plus acc_ci95 (±1.96·σ/√n, VERDICT weak #8) and the
    NOTA confusion metrics when na_rate > 0."""
    if trainer.cached_test_eval is not None:
        test_ds = load_data(args, cfg, "test")
        sampler, eval_step, fused_eval = trainer.cached_test_eval(test_ds)
        trainer.eval_step = eval_step
        # CRITICAL: any existing fused eval is bound to the VALIDATION
        # split's table (cli._wire_index_cache closes over table_va), so
        # reusing it here would silently score test indices against val
        # rows. Both steps installed here are bound to the TEST table.
        trainer._fused_eval = fused_eval
        try:
            m = trainer.evaluate(
                state.params, cfg.test_iter, sampler=sampler,
                return_metrics=True,
            )
            trainer.logger.log(0, "test", **m)
            return m
        finally:
            if hasattr(sampler, "close"):
                sampler.close()
    sampler = make_test_sampler(args, cfg, trainer.tokenizer)
    try:
        m = trainer.evaluate(
            state.params, cfg.test_iter, sampler=sampler, return_metrics=True
        )
        # kind="test" record: test accuracy + CI land in metrics.jsonl
        # alongside the run's train/val stream (machine-readable eval).
        trainer.logger.log(0, "test", **m)
        return m
    finally:
        if hasattr(sampler, "close"):
            sampler.close()


def _print_test_result(metrics: dict, kind: str = "test") -> None:
    """Human line (stderr, with the ±CI error bar) + machine JSON line
    (stdout; existing consumers key on test_accuracy, new ones get
    acc_ci95 alongside). json.dumps + json_sanitize, not f-strings: a
    pathological NaN accuracy must not produce an unparseable line."""
    import json

    from induction_network_on_fewrel_tpu.utils.metrics import json_sanitize

    acc, ci = metrics["accuracy"], metrics.get("acc_ci95", 0.0)
    print(f"{kind} accuracy: {acc:.4f} ± {ci:.4f} (95% CI)", file=sys.stderr)
    out = {"test_accuracy": acc, "acc_ci95": ci}
    out.update(
        {k: v for k, v in metrics.items() if k not in ("accuracy", "acc_ci95")}
    )
    print(json.dumps(
        {k: json_sanitize(round(v, 4) if isinstance(v, float) else v)
         for k, v in out.items()}
    ))


def _merge_ckpt_architecture(cfg: ExperimentConfig, src: str) -> ExperimentConfig:
    """Take architecture fields from a checkpoint dir's config.json so the
    restored weights always match the built model/tokenizer."""
    from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager

    try:
        saved = CheckpointManager.load_config(src)
    except FileNotFoundError:
        return cfg
    merged = cfg.merge_architecture_from(saved)
    if merged != cfg:
        print(f"using architecture from {src}/config.json", file=sys.stderr)
    return merged


def train_main(argv=None) -> int:
    parser = build_arg_parser(train=True)
    args = parser.parse_args(argv)
    if args.bert_weights and args.encoder != "bert":
        parser.error("--bert_weights requires --encoder bert")
    cfg = config_from_args(args)
    if args.load_ckpt:
        cfg = _merge_ckpt_architecture(cfg, args.load_ckpt)
        # Re-check on the MERGED config: the checkpoint's config.json can
        # flip loss back to mse and re-create the refused combination.
        if not args.only_test:
            _check_degenerate(cfg.loss, cfg.na_rate, args.force)
    select_device(cfg, args.compile_cache)
    trainer = make_trainer(args, cfg)
    try:
        return _run_train(args, trainer)
    finally:
        trainer.close()  # saver thread + native sampler handles


def _run_train(args, trainer) -> int:
    cfg = trainer.cfg  # make_trainer may pin tokenizer-derived fields

    state = trainer.init_state()
    if args.bert_weights and not cfg.feature_cache:
        # Cached mode has no backbone in the train state; make_trainer
        # already folded the weights into the feature tables instead.
        from induction_network_on_fewrel_tpu.models.bert import load_hf_weights

        enc = load_hf_weights({"params": state.params["params"]["encoder"]}, args.bert_weights)
        state.params["params"]["encoder"] = enc["params"]
        print(f"loaded BERT weights from {args.bert_weights}", file=sys.stderr)
    start_step = 0
    if args.resume or args.load_ckpt:
        from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager

        src = args.load_ckpt or args.save_ckpt
        mngr = None
        try:
            # logger threaded: an integrity quarantine during the resume
            # restore (corrupt slot -> ring-walk fallback) must land in
            # the telemetry stream, not happen silently.
            mngr = CheckpointManager(src, cfg, logger=trainer.logger)
            state, start_step = (
                mngr.restore_latest(state) if args.resume else mngr.restore_best(state)
            )
            state = trainer.reshard_state(state)
            print(f"restored checkpoint step={start_step} from {src}", file=sys.stderr)
            if args.resume:
                # Input-pipeline cursor (datapipe/): reposition the feed so
                # the resumed run replays the exact episode stream the
                # uninterrupted one would have consumed. A --load_ckpt
                # fine-tune deliberately restarts the stream at 0 (its
                # step numbering restarts too).
                if trainer.restore_feed_cursor(mngr, start_step):
                    print(
                        f"restored input-pipeline cursor at step "
                        f"{start_step}", file=sys.stderr,
                    )
                else:
                    print(
                        "no input-pipeline cursor in the checkpoint "
                        "(pre-datapipe dir?); the episode stream restarts "
                        "from its seed", file=sys.stderr,
                    )
        except FileNotFoundError:
            if args.load_ckpt:
                raise
            print(f"no checkpoint in {src}; starting fresh", file=sys.stderr)
        finally:
            if mngr is not None:
                mngr.close()  # restore-only manager: stop its saver thread

    if args.only_test:
        _print_test_result(_test_accuracy(args, cfg, trainer, state))
        return 0

    # Global step numbering continues from the restored step on --resume so
    # checkpoint retention / the recovery ring keep advancing (a fresh
    # --load_ckpt fine-tune restarts numbering at 0 on purpose).
    state = trainer.train(
        state, num_iters=cfg.train_iter,
        start_step=start_step if args.resume else 0,
    )
    if trainer.val_sampler is not None:
        # Reference behavior: the final number comes from the BEST
        # checkpoint, not the last state (the toolkit family's train()
        # reloads best-val weights before its final eval).
        if trainer.ckpt is not None:
            try:
                import jax as _jax

                state, best_step = trainer.ckpt.restore_best(
                    _jax.device_get(state)
                )
                state = trainer.reshard_state(state)
                print(f"final eval from best checkpoint (step {best_step})",
                      file=sys.stderr)
            except FileNotFoundError:
                pass  # no best saved (e.g. val never ran): use last state
        import json

        from induction_network_on_fewrel_tpu.utils.metrics import json_sanitize

        m = trainer.evaluate(state.params, cfg.val_iter, return_metrics=True)
        acc, ci = m["accuracy"], m.get("acc_ci95", 0.0)
        print(f"final val accuracy: {acc:.4f} ± {ci:.4f} (95% CI)",
              file=sys.stderr)
        # Same NaN-safe serialization contract as _print_test_result.
        print(json.dumps({
            "final_val_accuracy": json_sanitize(round(acc, 4)),
            "acc_ci95": json_sanitize(round(ci, 4)),
        }))
    return 0


def test_main(argv=None) -> int:
    args = build_arg_parser(train=False).parse_args(argv)
    if not args.load_ckpt and not os.path.isdir(args.save_ckpt):
        print("test.py needs --load_ckpt (or an existing --save_ckpt dir)", file=sys.stderr)
        return 2
    cfg = config_from_args(args)
    cfg = _merge_ckpt_architecture(cfg, args.load_ckpt or args.save_ckpt)
    select_device(cfg, args.compile_cache)
    trainer = make_trainer(args, cfg, only_test=True)
    try:
        cfg = trainer.cfg

        from induction_network_on_fewrel_tpu.train.checkpoint import (
            CheckpointManager,
        )

        src = args.load_ckpt or args.save_ckpt
        state = trainer.init_state()
        mngr = CheckpointManager(src, cfg)
        try:
            try:
                state, step = mngr.restore_best(state)
                which = "best"
            except FileNotFoundError:
                # A run trained with --val_step 0 never writes a best-val
                # checkpoint, but train() always leaves a final recovery-
                # ring save — evaluate that instead of refusing.
                state, step = mngr.restore_latest(state)
                which = "latest (no best-val checkpoint in this dir)"
        finally:
            mngr.close()
        state = trainer.reshard_state(state)
        print(f"loaded {which} checkpoint step={step} from {src}", file=sys.stderr)

        _print_test_result(_test_accuracy(args, cfg, trainer, state))
        return 0
    finally:
        trainer.close()
