"""Frozen experiment configuration.

The reference drives everything through argparse flags on ``train.py`` /
``test.py`` (SURVEY.md §5.6). Here the same knobs live in one frozen
dataclass: hashable (so it can be a static arg under ``jax.jit``),
serializable (saved into the checkpoint directory), and constructible from
the reference-compatible CLI in ``cli.py``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    # --- episode geometry (reference flags --trainN/--N/--K/--Q) ---
    train_n: int = 5          # N-way during training (can exceed eval N)
    n: int = 5                # N-way at eval
    k: int = 5                # K-shot
    q: int = 5                # queries per class per episode
    na_rate: int = 0          # NOTA: na_rate*Q extra none-of-the-above queries
    # NOTA head (models/base.py append_nota): "scalar" = one global learned
    # threshold logit; "stats" = per-query learned affine over the class-
    # score distribution (max/mean/std). Swept in BASELINE.md round 3.
    nota_head: str = "scalar"
    batch_size: int = 4       # episodes per optimizer step (vmapped in-device)

    # --- tokenization / embedding ---
    max_length: int = 40      # tokens per sentence (fixed; static shapes)
    word_dim: int = 50        # GloVe 6B.50d
    pos_dim: int = 5          # each of the two position embeddings
    vocab_size: int = 400002  # GloVe 400k + [UNK] + [BLANK]; synthetic is small

    # --- few-shot model (reference flag --model) ---
    model: str = "induction"  # induction | proto | proto_hatt | siamese | gnn | snail | metanet | pair
    proto_metric: str = "euclid"  # euclid | dot (proto only)
    gnn_dim: int = 64         # features added per GNN block
    gnn_blocks: int = 2
    gnn_adj_hidden: int = 64  # adjacency MLP hidden width
    snail_tc_filters: int = 128

    # --- encoder ---
    encoder: str = "bilstm"   # cnn | bilstm | bert
    hidden_size: int = 230    # CNN filters / 2*lstm_hidden for bilstm output
    lstm_hidden: int = 128    # per direction
    att_dim: int = 64         # structured self-attention projection dim
    lstm_backend: str = "auto"  # auto | scan | pallas | interpret (ops/lstm.py)
    # Self-attention impl (ops/attn.py): "auto" resolves to the two-pass
    # XLA form on EVERY backend (the fused kernel measured 0.97-0.98x of
    # it on this chip — BASELINE.md round-5 rejection; re-A/B on other
    # silicon before flipping). Not an architecture field — params and
    # math are backend-independent, like lstm_backend.
    attn_backend: str = "auto"  # auto | xla | pallas | interpret
    # Recompute-in-backward attention (ops/attn.py "xla_remat"): with the
    # resolved attention path "xla" on a TPU backend, run the two-pass XLA
    # forward through a custom VJP that saves only the [M] softmax stats
    # (not the [L, M, A] tanh projection / [L, M] attention weights) and
    # recomputes both inside the one-pass Pallas backward kernel from the
    # already-saved H. Byte arithmetic (utils/roofline.py, ROOFLINE_r06):
    # attn fwd 149 -> 133 MB/step, attn bwd 213 -> 134 MB/step at the
    # flagship shape. Parity is pinned in tests/test_attn.py (f32 ~1e-6;
    # bf16 within the documented kernel band). Default ON; not an
    # architecture field — params and checkpoints are backend-independent,
    # like attn_backend/lstm_backend.
    remat_attn: bool = True
    # Windowed-cs remat in the fused Pallas BiLSTM backward (ops/lstm.py,
    # round 8): the forward saves one (h, c) checkpoint pair per W
    # natural-time steps instead of the full [L, M, u] cs/hs residual
    # streams; the backward recomputes each window's states in VMEM from
    # the seed. 0 = the round-6 full-residual design (the A/B twin).
    # Byte arithmetic at the flagship shape (utils/roofline.py, W=8):
    # kernel fwd 146 -> 97, kernel bwd 227 -> 113 MB/step. Engages on the
    # kernel (pallas/interpret) lstm paths only — the scan backend keeps
    # no residuals and ignores it (models/build.resolve_runtime_backends,
    # the one home for the TPU-aware resolution of all encoder backend
    # knobs). Pure runtime knob: params/outputs/checkpoints identical at
    # every W (parity pinned in tests/test_lstm.py, windows {1, 8, T},
    # T % W != 0 included).
    lstm_cs_window: int = 8
    # Storage dtype of the BiLSTM residual streams (full-cs mode) or
    # checkpoint pairs (windowed mode): "auto" = follow compute_dtype
    # (bf16 on the flagship — halves residual HBM traffic), "f32"/"bf16"
    # force it. VMEM carries and the in-window recompute stay f32 either
    # way, so bf16 residuals round only the window seeds. Drift is policed
    # at run time by the --grad_probe_every grad-cosine machinery
    # (train/steps.py) and bounded in tests/test_lstm.py.
    lstm_residuals: str = "auto"
    # BERT (built from scratch in models/bert.py; random-init unless weights
    # are found on disk — this sandbox has no network):
    bert_layers: int = 12
    bert_hidden: int = 768
    bert_heads: int = 12
    bert_intermediate: int = 3072
    bert_vocab_size: int = 30522  # bert-base-uncased WordPiece vocab
    bert_vocab_path: str | None = None  # vocab.txt (None -> hash fallback)
    bert_frozen: bool = True  # frozen -> fine-tuned regime (reference config 4)
    bert_weights: str | None = None  # .npz of pretrained weights (or None)
    bert_remat: bool = False  # jax.checkpoint per layer (HBM vs FLOPs)

    # Transformer encoder (models/transformer.py; ring-attention capable):
    tfm_layers: int = 4
    tfm_model: int = 256
    tfm_heads: int = 4
    tfm_ff: int = 1024
    # Mixture-of-Experts FFN (models/moe.py): 0 = dense MLP everywhere;
    # > 0 routes every ``moe_every``-th block through that many experts,
    # sharded over the mesh's ``ep`` axis.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity: float = 2.0
    moe_every: int = 2
    moe_group_size: int = 512  # tokens per routing group (memory knob)
    moe_aux_weight: float = 1e-2  # load-balance aux loss weight
    # Layer-stacked transformer (models/pipeline_transformer.py): the
    # pipeline-parallel parameter layout. Forced on when pp > 1; can be set
    # alone so a single-device run produces pp-restorable checkpoints.
    tfm_stacked: bool = False
    pp_microbatches: int = 4  # GPipe microbatches per step (pp > 1)

    # --- induction + relation modules ---
    induction_dim: int = 100  # class-vector dim C after the squash transform
    routing_iters: int = 3    # fixed trip count -> jit-exact fori_loop
    ntn_slices: int = 100     # h tensor slices in the NTN scorer

    # --- optimization ---
    loss: str = "mse"         # mse (paper §3.4) | ce (toolkit forks)
    optimizer: str = "adam"   # adam | sgd
    # Word-embedding table optimizer: "shared" (reference parity — the main
    # optimizer updates the table densely), "lazy" (EXACT dense-Adam
    # trajectory, weight decay excluded on the table, per-step cost
    # proportional to touched rows — train/lazy_embed.py), "sgd" (stateless
    # scatter update; measured +15% end-to-end at 400k vocab, -160MB moment
    # state), "frozen" (stop_gradient: no table grad exists at all).
    embed_optimizer: str = "shared"
    lr: float = 1e-3
    weight_decay: float = 1e-5
    lr_step_size: int = 2000  # StepLR-style decay interval
    lr_gamma: float = 0.5
    grad_clip: float = 10.0
    train_iter: int = 10000
    val_iter: int = 1000
    val_step: int = 1000
    test_iter: int = 3000
    # Optimizer steps fused into one dispatch via lax.scan (train/steps.py
    # make_multi_train_step). 1 = classic per-step dispatch; >1 amortizes
    # host dispatch + transfer latency with identical update semantics.
    steps_per_call: int = 1
    # Eval batches fused per dispatch at val/test boundaries. 0 = auto:
    # min(steps_per_call, 16) — a fused-eval width sized to the training
    # scan (e.g. 256) forces a small val set to pad up to the full width
    # (val_iter=1000 at B=64 is 15 batches padded to 256 = 17x wasted
    # compute per boundary) or fall back to per-batch tunnel round-trips;
    # a right-sized width costs one extra compile and neither (round-3
    # VERDICT weak item 2, boundary decomposition).
    eval_steps_per_call: int = 0
    # Fused train calls between metric fetches (each fetch is a real
    # device sync on tunneled backends). The window in steps is
    # max(50, metric_window_calls * steps_per_call).
    metric_window_calls: int = 4
    # Checkpoint tmpfs staging (train/checkpoint.py _stage_root_for):
    # "auto" = orbax writes to /dev/shm staging and the async saver
    # thread drains completed saves to the real --save_ckpt dir (measured: host-disk
    # destinations cost ~38% of sustained soak throughput vs tmpfs,
    # BASELINE.md round-3 decomposition); "off" = write directly.
    ckpt_stage: str = "auto"
    # Delta ring checkpoints (train/checkpoint.py): recovery-ring saves
    # write base + touched-row deltas for the lazy embedding table and its
    # Adam moments (the ~240 MB of the ~250 MB lazy-state d2h that made
    # boundary saves the dominant all-in tax — BASELINE.md round 5,
    # all-in/windowed 54%). Best-checkpoint saves stay full. "auto" = on
    # when the state carries lazy-embed leaves; "off" = every ring save is
    # a full state. Resume-from-delta is trajectory-equal
    # (tests/test_ckpt_delta.py).
    ckpt_delta: str = "auto"
    # Frozen-encoder feature cache (train/feature_cache.py): encode the
    # dataset once, train the episode head on gathered features. Requires
    # --encoder bert with the frozen backbone; excludes pair/adv.
    feature_cache: bool = False
    # Device-resident token cache (train/token_cache.py): upload the
    # tokenized dataset once; per step only episode indices cross
    # host->device. Any encoder, full training semantics; excludes pair/adv.
    token_cache: bool = False
    # Training-divergence guard (SURVEY.md §5.3 failure detection). The
    # paper's MSE-over-sigmoid loss has a saturation dead zone: on long
    # overfit runs the constant downward pressure on false-class scores
    # eventually drives EVERY score to ~0, where sigmoid gradients vanish
    # and the run is permanently stuck (measured on the synthetic soak,
    # 2026-07-30; inherent to the loss, not a porting artifact — CE is
    # immune). "none": log a divergence event and keep going (reference
    # behavior); "stop": restore the best checkpoint and end the run.
    divergence_guard: str = "none"
    # Failure injection (SURVEY.md §5.3): raise a RuntimeError once the
    # step counter reaches this value — exercises the crash/recovery path
    # (recovery ring + --resume) end-to-end. 0 = off. Debug-only knob.
    fault_step: int = 0
    # Run-health watchdog (obs/health.py): watch the metrics stream for
    # NaN/Inf scalars, throughput regression vs a rolling baseline, and
    # routing-entropy collapse; critical events dump the flight recorder.
    watchdog: bool = False
    # Grad-health probe (train/steps.py make_grad_probe, VERDICT weak #7):
    # every K steps, log grad global-norm and grad-cosine against an
    # all-f32 reference backward on the same batch (kind="health",
    # event="grad_probe" in metrics.jsonl). 0 = off. Live-token
    # single-device path only (cached/adv paths skip it with a warning).
    grad_probe_every: int = 0
    # Quantized serving data plane (ISSUE 18, serving/registry.py): dtype
    # of the RESIDENT per-tenant class-vector matrix on the serving chip.
    # "f32" (default), "bf16", or "int8" (per-tenant symmetric scale, the
    # scale itself kept f32 and passed into the compiled program). Serving
    # runtime knob, NOT an architecture field: checkpoints always hold f32
    # class vectors; residency is a deployment decision per tenant.
    resident_dtype: str = "f32"
    # Quantization parity police (ISSUE 18, modeled on grad_probe_every):
    # every K scored batches of a quantized tenant, shadow-score the same
    # queries against the f32 class matrix and record verdict agreement +
    # margin drift (serving/stats.py, and obs/drift.py observe_parity so
    # a quantization regression trips the SAME alarm path as model
    # drift). 0 = off.
    quant_probe_every: int = 0
    # Geometry plane (ISSUE 19, serving/geometry.py): the N-tier ladder
    # resident [N, C] class stacks pad up to, bounding compiled query
    # programs by tiers x buckets x resident dtypes regardless of how
    # many distinct relation counts the fleet's tenants carry. Comma-
    # separated ascending ints, or "off" for exact-N residency (the
    # pre-tier behavior, kept as the loadgen A/B arm). Serving runtime
    # knob like resident_dtype: checkpoints are geometry-free here —
    # padding is a deployment decision.
    geometry_tiers: str = "4,8,16,32,64"
    # Geometry-aware rendezvous placement (fleet/placement.py): when
    # > 0, each N-tier's tenants concentrate onto this many "home"
    # replicas (rendezvous top-k on the tier, then rendezvous on the
    # tenant within the home set) so one replica is never stuck
    # compiling every tier's program family. 0 = tier-blind placement.
    geometry_tier_spread: int = 0
    # Telemetry-failure injection: corrupt the LOGGED loss with NaN once
    # the step counter crosses this value (training state is untouched) —
    # exercises watchdog trip + flight-recorder dump end-to-end the way
    # fault_step exercises crash/recovery. 0 = off. Debug-only knob.
    nan_inject_step: int = 0
    # Performance-attribution observability (ISSUE 11, obs/perf.py +
    # obs/compile.py): per-window step-time decomposition into segments
    # that tile the measured window (kind="perf"), XLA compile forensics
    # with the steady-state-recompile gate (kind="compile"), and named-
    # cause classification of out-of-band windows (feed_stall /
    # recompile_burst / checkpoint_spike / gc_pause /
    # neighbor_contention) as once-latched critical events with
    # auto-captured diagnostics. Host-side only; measured tax < 2% of
    # p50 step (tests/test_perf.py).
    perf: bool = False

    # --- FewRel 2.0 adversarial domain adaptation (training-time only) ---
    adv: bool = False         # train encoder against a domain discriminator
    adv_lambda: float = 1.0   # gradient-reversal scale (encoder side)
    adv_dis_hidden: int = 256 # discriminator MLP width
    adv_batch: int = 32       # unlabeled instances per domain per step

    # --- numerics / device ---
    device: str = "tpu"       # tpu | cpu  (reference-mandated new flag)
    compute_dtype: str = "bfloat16"  # matmul dtype on the MXU
    # Episode-head (induction/routing/NTN/logits) dtype. The head is tiny
    # next to the encoder, but its output IS the loss surface: in bf16 the
    # logits carry ~0.4% quantization, and a long overfit run sits exactly
    # on that noise floor, where Adam's tiny second moments turn the noise
    # into full-size random steps (observed collapse to the zero-logit
    # basin at step ~1.2k on the synthetic soak, 2026-07-30). f32 here
    # costs <~2% end-to-end and keeps the loss surface real.
    head_dtype: str = "float32"
    param_dtype: str = "float32"
    seed: int = 0

    # --- parallelism ---
    # ZeRO-1-style optimizer-state sharding (SURVEY.md §2.2 "ZeRO/FSDP"):
    # Adam moments shard their leading axis over dp instead of replicating
    # — 1/dp of the optimizer HBM per device (the relevant regime: BERT
    # fine-tune pressing v5e HBM at big batch). Exact same update
    # trajectory; GSPMD inserts the collectives.
    zero_opt: bool = False
    # Compact demb collective (parallel/sharding.make_compact_demb_lookup,
    # ISSUE 5): on dp-sharded runs, keep the embedding lookup AND its
    # backward segment-sum local to each shard and all-reduce only the
    # compact [U, D] touched-row gradient — without it GSPMD replicates
    # the [L, M, word_dim] f32 embedding cotangent across dp (26.1
    # MB/step/device at the flagship shape, 77% of the wire payload;
    # COMMS_r06 -> COMMS_r07). "auto"/"on" = active whenever the mesh has
    # dp > 1 (numerics-neutral restructure, any backend); "off" = the
    # pre-round-7 dense behavior, kept for the chip A/B. Not an
    # architecture field: params/checkpoints are identical either way.
    compact_demb: str = "auto"
    # Bucketed gradient collectives (parallel/grad_buckets.py, ISSUE 20):
    # on pure-dp meshes, spell the dense-param gradient psums explicitly —
    # fwd+bwd per shard in shard_map (partials, no collective), then one
    # free-floating, named mean per reverse-topological bucket
    # (grad/bucket_0 = relation head ... last = embedding table), each
    # lowering to its own all-reduce that can fly while earlier layers'
    # backward still computes (the PR 6 compact-demb hoist generalized).
    # "auto" = on TPU only (numerics-neutral anywhere, but the default
    # flip is the chip A/B's call — resolve_runtime_backends records the
    # projection); "on" forces the bucketed arm (CPU-mesh parity tests,
    # ledger legs); "off" = monolithic partitioner-inserted psums, the
    # baseline arm. Not an architecture field: identical params either
    # way. Refused (resolves off) on tp/sp/pp/ep meshes and under MoE.
    grad_bucketing: str = "auto"
    grad_bucket_count: int = 4  # buckets when grad_bucketing resolves on
    # Async-collective / latency-hiding-scheduler spelling (resolved in
    # models/build.resolve_runtime_backends, one home): "auto" = on for
    # TPU backends (XLA's async pass splits hoisted collectives into
    # start/done pairs it latency-hides), "off" = synchronous lowering.
    # CPU runs record the projection only — the wall-clock A/B rides the
    # chip backlog (BASELINE.md round 21).
    async_collectives: str = "auto"
    dp: int = 1               # data-parallel mesh axis (episodes sharded)
    tp: int = 1               # tensor-parallel mesh axis (NTN slices / hidden)
    sp: int = 1               # sequence-parallel mesh axis (ring attention)
    pp: int = 1               # pipeline-parallel mesh axis (layer stages)
    ep: int = 1               # expert-parallel mesh axis (MoE experts)

    # --- host data pipeline ---
    sampler: str = "auto"     # auto | native (C++ prefetching) | python
    prefetch: int = 4         # native ring-buffer depth (0 = synchronous)
    sampler_threads: int = 2  # native worker threads
    # datapipe/ producer pipeline (ISSUE 4): background thread drives the
    # train sampler into a bounded queue of this many UNITS (a unit is
    # steps_per_call batches on the fused index paths, 1 otherwise), with
    # double-buffered device puts on single-device runs — host sampling
    # overlaps train/dispatch instead of serializing with it. 0 = the
    # synchronous pre-datapipe path, bitwise-identical episode stream
    # (tests/test_datapipe.py pins both invariants). The pipeline cursor
    # rides in every checkpoint; resume replays the exact stream.
    prefetch_depth: int = 2
    # Declarative episode-mixture schedule (datapipe/mixture.py spec
    # grammar, e.g. "train:1.0;pubmed.json:0.0@0,1.0@4000" for a FewRel
    # 2.0 domain-adaptation ramp). "" = single-source (the flat sampler).
    # Sources must produce identically-shaped batches (static jit shapes):
    # curricula act on source WEIGHTS over batch index, never on episode
    # geometry.
    mixture: str = ""
    # Feed-path fault injection (datapipe/faults.py): "slow:SECONDS",
    # "stall:INDEX", "poison:INDEX", comma-separable. Debug-only drills
    # for the obs watchdog's feed_stall/feed_poisoned detectors. "" = off.
    feed_fault: str = ""
    # Unified chaos-injection plan (obs/chaos.py, ISSUE 12): comma-
    # separated POINT@AT[*COUNT][:ARG] directives over named fault points
    # (ckpt.bitflip / ckpt.truncate / ckpt.restore_raise /
    # publish.nan_params / publish.distill_raise / serve.execute_raise).
    # Deterministic, drill-only; every fired fault emits a kind="fault"
    # record. "" = off (zero-cost: one global check per fault point).
    chaos: str = ""
    # Self-healing adaptation loop (obs/adapt.py, ISSUE 14): a drift
    # CRITICAL kicks off a supervised mixture-ramp fine-tune from the
    # live checkpoint, gated by the scenario-harness canary floors
    # before any publish. The knobs below are resolved in ONE home
    # (resolve_adapt_policy, the resolve_runtime_backends discipline)
    # shared by serve.py and train.py; train runs stamp them into the
    # checkpoint's config.json so a serving controller fine-tuning FROM
    # that artifact inherits the policy.
    adapt: bool = False
    adapt_retries: int = 3        # flap damper: failed loops before the
                                  # permanent adapt_exhausted CRITICAL
    adapt_backoff_s: float = 2.0  # base retry backoff (doubles per fail)
    adapt_cooldown_s: float = 60.0   # post-success trigger suppression
    adapt_step_budget: int = 200     # fine-tune optimizer-step budget
    adapt_wall_s: float = 300.0      # fine-tune wall-clock budget
    adapt_verify_s: float = 30.0     # post-publish verification window
    adapt_canary: str = "in_domain:0.3"  # leg:floor[,leg:floor...] | off

    @property
    def total_q(self) -> int:
        """Queries per episode including NOTA negatives (static shape)."""
        return self.n * self.q + self.na_rate * self.q

    @property
    def num_classes(self) -> int:
        """Logit width: N, plus one 'none' class when NOTA is active."""
        return self.n + (1 if self.na_rate > 0 else 0)

    # Fields that define the trained artifact (must match a checkpoint to
    # load it); everything else is runtime/episode geometry a user may vary
    # at eval time. test.py merges these from the checkpoint's config.json.
    ARCHITECTURE_FIELDS = (
        "model", "proto_metric", "gnn_dim", "gnn_blocks", "gnn_adj_hidden",
        "snail_tc_filters",
        "encoder", "hidden_size", "lstm_hidden", "att_dim", "word_dim",
        "pos_dim", "vocab_size", "max_length", "induction_dim",
        "routing_iters", "ntn_slices", "bert_layers", "bert_hidden",
        "bert_heads", "bert_intermediate", "bert_vocab_size",
        "bert_vocab_path", "tfm_layers", "tfm_model", "tfm_heads", "tfm_ff",
        # moe_top_k/moe_capacity are runtime routing knobs (no param shapes
        # depend on them) and stay restorable-across; experts/every shape
        # the tree.
        "moe_experts", "moe_every", "tfm_stacked",
        # embed_optimizer changes the optimizer-state tree (multi_transform
        # wrapper), so resume requires it to match. nota_head changes the
        # NOTA params (scalar logit vs stats affine).
        "loss", "optimizer", "embed_optimizer", "nota_head",
        # feature_cache changes the state tree itself (head-only params), so
        # a cached checkpoint can only restore into a cached runtime — and
        # that runtime must rebuild the SAME backbone: frozen flag and
        # pretrained-weights path ride along.
        "feature_cache", "bert_frozen", "bert_weights",
    )

    def replace(self, **kw: Any) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)

    # Episode-geometry fields that become architectural for specific models
    # (they shape parameters there): gnn/snail bake the N-way label width
    # into Dense/Conv shapes; proto_hatt's feature-attention conv kernel is
    # K-sized. For induction/proto these stay freely variable at eval time.
    MODEL_GEOMETRY_FIELDS = {
        "gnn": ("train_n", "n"),
        "snail": ("train_n", "n"),
        "metanet": ("train_n", "n"),
        "proto_hatt": ("k",),
    }

    def merge_architecture_from(self, other: "ExperimentConfig") -> "ExperimentConfig":
        """Take architecture-defining fields from ``other`` (a checkpoint's
        saved config), keep this config's runtime/episode fields."""
        fields = self.ARCHITECTURE_FIELDS + self.MODEL_GEOMETRY_FIELDS.get(
            other.model, ()
        )
        return self.replace(**{f: getattr(other, f) for f in fields})

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ExperimentConfig":
        return cls(**json.loads(s))


# --- self-healing adaptation knob resolution (ISSUE 14) --------------------

# The controller-facing knob names, in the order serve.py/train.py expose
# them. Each maps 1:1 onto an ExperimentConfig ``adapt_*`` field.
ADAPT_KNOBS = (
    "adapt_retries", "adapt_backoff_s", "adapt_cooldown_s",
    "adapt_step_budget", "adapt_wall_s", "adapt_verify_s", "adapt_canary",
)


def parse_canary_plan(spec: str) -> dict[str, float]:
    """``"leg:floor[,leg:floor...]"`` -> {leg: floor}; "off" -> {} (the
    canary gate disabled — every candidate publishes). Floors are hard
    go/no-go accuracy bars for tools/scenarios.run_canary; legs name the
    evaluation datasets the CLI wires (``in_domain`` = the serving
    support corpus, ``target`` = the remediation corpus)."""
    spec = (spec or "").strip()
    if not spec or spec == "off":
        return {}
    floors: dict[str, float] = {}
    for part in spec.split(","):
        leg, sep, floor_s = part.strip().partition(":")
        if not sep or not leg:
            raise ValueError(
                f"canary plan entry {part!r} must be 'leg:floor' "
                f"(e.g. 'in_domain:0.3,target:0.25') or 'off'"
            )
        floor = float(floor_s)
        if not 0.0 <= floor <= 1.0:
            raise ValueError(
                f"canary floor for {leg!r} must be in [0, 1], got {floor}"
            )
        if leg in floors:
            raise ValueError(f"canary plan names leg {leg!r} twice")
        floors[leg] = floor
    return floors


# Legal values for the resident class-matrix dtype (ISSUE 18). Order is
# the density ladder: f32 is the checkpoint truth, bf16 halves resident
# bytes with dequant-free scoring (a plain upcast the head does anyway),
# int8 quarters them behind a per-tenant symmetric f32 scale.
RESIDENT_DTYPE_CHOICES = ("f32", "bf16", "int8")


def resolve_quant_policy(knobs: Any, base: "ExperimentConfig | None" = None):
    """ONE home for the quantized-serving knob resolution (ISSUE 18, the
    models/build.resolve_runtime_backends discipline), shared by
    serve.py and the loadgen drills. ``knobs`` is any object with
    ``resident_dtype``/``quant_probe_every`` attributes — an
    ExperimentConfig or an argparse namespace; a missing or None
    attribute falls back to ``base`` (the served checkpoint's stored
    config), then to the ExperimentConfig default. Returns the validated
    policy dict {"resident_dtype", "probe_every"}."""
    fields = {f.name: f.default for f in dataclasses.fields(ExperimentConfig)}

    def knob(name):
        v = getattr(knobs, name, None)
        if v is None and base is not None:
            v = getattr(base, name, None)
        return fields[name] if v is None else v

    dtype = str(knob("resident_dtype"))
    if dtype not in RESIDENT_DTYPE_CHOICES:
        raise ValueError(
            f"resident_dtype must be one of {RESIDENT_DTYPE_CHOICES}, "
            f"got {dtype!r}"
        )
    probe_every = int(knob("quant_probe_every"))
    if probe_every < 0:
        raise ValueError(
            f"quant_probe_every must be >= 0, got {probe_every}"
        )
    return {"resident_dtype": dtype, "probe_every": probe_every}


def resolve_geometry_policy(
    knobs: Any, base: "ExperimentConfig | None" = None
):
    """ONE home for the geometry-plane knob resolution (ISSUE 19, same
    discipline as ``resolve_quant_policy``), shared by serve.py, the
    fleet CLI, and the loadgen drills. ``knobs`` is any object with
    ``geometry_tiers``/``geometry_tier_spread`` attributes — an
    ExperimentConfig or an argparse namespace; a missing or None
    attribute falls back to ``base`` (the served checkpoint's stored
    config), then to the ExperimentConfig default. Returns the
    validated policy dict {"tiers": tuple | None, "tier_spread": int}
    with the tier spec already parsed (None = exact-N residency)."""
    from induction_network_on_fewrel_tpu.serving.geometry import parse_tiers

    fields = {f.name: f.default for f in dataclasses.fields(ExperimentConfig)}

    def knob(name):
        v = getattr(knobs, name, None)
        if v is None and base is not None:
            v = getattr(base, name, None)
        return fields[name] if v is None else v

    tiers = parse_tiers(knob("geometry_tiers"))
    spread = int(knob("geometry_tier_spread"))
    if spread < 0:
        raise ValueError(
            f"geometry_tier_spread must be >= 0, got {spread}"
        )
    return {"tiers": tiers, "tier_spread": spread}


def resolve_adapt_policy(knobs: Any, base: "ExperimentConfig | None" = None):
    """ONE home for the --adapt knob resolution (the
    models/build.resolve_runtime_backends discipline), shared by
    serve.py, train.py, and the drills. ``knobs`` is any object with
    ``adapt`` + the ADAPT_KNOBS attributes — an ExperimentConfig or an
    argparse namespace; an attribute that is missing or None falls back
    to ``base`` (e.g. the served checkpoint's stored config — train runs
    stamp the policy into config.json exactly so a serving controller
    inherits it), then to the ExperimentConfig default. Returns the
    validated policy dict (controller kwargs + the parsed canary plan),
    or None when adaptation is off."""
    fields = {f.name: f.default for f in dataclasses.fields(ExperimentConfig)}

    def knob(name):
        v = getattr(knobs, name, None)
        if v is None and base is not None:
            v = getattr(base, name, None)
        return fields[name] if v is None else v

    enabled = getattr(knobs, "adapt", None)
    if enabled is None and base is not None:
        enabled = getattr(base, "adapt", False)
    if not enabled:
        return None
    retries = int(knob("adapt_retries"))
    backoff_s = float(knob("adapt_backoff_s"))
    cooldown_s = float(knob("adapt_cooldown_s"))
    step_budget = int(knob("adapt_step_budget"))
    wall_s = float(knob("adapt_wall_s"))
    verify_s = float(knob("adapt_verify_s"))
    if retries < 1:
        raise ValueError(f"adapt_retries must be >= 1, got {retries}")
    if backoff_s <= 0 or wall_s <= 0 or verify_s <= 0:
        raise ValueError(
            f"adapt_backoff_s/adapt_wall_s/adapt_verify_s must be > 0 "
            f"(got {backoff_s}/{wall_s}/{verify_s})"
        )
    if cooldown_s < 0:
        raise ValueError(f"adapt_cooldown_s must be >= 0, got {cooldown_s}")
    if step_budget < 1:
        raise ValueError(
            f"adapt_step_budget must be >= 1, got {step_budget}"
        )
    return {
        "retry_budget": retries,
        "backoff_s": backoff_s,
        "cooldown_s": cooldown_s,
        "step_budget": step_budget,
        "wall_budget_s": wall_s,
        "verify_window_s": verify_s,
        "canary_floors": parse_canary_plan(str(knob("adapt_canary"))),
    }
