"""GloVe-path tokenizer: tokens -> (word ids, pos1, pos2, mask).

Mirrors the reference's ``CNNSentenceEncoder.tokenize`` contract (SURVEY.md
§2.1 "Tokenizer (GloVe path)"): lowercase lookup with ``[UNK]`` fallback and
``[BLANK]`` padding to ``max_length``; per-token signed offsets to the head
and tail entity starts, clamped to ±max_length and shifted into
``[0, 2*max_length)`` so they index an ``Embedding(2*max_length, pos_dim)``.

Everything is numpy on the host; output shapes are fixed by ``max_length`` so
the jitted step never recompiles (TPU static-shape discipline).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import Instance
from induction_network_on_fewrel_tpu.data.glove import GloveVocab


@dataclasses.dataclass
class TokenizedInstance:
    word: np.ndarray  # [L] int32
    pos1: np.ndarray  # [L] int32, offsets to head start, shifted non-negative
    pos2: np.ndarray  # [L] int32, offsets to tail start
    mask: np.ndarray  # [L] float32, 1 for real tokens


class GloveTokenizer:
    def __init__(self, vocab: GloveVocab, max_length: int = 40):
        self.vocab = vocab
        self.max_length = int(max_length)

    def __call__(self, inst: Instance) -> TokenizedInstance:
        L = self.max_length
        ids = np.full(L, self.vocab.blank_id, dtype=np.int32)
        n = min(len(inst.tokens), L)
        for i in range(n):
            ids[i] = self.vocab.lookup(inst.tokens[i])

        head = min(inst.head_pos[0] if inst.head_pos else 0, L - 1)
        tail = min(inst.tail_pos[0] if inst.tail_pos else 0, L - 1)
        idx = np.arange(L, dtype=np.int32)
        pos1 = np.clip(idx - head, -L, L - 1) + L
        pos2 = np.clip(idx - tail, -L, L - 1) + L

        mask = np.zeros(L, dtype=np.float32)
        mask[:n] = 1.0
        return TokenizedInstance(ids, pos1.astype(np.int32), pos2.astype(np.int32), mask)
