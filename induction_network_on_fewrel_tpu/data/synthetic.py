"""Schema-faithful synthetic FewRel data + GloVe fixtures.

This sandbox has no network and no FewRel/GloVe files on disk (SURVEY.md §7
environment facts), so every loader, test, and benchmark must be able to run
against synthetic fixtures that obey the real schemas exactly. The generator
plants a learnable signal: each relation owns a small set of "trigger" words
that appear only in its sentences, so a correct model can overfit to 100%
(used by the integration test, SURVEY.md §4.4).
"""

from __future__ import annotations

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset, Instance
from induction_network_on_fewrel_tpu.data.glove import GloveVocab


def make_synthetic_glove(
    vocab_size: int = 200, word_dim: int = 50, seed: int = 0
) -> GloveVocab:
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(vocab_size)]
    vecs = rng.normal(0, 0.5, (vocab_size, word_dim)).astype(np.float32)
    return GloveVocab.from_words(words, vecs)


def make_synthetic_fewrel(
    num_relations: int = 10,
    instances_per_relation: int = 30,
    vocab_size: int = 200,
    sentence_len: tuple[int, int] = (8, 20),
    triggers_per_relation: int = 3,
    seed: int = 0,
) -> FewRelDataset:
    """Generate a FewRel-schema dataset whose relations are identifiable.

    Each relation r reserves ``triggers_per_relation`` exclusive vocabulary
    words; each of its sentences contains 1-3 of them at random positions.
    Head/tail entity mentions are random single-token spans, exercising the
    position-offset features.
    """
    rng = np.random.default_rng(seed)
    n_trigger = num_relations * triggers_per_relation
    if vocab_size <= n_trigger + 10:
        raise ValueError("vocab too small for distinct trigger words")

    relations: dict[str, list[Instance]] = {}
    for r in range(num_relations):
        trig = [f"w{r * triggers_per_relation + t}" for t in range(triggers_per_relation)]
        insts = []
        for _ in range(instances_per_relation):
            L = int(rng.integers(*sentence_len))
            toks = [f"w{int(i)}" for i in rng.integers(n_trigger, vocab_size, L)]
            for t in rng.choice(trig, size=int(rng.integers(1, 4)), replace=True):
                toks[int(rng.integers(0, L))] = t
            h, t_ = rng.choice(L, 2, replace=False)
            insts.append(
                Instance(
                    tokens=tuple(toks),
                    head_pos=(int(h),),
                    tail_pos=(int(t_),),
                    head_name=toks[int(h)],
                    tail_name=toks[int(t_)],
                )
            )
        relations[f"P{9000 + r}"] = insts
    return FewRelDataset(relations)
