"""Schema-faithful synthetic FewRel data + GloVe fixtures.

This sandbox has no network and no FewRel/GloVe files on disk (SURVEY.md §7
environment facts), so every loader, test, and benchmark must be able to run
against synthetic fixtures that obey the real schemas exactly. The generator
plants a learnable signal: each relation owns a small set of "trigger" words
that appear only in its sentences, so a correct model can overfit to 100%
(used by the integration test, SURVEY.md §4.4).
"""

from __future__ import annotations

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset, Instance
from induction_network_on_fewrel_tpu.data.glove import GloveVocab


def make_synthetic_glove(
    vocab_size: int = 200, word_dim: int = 50, seed: int = 0
) -> GloveVocab:
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(vocab_size)]
    vecs = rng.normal(0, 0.5, (vocab_size, word_dim)).astype(np.float32)
    return GloveVocab.from_words(words, vecs)


def make_synthetic_fewrel(
    num_relations: int = 10,
    instances_per_relation: int = 30,
    vocab_size: int = 200,
    sentence_len: tuple[int, int] = (8, 20),
    triggers_per_relation: int = 3,
    seed: int = 0,
) -> FewRelDataset:
    """Generate a FewRel-schema dataset whose relations are identifiable.

    Each relation r reserves ``triggers_per_relation`` exclusive vocabulary
    words; each of its sentences contains 1-3 of them at random positions.
    Head/tail entity mentions are random single-token spans, exercising the
    position-offset features.
    """
    rng = np.random.default_rng(seed)
    n_trigger = num_relations * triggers_per_relation
    if vocab_size <= n_trigger + 10:
        raise ValueError("vocab too small for distinct trigger words")

    relations: dict[str, list[Instance]] = {}
    for r in range(num_relations):
        trig = [f"w{r * triggers_per_relation + t}" for t in range(triggers_per_relation)]
        insts = []
        for _ in range(instances_per_relation):
            L = int(rng.integers(*sentence_len))
            toks = [f"w{int(i)}" for i in rng.integers(n_trigger, vocab_size, L)]
            for t in rng.choice(trig, size=int(rng.integers(1, 4)), replace=True):
                toks[int(rng.integers(0, L))] = t
            h, t_ = rng.choice(L, 2, replace=False)
            insts.append(
                Instance(
                    tokens=tuple(toks),
                    head_pos=(int(h),),
                    tail_pos=(int(t_),),
                    head_name=toks[int(h)],
                    tail_name=toks[int(t_)],
                )
            )
        relations[f"P{9000 + r}"] = insts
    return FewRelDataset(relations)


def make_domain_shifted_fewrel(
    num_relations: int = 10,
    instances_per_relation: int = 30,
    vocab_size: int = 200,
    sentence_len: tuple[int, int] = (8, 20),
    triggers_per_relation: int = 3,
    shift: float = 1.0,
    seed: int = 0,
) -> FewRelDataset:
    """A domain-shifted twin of ``make_synthetic_fewrel`` (ISSUE 10).

    Same relation names, same episode geometry — but each relation's
    identifying trigger words move to a DISJOINT vocabulary block with
    probability ``shift`` per occurrence (relation r's trigger t becomes
    word ``n_trigger + r*tpr + t`` instead of ``r*tpr + t``). This is the
    synthetic analog of FewRel 2.0's wiki -> pubmed transfer: relation
    semantics are unchanged, the surface vocabulary that carries them is
    not. A model trained on the source domain degrades toward chance as
    ``shift`` -> 1.0 unless it has seen target-domain episodes (e.g. via
    a datapipe mixture ramp) — exactly the silent quality cliff the
    scenarios harness (tools/scenarios.py) measures.

    ``shift=0.0`` reproduces the source domain's trigger placement
    (though with an independent sentence draw); pass the same ``seed`` as
    the source dataset so relation names line up.
    """
    if not 0.0 <= shift <= 1.0:
        raise ValueError(f"shift must be in [0, 1], got {shift}")
    rng = np.random.default_rng(seed + 0x5D1F7)
    n_trigger = num_relations * triggers_per_relation
    if vocab_size <= 2 * n_trigger + 10:
        raise ValueError(
            "vocab too small for disjoint source+shifted trigger blocks"
        )

    relations: dict[str, list[Instance]] = {}
    for r in range(num_relations):
        src_trig = [
            f"w{r * triggers_per_relation + t}"
            for t in range(triggers_per_relation)
        ]
        tgt_trig = [
            f"w{n_trigger + r * triggers_per_relation + t}"
            for t in range(triggers_per_relation)
        ]
        insts = []
        for _ in range(instances_per_relation):
            L = int(rng.integers(*sentence_len))
            # Background draws start past BOTH trigger blocks so a
            # shifted trigger is as exclusive to its relation as a source
            # trigger is in the source domain.
            toks = [
                f"w{int(i)}" for i in rng.integers(2 * n_trigger, vocab_size, L)
            ]
            for t in range(int(rng.integers(1, 4))):
                which = int(rng.integers(triggers_per_relation))
                word = (
                    tgt_trig[which] if rng.random() < shift
                    else src_trig[which]
                )
                toks[int(rng.integers(0, L))] = word
            h, t_ = rng.choice(L, 2, replace=False)
            insts.append(
                Instance(
                    tokens=tuple(toks),
                    head_pos=(int(h),),
                    tail_pos=(int(t_),),
                    head_name=toks[int(h)],
                    tail_name=toks[int(t_)],
                )
            )
        relations[f"P{9000 + r}"] = insts
    return FewRelDataset(relations)
