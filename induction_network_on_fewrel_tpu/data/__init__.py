from induction_network_on_fewrel_tpu.data.fewrel import (  # noqa: F401
    FewRelDataset,
    Instance,
    load_fewrel_json,
)
from induction_network_on_fewrel_tpu.data.glove import GloveVocab  # noqa: F401
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer  # noqa: F401
from induction_network_on_fewrel_tpu.data.synthetic import (  # noqa: F401
    make_domain_shifted_fewrel,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
