"""GloVe vocabulary + embedding matrix loading.

The reference family ships GloVe 6B.50d as a word->id JSON plus an ``.npy``
matrix (SURVEY.md §1 L1 row, [E]); a single combined JSON
``[{"word": w, "vec": [...]}]`` also circulates. Both are accepted here, and
two extra rows are appended for ``[UNK]`` and ``[BLANK]`` (pad), matching the
"+2 rows" convention in SURVEY.md §2.1 "Embedding".
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import numpy as np

UNK = "[UNK]"
BLANK = "[BLANK]"


@dataclasses.dataclass
class GloveVocab:
    word2id: dict[str, int]
    vectors: np.ndarray  # [V, word_dim] float32, rows for UNK/BLANK included

    @property
    def unk_id(self) -> int:
        return self.word2id[UNK]

    @property
    def blank_id(self) -> int:
        return self.word2id[BLANK]

    @property
    def vocab_size(self) -> int:
        return self.vectors.shape[0]

    @property
    def word_dim(self) -> int:
        return self.vectors.shape[1]

    def lookup(self, token: str) -> int:
        w2i = self.word2id
        return w2i.get(token, w2i.get(token.lower(), self.unk_id))

    @classmethod
    def from_words(cls, words: list[str], vectors: np.ndarray) -> "GloveVocab":
        """Build from plain words + their vectors, appending UNK/BLANK rows."""
        dim = vectors.shape[1]
        word2id = {w: i for i, w in enumerate(words)}
        word2id[UNK] = len(words)
        word2id[BLANK] = len(words) + 1
        rng = np.random.default_rng(0)
        extra = np.stack(
            # UNK: small random (never trained to zero); BLANK: exact zeros so
            # padding contributes nothing before masking.
            [rng.normal(0, 0.1, dim).astype(np.float32), np.zeros(dim, np.float32)]
        )
        return cls(word2id, np.concatenate([vectors.astype(np.float32), extra]))


def load_glove(path: str | Path, mat_path: str | Path | None = None) -> GloveVocab:
    """Load GloVe from a word2id JSON + .npy matrix, a combined JSON, or the
    stock ``glove.6B.50d.txt`` format ("word v1 ... v50" per line)."""
    path = Path(path)
    if path.suffix == ".txt":
        # Tokens may themselves contain spaces (glove.840B.300d has entries
        # like ". . ."), so the vector dim is detected once from the first
        # line's maximal float suffix, then every line is split from the
        # right: word = everything before the last ``dim`` fields.
        words, rows, dim = [], [], None
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                if dim is None:
                    dim = 0
                    for p in reversed(parts[1:]):
                        try:
                            float(p)
                        except ValueError:
                            break
                        dim += 1
                    if dim == 0:
                        raise ValueError(
                            f"{path}:{lineno}: no numeric vector fields"
                        )
                try:
                    rows.append(np.asarray(parts[-dim:], dtype=np.float32))
                except ValueError as e:
                    raise ValueError(
                        f"{path}:{lineno}: expected {dim} floats at line "
                        f"end: {e}"
                    ) from e
                words.append(" ".join(parts[:-dim]))
        if not words:
            raise ValueError(f"{path}: no GloVe vectors found")
        return GloveVocab.from_words(words, np.stack(rows))
    with open(path) as f:
        raw = json.load(f)
    if isinstance(raw, dict):  # word2id json + separate matrix
        if mat_path is None:
            if "word2id.json" not in path.name:
                raise ValueError(
                    f"{path.name!r} is a word2id dict but mat_path was not given "
                    "and the filename does not follow the '*word2id.json' -> "
                    "'*mat.npy' convention"
                )
            mat_path = path.with_name(path.name.replace("word2id.json", "mat.npy"))
        mat = np.load(mat_path)
        words = [w for w, _ in sorted(raw.items(), key=lambda kv: kv[1])]
        return GloveVocab.from_words(words, mat)
    # combined [{"word": ..., "vec": [...]}] json
    words = [e["word"] for e in raw]
    mat = np.asarray([e["vec"] for e in raw], dtype=np.float32)
    return GloveVocab.from_words(words, mat)
