"""FewRel dataset schema and loader.

FewRel JSON (Han et al., EMNLP 2018) maps relation name -> list of instances;
each instance is ``{"tokens": [str, ...], "h": [name, wikidata_id,
[[head token positions]]], "t": [same for tail]}`` (SURVEY.md §2.1 "Dataset
loader" row). This module parses that schema into plain-Python structures;
all array work happens downstream in the tokenizer/sampler so this layer
stays numpy/JAX-free and trivially testable.

No torch Dataset/DataLoader machinery: on TPU the sampler is a host-side
numpy generator feeding the jit boundary (SURVEY.md §3.4), so the "dataset"
is just an indexed, tokenized store.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class Instance:
    """One sentence with marked head/tail entity mentions."""

    tokens: tuple[str, ...]
    head_pos: tuple[int, ...]   # token indices of the head mention (first span)
    tail_pos: tuple[int, ...]   # token indices of the tail mention (first span)
    head_name: str = ""
    tail_name: str = ""

    @classmethod
    def from_raw(cls, raw: Mapping) -> "Instance":
        h, t = raw["h"], raw["t"]
        # Positions nest as [[span1 indices], [span2 indices], ...]; the
        # first span is the mention used for position features.
        return cls(
            tokens=tuple(raw["tokens"]),
            head_pos=tuple(h[2][0]),
            tail_pos=tuple(t[2][0]),
            head_name=str(h[0]),
            tail_name=str(t[0]),
        )


class FewRelDataset:
    """Relation-indexed store of instances.

    ``rel_names`` fixes a deterministic relation ordering so that a seeded
    sampler draws identical episodes across runs and hosts (multi-host data
    parallelism shards episodes by index, so determinism is load-bearing).
    """

    def __init__(self, relations: Mapping[str, Sequence[Instance]]):
        if not relations:
            raise ValueError("FewRelDataset needs at least one relation")
        self.rel_names: tuple[str, ...] = tuple(sorted(relations))
        self.instances: dict[str, tuple[Instance, ...]] = {
            r: tuple(relations[r]) for r in self.rel_names
        }
        for r, insts in self.instances.items():
            if not insts:
                raise ValueError(f"relation {r!r} has no instances")

    @property
    def num_relations(self) -> int:
        return len(self.rel_names)

    def __repr__(self) -> str:
        n_inst = sum(len(v) for v in self.instances.values())
        return f"FewRelDataset({self.num_relations} relations, {n_inst} instances)"


def load_fewrel_json(path: str | Path) -> FewRelDataset:
    """Load a FewRel-schema JSON file (train_wiki/val_wiki/val_pubmed style)."""
    with open(path) as f:
        raw = json.load(f)
    return FewRelDataset(
        {rel: [Instance.from_raw(x) for x in insts] for rel, insts in raw.items()}
    )
