"""BERT-path tokenizer: WordPiece + entity markers (SURVEY.md §2.1
"Tokenizer (BERT path)").

Emits the same ``TokenizedInstance`` contract as the GloVe tokenizer, so the
episodic sampler is encoder-agnostic. Entity position information is carried
in-band: ``[E1]``/``[E2]`` marker tokens (ids 1/2 == BERT's [unused0]/
[unused1]) are inserted before the head/tail mention; BertEncoder pools the
hidden states at those marker positions. pos1/pos2 are zero-filled (the BERT
path does not use offset embeddings).

Two modes:
* ``vocab_path`` given -> real WordPiece over a bert-base-uncased vocab.txt
  (greedy longest-match-first, ``##`` continuations).
* no vocab (this sandbox has none on disk) -> deterministic hash fallback:
  whole tokens map to ids in [16, vocab_size); schema- and shape-faithful so
  training/benchmarks run end-to-end with random-init BERT.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import Instance
from induction_network_on_fewrel_tpu.data.tokenizer import TokenizedInstance

PAD_ID = 0
E1_ID = 1   # [unused0]
E2_ID = 2   # [unused1]
_FALLBACK_CLS, _FALLBACK_SEP, _FALLBACK_UNK = 3, 4, 5
_FALLBACK_RESERVED = 16


class BertTokenizer:
    def __init__(
        self,
        max_length: int = 128,
        vocab_path: str | Path | None = None,
        vocab_size: int = 30522,
    ):
        self.max_length = int(max_length)
        self.vocab: dict[str, int] | None = None
        self.vocab_size = vocab_size
        if vocab_path is not None:
            words = Path(vocab_path).read_text().splitlines()
            self.vocab = {w: i for i, w in enumerate(words)}
            self.vocab_size = len(words)
            self.cls_id = self.vocab.get("[CLS]", _FALLBACK_CLS)
            self.sep_id = self.vocab.get("[SEP]", _FALLBACK_SEP)
            self.unk_id = self.vocab.get("[UNK]", _FALLBACK_UNK)
        else:
            self.cls_id, self.sep_id, self.unk_id = (
                _FALLBACK_CLS, _FALLBACK_SEP, _FALLBACK_UNK,
            )

    # -- wordpiece ----------------------------------------------------------

    def _wordpiece(self, token: str) -> list[int]:
        if self.vocab is None:
            # stable FNV-1a hash into the non-reserved id range
            h = 2166136261
            for ch in token.lower().encode():
                h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
            span = self.vocab_size - _FALLBACK_RESERVED
            return [h % span + _FALLBACK_RESERVED]
        tok, out, start = token.lower(), [], 0
        while start < len(tok):
            end, cur = len(tok), None
            while start < end:
                piece = tok[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            out.append(cur)
            start = end
        return out

    def __call__(self, inst: Instance) -> TokenizedInstance:
        L = self.max_length
        head = inst.head_pos[0] if inst.head_pos else 0
        tail = inst.tail_pos[0] if inst.tail_pos else 0

        ids = [self.cls_id]
        for i, tok in enumerate(inst.tokens):
            if i == head:
                ids.append(E1_ID)
            if i == tail:
                ids.append(E2_ID)
            ids.extend(self._wordpiece(tok))
        ids.append(self.sep_id)
        ids = ids[:L]

        word = np.full(L, PAD_ID, dtype=np.int32)
        word[: len(ids)] = ids
        mask = np.zeros(L, dtype=np.float32)
        mask[: len(ids)] = 1.0
        zeros = np.zeros(L, dtype=np.int32)
        return TokenizedInstance(word, zeros, zeros.copy(), mask)
