"""Fleet control plane: tenant lifecycle routed to owners + the
all-or-nothing fan-out publish (ISSUE 13 tentpole, piece c).

**Tenant ops route to the owner.** ``register_tenant`` resolves the
rendezvous owner, registers the support set THERE, and records the
source in the router's tenant directory — which is what makes failover
real: after a replica death, ``replace_tenants`` re-registers every
displaced tenant (same source, same NOTA threshold, same quarantine
flag) on its new rendezvous owner, and per-tenant state — exactly the
FewRel 2.0 knobs (NOTA thresholds, drift baselines re-armed by the
registration) — survives re-placement. Quarantine/threshold ops route
the same way.

**Fan-out publish is one fleet transaction.** ``publish_params`` /
``publish_checkpoint`` run the registry's two-phase publish
(serving/registry.prepare_publish -> PublishTransaction) across every
non-dead replica: phase 1 prepares ALL replicas (validation gate + full
re-distill, nothing visible to any data plane); only when every prepare
succeeded does phase 2 commit them one by one (plain-assignment swaps —
zero recompiles, in-flight batches pinned to their old snapshots). ANY
prepare failure — a validation veto, a raising distill, an injected
``publish.nan_params`` on any ONE replica — aborts every prepared
transaction before any replica moved: params_version stays uniform at
the OLD generation fleet-wide, and one ``kind="fault"``
``action="publish_rollback"`` record (``scope="fleet"``) names the
refusing replica. After a committed fan-out every replica is at the
SAME new params_version (asserted), each engine's drift detector
re-armed through its own commit hook.
"""

from __future__ import annotations

import time

from induction_network_on_fewrel_tpu.fleet.placement import DEAD
from induction_network_on_fewrel_tpu.fleet.router import (
    FleetRouter,
    InProcessReplica,
    ReplicaHandle,
    _TenantEntry,
    drive_tenant_state,
)


class FleetPublishError(RuntimeError):
    """The fan-out publish failed. With ``committed`` empty (the normal
    case — phase 1 refused) the WHOLE fleet rolled back: every replica
    still serves its pre-publish generation at the old params_version.
    A non-empty ``committed`` means a COMMIT-phase failure (rare: a
    late-registered straggler whose re-distill fails validation) left
    the fleet version-skewed — the named replicas are live on the new
    generation, the failing one rolled back; re-running the fan-out
    once the cause is fixed restores uniformity. ``replica`` names the
    refusing replica either way."""

    def __init__(self, replica: str, cause: BaseException,
                 committed: tuple[str, ...] = ()):
        if committed:
            msg = (
                f"fleet publish PARTIALLY committed: replica {replica!r} "
                f"failed its commit ({type(cause).__name__}: {cause}) "
                f"after {list(committed)} committed — the fleet is "
                f"version-skewed; re-run the fan-out once the failure "
                f"is fixed"
            )
        else:
            msg = (
                f"fleet publish rolled back: replica {replica!r} refused "
                f"({type(cause).__name__}: {cause}); every replica stays "
                f"on its old params_version"
            )
        super().__init__(msg)
        self.replica = replica
        self.cause = cause
        self.committed = tuple(committed)


class FleetControl:
    """Control-plane operations over a ``FleetRouter``'s replicas.

    With a ``journal`` (fleet/journal.FleetJournal, ISSUE 15) every
    control-plane op is write-ahead-logged AFTER it succeeds on the
    replicas: tenant register/threshold/quarantine, replica
    add/drain/revive, and committed publishes (params_version + the
    checkpoint path a catch-up can re-drive). A crashed router then
    rebuilds everything through ``FleetRouter.recover(journal)``.
    Placement is never journaled — it stays a pure rendezvous function
    of (tenant id, live replica set)."""

    def __init__(self, router: FleetRouter, logger=None, journal=None):
        self.router = router
        self._logger = logger if logger is not None else router._logger
        self.journal = journal

    def _journal(self, op: str, **fields) -> None:
        if self.journal is not None:
            self.journal.append(op, **fields)

    @staticmethod
    def _source_wire(dataset):
        """The journal-ready form of a support source (None for
        non-dataset sources — e.g. routing-only stubs; such tenants
        recover their directory row but cannot be re-registered)."""
        if dataset is None or not hasattr(dataset, "rel_names"):
            return None
        from induction_network_on_fewrel_tpu.fleet.transport import (
            _dataset_to_wire,
        )

        return _dataset_to_wire(dataset)

    # --- tenant lifecycle -------------------------------------------------

    def register_tenant(
        self, tenant: str, dataset, max_classes=None,
        nota_threshold=None,
    ) -> str:
        """Register ``tenant``'s support corpus on its rendezvous owner;
        returns the owning replica id. The source is recorded in the
        router directory so failover can re-register it elsewhere.

        When the router carries a ``resident_budget_bytes``, placement
        capacity is derived from RESIDENT BYTES (ISSUE 18), not tenant
        count: a registration that would land on a replica already at
        its byte budget is refused up front — quantized (bf16/int8)
        tenants pack ~2-4x denser than f32 under the same budget."""
        # Place through the router's one tier-aware spelling so register,
        # submit, failover, and recovery all agree on the owner.
        owner = self.router.place_tenant(
            tenant, _TenantEntry(None, dataset, max_classes=max_classes)
        )
        if owner is None:
            raise RuntimeError("no live replica to place the tenant on")
        budget = self.router.resident_budget_bytes
        if budget is not None:
            used = self.router.replica_resident_bytes(owner)
            if used >= budget:
                raise RuntimeError(
                    f"replica {owner!r} is at its resident-byte budget "
                    f"({used:.0f}/{budget:.0f} bytes) — cannot place "
                    f"tenant {tenant!r}; lower the tenant's resident "
                    f"dtype or add replicas"
                )
        handle = self.router.replicas[owner]
        handle.register_dataset(dataset, tenant, max_classes=max_classes)
        entry = _TenantEntry(owner, dataset, max_classes=max_classes)
        if nota_threshold is not None:
            handle.set_nota_threshold(nota_threshold, tenant)
            entry.nota_threshold = nota_threshold
        # Under the router lock: directory iterations (pending_failover,
        # mark_replica_dead's affected-tenant count) snapshot under the
        # same lock, so a concurrent registration can't blow up a
        # mid-failover iteration.
        with self.router._lock:
            self.router.directory[tenant] = entry
        self._journal(
            "tenant_register", tenant=tenant,
            source=self._source_wire(dataset), max_classes=max_classes,
            nota_threshold=nota_threshold,
        )
        return owner

    def set_nota_threshold(self, tenant: str, threshold) -> None:
        entry = self._entry(tenant)
        self.router.replicas[entry.owner].set_nota_threshold(
            threshold, tenant
        )
        entry.nota_threshold = threshold
        self._journal("tenant_threshold", tenant=tenant,
                      threshold=threshold)

    def quarantine_tenant(self, tenant: str, reason: str = "") -> None:
        entry = self._entry(tenant)
        self.router.replicas[entry.owner].quarantine_tenant(tenant, reason)
        entry.quarantined = True
        self._journal("tenant_quarantine", tenant=tenant, reason=reason)

    def unquarantine_tenant(self, tenant: str, reason: str = "") -> None:
        entry = self._entry(tenant)
        self.router.replicas[entry.owner].unquarantine_tenant(
            tenant, reason
        )
        entry.quarantined = False
        self._journal("tenant_unquarantine", tenant=tenant, reason=reason)

    def _entry(self, tenant: str) -> _TenantEntry:
        entry = self.router.directory.get(tenant)
        if entry is None:
            raise ValueError(f"unknown tenant {tenant!r}")
        return entry

    # --- membership / re-placement ----------------------------------------

    def add_replica(self, handle: ReplicaHandle) -> None:
        """Join a replica: membership + placement. Tenants whose
        rendezvous now prefers the newcomer (the ~1/R bound) show up in
        ``pending_failover`` and move on the next ``replace_tenants``."""
        rid = handle.replica_id
        self.router.replicas[rid] = handle
        self.router.routed.setdefault(rid, 0)
        self.router.placement.add_replica(rid)
        self._journal("replica_add", replica=rid)
        if self._logger is not None:
            self._logger.log(
                self.router.submitted, kind="fleet", event="replica_add",
                replica=rid, replicas=float(len(self.router.replicas)),
            )

    def drain_replica(self, replica: str) -> None:
        """Operator drain, journaled: the replica leaves placement (its
        tenants remap at the rendezvous bound) but keeps serving what is
        in flight — and a recovered router replays the drain instead of
        routing fresh traffic back."""
        self.router.drain_replica(replica)
        self._journal("replica_drain", replica=replica)

    def revive_replica(self, replica: str, reason: str = "") -> None:
        self.router.revive_replica(replica, reason=reason)
        self._journal("replica_revive", replica=replica)

    def retire_replica(self, replica: str) -> None:
        """Remove a replica from the fleet FOR GOOD (the autoscaler's
        drain-in endpoint, also an operator op). Refuses while the
        directory still names it as an owner — drain + ``replace_tenants``
        first; retiring is the last step, after in-flight work is out."""
        owners = {e.owner for e in self.router.directory.values()}
        if replica in owners:
            raise ValueError(
                f"replica {replica!r} still owns tenants — drain it and "
                "run replace_tenants() before retiring"
            )
        handle = self.router.replicas.get(replica)
        self.router.remove_replica(replica)
        self._journal("replica_retire", replica=replica)
        if handle is not None:
            try:
                handle.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    def forgive_replica(self, replica: str, supervisor=None) -> None:
        """Operator escape hatch: re-arm a replica's supervisor restart
        budget (``ReplicaSupervisor.forgive``), journaled so the audit
        trail shows WHO un-latched a restart-exhausted replica (the
        replay itself is neutral — budgets are process-local)."""
        if supervisor is not None:
            supervisor.forgive(replica)
        self._journal("replica_forgive", replica=replica)
        if self._logger is not None:
            self._logger.log(
                self.router.submitted, kind="fleet",
                event="replica_forgive", replica=replica,
            )

    def replace_tenants(self) -> int:
        """Re-register every displaced tenant (registered owner !=
        current placement) on its new owner, carrying its NOTA threshold
        and quarantine flag; the OLD registration is dropped when its
        replica is still reachable (a dead one simply keeps stale state
        it will never be asked about — and a revive re-fans a publish
        before it re-enters placement anyway). A request already QUEUED
        on the old owner when its tenant state drops fails with a typed
        retryable ``ExecuteError`` (clients retry onto the new owner;
        the router's breaker ignores failures from a replica that is no
        longer the tenant's registered owner, so stragglers cannot
        open a healthy replica's breaker). Returns tenants moved —
        the placement-churn number the FLEET artifact records."""
        moved = 0
        for tenant in self.router.pending_failover():
            entry = self.router.directory[tenant]
            target = self.router.place_tenant(tenant, entry)
            if target is None:
                continue
            drive_tenant_state(
                self.router.replicas[target], tenant, entry,
                reason="carried over",
            )
            old = entry.owner
            entry.owner = target
            moved += 1
            if (old in self.router.replicas
                    and self.router.placement.state(old) not in (None, DEAD)):
                try:
                    self.router.replicas[old].drop_tenant(tenant)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
        if moved:
            with self.router._lock:
                self.router.replaced += moved
            if self._logger is not None:
                self._logger.log(
                    self.router.submitted, kind="fleet", event="replace",
                    moved=float(moved),
                    tenants=float(len(self.router.directory)),
                )
        return moved

    # --- fan-out publish --------------------------------------------------

    def _publish_targets(self) -> list[str]:
        """Every non-dead replica, deterministic order. Dead replicas
        miss the fan-out by design — they re-enter service only through
        revive + replace/re-publish (RUNBOOK §18)."""
        states = self.router.placement.states()
        return [
            rid for rid in sorted(self.router.replicas)
            if states.get(rid) != DEAD
        ]

    def publish_params(self, new_params) -> int:
        return self._fanout_publish(params=new_params)

    def publish_checkpoint(self, ckpt_dir: str) -> int:
        return self._fanout_publish(ckpt_dir=ckpt_dir)

    def _fanout_publish(self, params=None, ckpt_dir=None) -> int:
        t0 = time.monotonic()
        targets = self._publish_targets()
        if not targets:
            raise RuntimeError("no live replica to publish to")
        prepared: list[tuple[str, object]] = []
        try:
            # Prepares run SEQUENTIALLY, deterministic replica order, by
            # design: the chaos grammar targets fault points by 0-based
            # GLOBAL arrival index (publish.nan_params@1 = the middle
            # replica of three — test-pinned), and parallel prepares
            # would make that order a race. Fan-out publish wall time
            # therefore scales with R; parallel prepare needs a
            # per-replica chaos ARG filter first (future work).
            shared_params = params
            for rid in targets:
                handle = self.router.replicas[rid]
                if isinstance(handle, InProcessReplica):
                    # In-process replicas share this process's memory:
                    # restore the checkpoint ONCE and fan the tree out,
                    # instead of R identical disk restores (the cost
                    # lands straight in the recorded publish_s). Socket
                    # replicas keep ckpt_dir — a params tree does not
                    # cross the wire; each process restores locally.
                    if shared_params is None and ckpt_dir is not None:
                        from induction_network_on_fewrel_tpu.serving \
                            .registry import load_params

                        shared_params = load_params(ckpt_dir)
                    txn = handle.prepare_publish(params=shared_params)
                else:
                    txn = handle.prepare_publish(params=params,
                                                 ckpt_dir=ckpt_dir)
                prepared.append((rid, txn))
        except BaseException as e:
            failing = targets[len(prepared)]
            for rid, txn in prepared:
                try:
                    self.router.replicas[rid].abort_publish(txn)
                except Exception:  # noqa: BLE001 — abort the rest anyway
                    pass
            if self._logger is not None:
                self._logger.log(
                    self.router.submitted, kind="fault",
                    action="publish_rollback", scope="fleet",
                    replica=failing,
                    reason=f"{type(e).__name__}: {e}",
                    prepared=float(len(prepared)),
                )
            raise FleetPublishError(failing, e) from e
        # Phase 2: commit every prepared transaction. A commit CAN still
        # refuse (a late-registered straggler whose re-distill fails
        # validation — that replica rolls back and releases its serial
        # lock in its own finally). Keep committing the rest either way:
        # once any replica is live on the new generation, aborting the
        # others would only WIDEN the skew — and every transaction must
        # be finished (commit or its own rollback) so no publish-serial
        # lock is ever left held.
        versions: dict[str, int] = {}
        failed: list[tuple[str, BaseException]] = []
        telemetry_errors: list[tuple[str, BaseException]] = []
        for rid, txn in prepared:
            try:
                versions[rid] = self.router.replicas[rid].commit_publish(
                    txn
                )
            except BaseException as e:  # noqa: BLE001 — finish the fan-out
                if getattr(txn, "committed", False):
                    # The swap IS live on this replica — the exception
                    # came from POST-commit bookkeeping (a raising
                    # logger hook, disk-full jsonl write; the exact
                    # case PublishTransaction.committed exists for).
                    # Count it committed at its staged version and
                    # surface the real error below — never report a
                    # rollback that did not happen. (A socket txn is a
                    # token, committed unreadable: the wire path stays
                    # conservative and lands in ``failed``.)
                    versions[rid] = txn.new_version
                    telemetry_errors.append((rid, e))
                else:
                    failed.append((rid, e))
        if failed:
            rid, cause = failed[0]
            committed = tuple(sorted(versions))
            if self._logger is not None:
                self._logger.log(
                    self.router.submitted, kind="fault",
                    action="publish_rollback", scope="fleet",
                    replica=rid,
                    reason=f"commit: {type(cause).__name__}: {cause}",
                    prepared=float(len(prepared)),
                    committed=float(len(committed)),
                )
            raise FleetPublishError(rid, cause, committed=committed) \
                from cause
        version = max(versions.values())
        # Write-ahead the COMMITTED generation (the publish is live on
        # every replica at this point): the params_version plus the
        # checkpoint path recovery re-drives a stale replica's catch-up
        # from. Journaled before the telemetry/skew records so a raising
        # logger hook can never lose a live commit. fsync="commit"
        # syncs exactly this append.
        self._journal("publish_commit", params_version=int(version),
                      ckpt_dir=str(ckpt_dir) if ckpt_dir else None)
        if len(set(versions.values())) != 1 and self._logger is not None:
            # The fleet is LIVE on the new weights everywhere (commits
            # landed) but the version COUNTERS disagree — a replica with
            # a different publish history (e.g. direct per-replica
            # publishes before it joined). Surfaced, never hidden: the
            # uniformity invariant the drills assert is on fleets whose
            # replicas share one history.
            self._logger.log(
                self.router.submitted, kind="fault",
                action="publish_version_skew",
                reason=" ".join(
                    f"{r}:{v}" for r, v in sorted(versions.items())
                ),
            )
        if self._logger is not None:
            self._logger.log(
                self.router.submitted, kind="fleet",
                event="fanout_publish",
                publish_s=round(time.monotonic() - t0, 4),
                replicas=float(len(versions)),
                params_version=float(version),
            )
        if telemetry_errors:
            # Every commit is live (the publish SUCCEEDED fleet-wide),
            # but a replica's post-commit bookkeeping raised — re-raise
            # the real error like single-replica publish_params does,
            # after the fanout record above told the truth.
            raise telemetry_errors[0][1]
        return version
