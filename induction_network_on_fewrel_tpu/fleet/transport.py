"""Socket transport: the ``ReplicaHandle`` interface over JSON-lines TCP
(ISSUE 13 tentpole, piece b — the multi-process spelling).

The router is transport-agnostic: tier-1 and CPU drills run
``InProcessReplica`` handles, and THIS module puts the exact same
interface over a localhost socket so a real deployment can run one
engine replica per process (or per host) behind the same router code.
One JSON object per line each way:

    request:  {"op": <name>, ...operands}
    response: {"ok": true, ...result} | {"ok": false, "error": <type>,
               "message": str, ...typed-error fields}

Typed serving errors cross the wire by name: ``Saturated`` (with
``retry_after_s``/``tenant``) and ``ExecuteError`` (with ``tenant``/
``retry_after_s``) are re-raised as the SAME types client-side, so the
router's breaker/backpressure logic cannot tell the transports apart —
which is the point.

Publish fan-out over the wire ships the CHECKPOINT DIRECTORY, not a
params tree: replicas of a real multi-process fleet share the training
run's artifact store, and ``publish_prepare`` restores + prepares
locally (phase 1), holding the transaction server-side under a token
until ``publish_commit``/``publish_abort`` (phase 2) — the same
two-phase contract the in-process handle provides, so the fleet control
plane's all-or-nothing fan-out works unchanged across processes.

Scope: the wire format favors clarity over throughput (tokens travel as
JSON); it is the correctness-faithful IPC arm the slow-lane tests
exercise, not a tuned RPC stack.

Trace stitching (ISSUE 17): the FULL ``TraceContext`` crosses the wire —
``trace_id`` AND ``span_id`` (the originating span, normally the
router's ``fleet/route`` span). The id string alone is NOT enough: a
replica that rebuilds the context with ``span_id=0`` treats its first
span as the trace's origin, and the router→replica parent link is lost
— the stitched waterfall degenerates into two sibling trees that merely
share an id. With the span id carried, the replica's top-level
``serve/*`` spans parent to the router's span exactly as a cross-THREAD
adoption does in-process (obs/spans.SpanTracker.span). Alongside it,
each new connection runs an NTP-style clock handshake: ``op="clock"``
probes collect (t0 send, t1 server recv, t2 server send, t3 recv)
quadruples and ``ClockSync`` keeps a rolling-median offset estimate —
how tools/fleet_report.py aligns replica-side wall clocks onto the
router's timeline.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from induction_network_on_fewrel_tpu.fleet.router import ReplicaHandle
from induction_network_on_fewrel_tpu.obs.chaos import (
    chaos_active,
    chaos_fire,
)
from induction_network_on_fewrel_tpu.serving.batcher import (
    ExecuteError,
    Saturated,
    TransportTimeout,
)


class ClockSync:
    """NTP-style clock-offset estimator for one router→replica link
    (ISSUE 17). Each probe contributes four timestamps — t0 client
    send, t1 server receive, t2 server send, t3 client receive — and
    one offset sample ``((t1 - t0) + (t2 - t3)) / 2``: the
    symmetric-path estimate of (server clock − client clock). The
    estimate is the rolling MEDIAN of the last ``window`` samples,
    robust to the occasional probe that straddles a GC pause or a
    loaded accept queue (an asymmetric leg skews the mean, not the
    median). A sample's RTT, ``(t3 - t0) - (t2 - t1)``, bounds its
    error at half-RTT; the median across probes keeps the estimate
    near the fastest probe's bound. Thread-safe: every dialing thread
    of a ``SocketReplica`` feeds the same estimator."""

    __slots__ = ("window", "_samples", "_rtts", "_lock")

    def __init__(self, window: int = 15):
        self.window = max(1, int(window))
        self._samples: list[float] = []
        self._rtts: list[float] = []
        self._lock = threading.Lock()

    def observe(self, t0: float, t1: float, t2: float, t3: float) -> float:
        """Fold one probe quadruple in; returns this probe's offset
        sample (server − client, seconds)."""
        sample = ((t1 - t0) + (t2 - t3)) / 2.0
        rtt = max(0.0, (t3 - t0) - (t2 - t1))
        with self._lock:
            self._samples.append(sample)
            self._rtts.append(rtt)
            if len(self._samples) > self.window:
                del self._samples[0]
                del self._rtts[0]
        return sample

    @property
    def samples(self) -> int:
        with self._lock:
            return len(self._samples)

    def offset_s(self) -> float:
        """Current estimate of (server clock − client clock) in
        seconds; 0.0 before any probe has landed."""
        with self._lock:
            xs = sorted(self._samples)
        if not xs:
            return 0.0
        mid = len(xs) // 2
        if len(xs) % 2:
            return xs[mid]
        return (xs[mid - 1] + xs[mid]) / 2.0

    def rtt_s(self) -> float:
        """Median probe round-trip (the error bound's scale); 0.0
        before any probe."""
        with self._lock:
            xs = sorted(self._rtts)
        if not xs:
            return 0.0
        mid = len(xs) // 2
        if len(xs) % 2:
            return xs[mid]
        return (xs[mid - 1] + xs[mid]) / 2.0


def _inst_to_wire(inst) -> dict:
    return {
        "tokens": list(inst.tokens),
        "head_pos": list(inst.head_pos),
        "tail_pos": list(inst.tail_pos),
    }


def _inst_from_wire(d: dict):
    from induction_network_on_fewrel_tpu.data.fewrel import Instance

    return Instance(
        tokens=tuple(d["tokens"]),
        head_pos=tuple(int(p) for p in d["head_pos"]),
        tail_pos=tuple(int(p) for p in d["tail_pos"]),
    )


def _dataset_to_wire(dataset) -> dict:
    return {
        rel: [_inst_to_wire(i) for i in dataset.instances[rel]]
        for rel in dataset.rel_names
    }


def _dataset_from_wire(d: dict):
    from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset

    return FewRelDataset({
        rel: [_inst_from_wire(i) for i in insts]
        for rel, insts in d.items()
    })


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        server: ReplicaServer = self.server.replica_server  # type: ignore
        server.track(self.connection)
        try:
            for line in self.rfile:
                line = line.strip()
                if not line:
                    continue
                req = None
                try:
                    req = json.loads(line)
                    resp = server.dispatch(req)
                except Exception as e:  # noqa: BLE001 — typed -> wire
                    resp = _error_response(e)
                self.wfile.write(
                    (json.dumps(resp) + "\n").encode()
                )
                self.wfile.flush()
                if isinstance(req, dict) and req.get("op") == "bye":
                    return
        finally:
            server.untrack(self.connection)


def _error_response(e: BaseException) -> dict:
    resp = {
        "ok": False, "error": type(e).__name__, "message": str(e),
    }
    for field in ("retry_after_s", "tenant"):
        v = getattr(e, field, None)
        if isinstance(v, (int, float, str)):
            resp[field] = v
    return resp


class ReplicaServer:
    """One engine replica served over a JSON-lines socket. Construct
    with a live ``InferenceEngine``; ``start()`` binds (port 0 = pick a
    free one) and serves on daemon threads; ``address`` is what a
    ``SocketReplica`` connects to."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        self._txns: dict[int, object] = {}
        self._txn_seq = 0
        self._txn_lock = threading.Lock()
        self._active: set = set()      # live handler connections
        self._active_lock = threading.Lock()
        srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        srv.daemon_threads = True
        srv.replica_server = self  # type: ignore[attr-defined]
        self._srv = srv
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "ReplicaServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="replica-server",
        )
        self._thread.start()
        return self

    def track(self, conn) -> None:
        with self._active_lock:
            self._active.add(conn)

    def untrack(self, conn) -> None:
        with self._active_lock:
            self._active.discard(conn)

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        # Sever live handler connections too: a stopped server must look
        # like a DEAD PROCESS to its clients (connection reset on the
        # next call), not like a process that stopped listening while
        # old handler threads keep answering — the supervisor's probe
        # depends on the distinction (ISSUE 15).
        with self._active_lock:
            active, self._active = set(self._active), set()
        for conn in active:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        with self._txn_lock:
            txns, self._txns = dict(self._txns), {}
        for txn in txns.values():
            try:
                txn.abort()
            except Exception:  # noqa: BLE001 — release every serial lock
                pass

    # --- op dispatch ------------------------------------------------------

    def dispatch(self, req: dict) -> dict:
        op = req.get("op")
        eng = self.engine
        if op in ("ping", "bye"):
            return {"ok": True}
        if op == "clock":
            # NTP-style probe (ISSUE 17): stamp receive and send on the
            # SERVER's wall clock; the client supplies t0/t3 and feeds
            # the quadruple to its ClockSync. Two separate stamps on
            # purpose — the processing gap between them is subtracted
            # out of the client's RTT bound.
            t_recv = time.time()
            return {"ok": True, "t_recv": t_recv, "t_send": time.time()}
        if op == "classify":
            from induction_network_on_fewrel_tpu.obs.spans import (
                TraceContext,
            )

            # Rebuild the FULL context: span_id is the router-side
            # originating span, so this replica's top-level serve/*
            # spans parent to it (the cross-process stitch — the module
            # docstring says why id-only is not enough).
            trace = (
                TraceContext(
                    str(req["trace_id"]),
                    span_id=int(req.get("span_id") or 0),
                )
                if req.get("trace_id") else None
            )
            inst = req["instance"]
            if (isinstance(inst, dict)
                    and {"tokens", "head_pos", "tail_pos"} <= set(inst)):
                inst = _inst_from_wire(inst)
            # Any other dict shape (raw FewRel records, token dicts with
            # defaulted positions) passes through VERBATIM to
            # engine._as_instance — transport parity: every instance
            # shape the in-process handle accepts works over the wire.
            fut = eng.submit(
                inst,
                req.get("deadline_s"), tenant=req.get("tenant", "default"),
                trace=trace,
            )
            timeout = (req.get("deadline_s") or eng.default_deadline_s) + 30.0
            return {"ok": True, "verdict": fut.result(timeout=timeout)}
        if op == "register":
            names = eng.register_dataset(
                _dataset_from_wire(req["dataset"]),
                max_classes=req.get("max_classes"),
                tenant=req.get("tenant", "default"),
            )
            return {"ok": True, "classes": list(names)}
        if op == "set_nota_threshold":
            eng.set_nota_threshold(
                req.get("threshold"), tenant=req.get("tenant", "default")
            )
            return {"ok": True}
        if op == "quarantine":
            eng.quarantine_tenant(req["tenant"], reason=req.get("reason", ""))
            return {"ok": True}
        if op == "unquarantine":
            eng.unquarantine_tenant(
                req["tenant"], reason=req.get("reason", "")
            )
            return {"ok": True}
        if op == "drop_tenant":
            eng.registry.drop_tenant(req["tenant"])
            return {"ok": True}
        if op == "has_tenant":
            return {"ok": True,
                    "has": bool(eng.registry.has_tenant(req["tenant"]))}
        if op == "publish_prepare":
            from induction_network_on_fewrel_tpu.serving.registry import (
                load_params,
            )

            tv = req.get("target_version")
            txn = eng.prepare_publish(
                load_params(req["ckpt_dir"]),
                target_version=int(tv) if tv is not None else None,
            )
            with self._txn_lock:
                self._txn_seq += 1
                token = self._txn_seq
                self._txns[token] = txn
            return {"ok": True, "txn": token}
        if op == "publish_commit":
            txn = self._take_txn(req["txn"])
            return {"ok": True, "version": eng.commit_publish(txn)}
        if op == "publish_abort":
            self._take_txn(req["txn"]).abort()
            return {"ok": True}
        if op == "stats":
            return {"ok": True, "stats": eng.stats.snapshot(
                queue_depth=eng.batcher.queue_depth
            )}
        if op == "params_version":
            return {"ok": True, "version": eng.registry.params_version}
        if op == "warmup":
            return {"ok": True, "compiled": eng.warmup()}
        raise ValueError(f"unknown op {op!r}")

    def _take_txn(self, token):
        with self._txn_lock:
            txn = self._txns.pop(int(token), None)
        if txn is None:
            raise ValueError(f"unknown publish transaction {token!r}")
        return txn


class SocketReplica(ReplicaHandle):
    """Client half: the ``ReplicaHandle`` interface over per-thread
    connections. Each calling thread lazily dials its OWN connection
    (the server is a ThreadingTCPServer — one handler per connection),
    so the ``pool_size`` submit workers drive up to that many classifies
    concurrently and the replica's batcher can actually batch across
    them; ``submit`` runs the blocking classify on the pool so the
    router still gets a Future. Requests on one connection are strictly
    request/response, so no per-connection lock is needed — a
    connection is only ever used by the thread that dialed it.

    Transport hardening (ISSUE 15): every call carries a PER-CALL
    deadline (``call_deadline_s`` default; classifies get the request
    deadline plus the server's resolve slack) — a wedged peer raises
    the typed ``TransportTimeout`` (a ``DeadlineExceeded``) instead of
    blocking the calling thread forever, and the connection is dropped
    so the next call re-dials. IDEMPOTENT control-plane calls (ping,
    stats, register, thresholds, quarantine flips — never classify,
    never the token-bearing two-phase publish ops) retry up to
    ``retries`` times on connection errors with deterministic
    exponential backoff. The ``net.partition`` / ``net.drop`` /
    ``net.slow`` chaos points fire here, so every failure arm is
    drillable from one ``--chaos`` spec."""

    # Safe to resend: either read-only or last-write-wins on the server.
    # classify is excluded (a retried request could be answered twice
    # under load); the two-phase publish ops are excluded (the txn
    # token is single-shot server-side — a blind resend can double-
    # commit or hit an already-consumed token).
    _IDEMPOTENT_OPS = frozenset({
        "ping", "stats", "params_version", "warmup", "has_tenant",
        "register", "set_nota_threshold", "quarantine", "unquarantine",
        "drop_tenant", "clock",
    })

    # Probes per NEW connection feeding the link's ClockSync: enough
    # for the median to shrug off one slow probe, cheap enough that a
    # re-dial after a transport error stays sub-millisecond on
    # localhost.
    _CLOCK_PROBES = 3

    def __init__(self, replica_id: str, address: tuple[str, int],
                 pool_size: int = 8, timeout_s: float = 120.0,
                 call_deadline_s: float = 30.0, retries: int = 2,
                 retry_backoff_s: float = 0.05):
        self.replica_id = str(replica_id)
        self._address = address
        self._timeout_s = timeout_s          # connect timeout
        self._call_deadline_s = call_deadline_s
        self._retries = max(int(retries), 0)
        self._retry_backoff_s = retry_backoff_s
        self._tls = threading.local()
        self._conns: list[tuple[socket.socket, object]] = []
        self._conns_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=pool_size,
            thread_name_prefix=f"replica-{replica_id}",
        )
        self._closed = False
        self._clock = ClockSync()   # shared across all dialed threads
        self._connect()   # dial eagerly: fail fast on a bad address

    def _connect(self) -> tuple[socket.socket, object]:
        conn = self._dial()
        if not self._clock_handshake(conn):
            # A probe went UNANSWERED (wedged peer, garbled frame):
            # its late reply would be read as the next RPC's response,
            # and a timed-out buffered reader is poisoned for good
            # (CPython latches _timeout_occurred) — so the stream is
            # unusable either way. Replace it with a fresh one, no
            # probes; the offset estimate keeps whatever samples
            # earlier connections contributed.
            self._drop_conn(conn)
            conn = self._dial()
        return conn

    def _dial(self) -> tuple[socket.socket, object]:
        sock = socket.create_connection(
            self._address, timeout=self._timeout_s
        )
        conn = (sock, sock.makefile("rb"))
        self._tls.conn = conn
        with self._conns_lock:
            self._conns.append(conn)
        return conn

    def _clock_handshake(self, conn) -> bool:
        """Per-connection NTP-style offset probes (ISSUE 17). Writes
        directly on the fresh socket (NOT through ``_call`` — we are
        inside ``_connect`` and must not recurse). Best-effort for the
        ESTIMATE (a refused probe leaves the rolling median as it was)
        but strict about FRAMING: returns False iff a probe went
        unanswered or unparseable, i.e. the request/response stream
        can no longer be trusted and the caller must replace it."""
        sock, rfile = conn
        try:
            sock.settimeout(min(self._call_deadline_s, 5.0))
            for _ in range(self._CLOCK_PROBES):
                t0 = time.time()
                sock.sendall(b'{"op": "clock"}\n')
                line = rfile.readline()
                t3 = time.time()
                if not line:
                    return False      # peer closed mid-handshake
                try:
                    resp = json.loads(line)
                except ValueError:
                    return False      # garbled frame: desynced
                if not resp.get("ok"):
                    # An answered refusal (pre-ISSUE-17 server): the
                    # framing is intact, there is just no clock op.
                    return True
                try:
                    self._clock.observe(
                        t0, float(resp["t_recv"]), float(resp["t_send"]),
                        t3,
                    )
                except (KeyError, TypeError, ValueError):
                    return True       # answered but malformed fields
            return True
        except OSError:
            return False              # timeout/transport fault mid-probe

    @property
    def clock_offset_s(self) -> float:
        """Estimated (replica clock − router clock), seconds — the
        rolling median over this handle's connection handshakes. The
        router stamps it on ``kind="hop"`` records as ``offset_ms``."""
        return self._clock.offset_s()

    def _drop_conn(self, conn) -> None:
        """Invalidate this thread's cached connection: after any
        transport error (broken pipe, timeout mid-response) the socket
        is dead or DESYNCED (a late response line would be read as the
        next request's reply) — the next call from this thread must
        re-dial, which is also what lets a half-open recovery probe
        succeed once a restarted replica process is back."""
        if getattr(self._tls, "conn", None) is conn:
            self._tls.conn = None
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
        sock, rfile = conn
        for closer in (rfile.close, sock.close):
            try:
                closer()
            except OSError:
                pass

    def _call(self, _deadline: float | None = None, **req) -> dict:
        """One request/response with bounded retry: idempotent ops
        resend on CONNECTION errors (never on ``TransportTimeout`` —
        a wedged peer costs a full deadline per attempt, and the
        supervisor/breaker own that diagnosis); everything else
        surfaces the first failure."""
        if self._closed:
            # Local refusal, not a transport fault: retrying a closed
            # handle can never succeed — fail immediately, before the
            # retry loop burns its backoff budget on it.
            raise ConnectionError(f"replica {self.replica_id}: closed")
        op = req.get("op")
        budget = self._retries if op in self._IDEMPOTENT_OPS else 0
        attempt = 0
        while True:
            try:
                return self._call_once(_deadline, req)
            except TransportTimeout:
                raise
            except OSError:
                if attempt >= budget:
                    raise
                attempt += 1
                # Deterministic exponential backoff — no RNG (the
                # chaos/drill replay discipline); per-thread, so no
                # herd to de-synchronize.
                time.sleep(self._retry_backoff_s * (2.0 ** (attempt - 1)))

    def _call_once(self, deadline_s: float | None, req: dict) -> dict:
        if self._closed:
            raise ConnectionError(f"replica {self.replica_id}: closed")
        if chaos_active():
            if chaos_fire("net.partition",
                          replica=self.replica_id) is not None:
                raise ConnectionError(
                    f"replica {self.replica_id}: injected partition"
                )
            slow = chaos_fire("net.slow", replica=self.replica_id)
            if slow is not None:
                time.sleep(float(slow.arg or 0.05))
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = self._connect()
        sock, rfile = conn
        deadline = (self._call_deadline_s if deadline_s is None
                    else deadline_s)
        try:
            sock.settimeout(deadline)
            sock.sendall((json.dumps(req) + "\n").encode())
            if chaos_active() and chaos_fire(
                    "net.drop", replica=self.replica_id) is not None:
                # The response is "lost": the peer may well have acted —
                # exactly why only idempotent ops retry.
                self._drop_conn(conn)
                raise ConnectionError(
                    f"replica {self.replica_id}: injected response drop"
                )
            line = rfile.readline()
        except socket.timeout:
            # The per-call deadline (ISSUE 15): a wedged peer must not
            # block this thread forever. The connection is DESYNCED by
            # construction (a late response line would answer the next
            # request) — drop it; typed so callers and the router's
            # breaker can tell transport wedge (health) from a server-
            # side deadline miss (load).
            self._drop_conn(conn)
            raise TransportTimeout(
                f"replica {self.replica_id}: no response within "
                f"{deadline:.1f}s (per-call deadline)"
            )
        except OSError:
            self._drop_conn(conn)
            raise
        if not line:
            self._drop_conn(conn)
            raise ConnectionError(
                f"replica {self.replica_id}: connection closed"
            )
        resp = json.loads(line)
        if resp.get("ok"):
            return resp
        err, msg = resp.get("error"), resp.get("message", "")
        if err == "Saturated":
            raise Saturated(
                float(resp.get("retry_after_s", 0.05)),
                tenant=resp.get("tenant"),
            )
        if err == "ExecuteError":
            raise ExecuteError(
                str(resp.get("tenant", "?")),
                retry_after_s=float(resp.get("retry_after_s", 0.05)),
                cause=RuntimeError(msg),
            )
        if err == "DeadlineExceeded":
            from induction_network_on_fewrel_tpu.serving.batcher import (
                DeadlineExceeded,
            )

            raise DeadlineExceeded(msg)
        raise RuntimeError(f"replica {self.replica_id}: {err}: {msg}")

    # --- ReplicaHandle ----------------------------------------------------

    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None) -> Future:
        wire = _inst_to_wire(instance) if hasattr(instance, "tokens") \
            else instance
        # The transport read deadline must sit ABOVE the server's
        # resolve window (request deadline + its 30 s result slack) so
        # a server-side deadline miss comes back as the typed wire
        # error, and TransportTimeout fires only when the peer answers
        # NOTHING — a wedged process, the case that is health.
        wire_deadline = (
            deadline_s if deadline_s is not None else self._call_deadline_s
        ) + 35.0
        return self._pool.submit(
            lambda: self._call(
                _deadline=wire_deadline,
                op="classify", instance=wire, deadline_s=deadline_s,
                tenant=tenant,
                trace_id=trace.trace_id if trace is not None else None,
                # The parent link (ISSUE 17): without span_id the
                # replica re-roots the trace and the stitched chain
                # breaks — see the module docstring.
                span_id=trace.span_id if trace is not None else None,
            )["verdict"]
        )

    def ping(self) -> bool:
        return bool(self._call(op="ping").get("ok"))

    def has_tenant(self, tenant) -> bool:
        return bool(self._call(op="has_tenant", tenant=tenant)["has"])

    def register_dataset(self, dataset, tenant, max_classes=None):
        # Registration distills server-side (and may compile on the
        # first shape): same headroom as the publish ops.
        return self._call(
            _deadline=max(self._call_deadline_s, 120.0),
            op="register", dataset=_dataset_to_wire(dataset),
            tenant=tenant, max_classes=max_classes,
        )["classes"]

    def set_nota_threshold(self, threshold, tenant):
        self._call(op="set_nota_threshold", threshold=threshold,
                   tenant=tenant)

    def quarantine_tenant(self, tenant, reason=""):
        self._call(op="quarantine", tenant=tenant, reason=reason)

    def unquarantine_tenant(self, tenant, reason=""):
        self._call(op="unquarantine", tenant=tenant, reason=reason)

    def drop_tenant(self, tenant):
        self._call(op="drop_tenant", tenant=tenant)

    def prepare_publish(self, params=None, ckpt_dir=None,
                        target_version=None):
        if ckpt_dir is None:
            raise ValueError(
                "socket replicas publish from a shared checkpoint "
                "directory (pass ckpt_dir; a raw params tree does not "
                "cross the wire)"
            )
        # Prepare restores + re-distills server-side: give it headroom
        # beyond the default control-plane deadline.
        return self._call(
            _deadline=max(self._call_deadline_s, 120.0),
            op="publish_prepare", ckpt_dir=str(ckpt_dir),
            target_version=target_version,
        )["txn"]

    def commit_publish(self, txn) -> int:
        return int(self._call(
            _deadline=max(self._call_deadline_s, 120.0),
            op="publish_commit", txn=txn,
        )["version"])

    def abort_publish(self, txn) -> None:
        self._call(op="publish_abort", txn=txn)

    @property
    def params_version(self) -> int:
        return int(self._call(op="params_version")["version"])

    def stats_snapshot(self) -> dict:
        return self._call(op="stats")["stats"]

    def warmup(self) -> int:
        # Warmup AOT-compiles every bucket program — the slowest
        # control-plane op by far on a cold process.
        return int(self._call(
            _deadline=max(self._call_deadline_s, 300.0), op="warmup",
        )["compiled"])

    def close(self) -> None:
        if self._closed:
            return
        try:
            self._call(op="bye")   # best-effort, this thread's conn
        except Exception:  # noqa: BLE001 — closing a dead socket is fine
            pass
        self._closed = True
        self._pool.shutdown(wait=False)
        with self._conns_lock:
            conns, self._conns = list(self._conns), []
        for sock, rfile in conns:
            for closer in (rfile.close, sock.close):
                try:
                    closer()
                except OSError:
                    pass
