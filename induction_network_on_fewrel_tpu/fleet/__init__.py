"""Fleet tier (ISSUE 13): multi-replica serving router + control plane.

* ``placement``  — deterministic rendezvous tenant->replica placement
  with replica health states (bounded remap on membership changes).
* ``router``     — the submit front door: placement resolution, fleet-
  level shed fairness, TraceContext propagation across the hop,
  failover to degraded NOTA verdicts, per-replica circuit breaker as
  the health feed. ``InProcessReplica`` is the tier-1/CPU transport.
* ``control``    — tenant lifecycle routed to owners + the
  all-or-nothing fan-out publish over the registry's two-phase
  prepare/commit (any replica's refusal rolls the whole fleet back).
* ``transport``  — the same ``ReplicaHandle`` interface over JSON-lines
  sockets for real multi-process replicas (per-call deadlines, bounded
  idempotent retry, net.* chaos points — ISSUE 15).
* ``journal``    — the write-ahead log of every control-plane op
  (ISSUE 15): CRC-framed records, torn-tail truncation, snapshot
  compaction, deterministic replay; ``FleetRouter.recover(journal)``
  rebuilds the directory bitwise after a crash.
* ``supervisor`` — replica supervision: health probes, restart with
  exponential backoff + deterministic jitter, bounded budget degrading
  to permanent-dead, re-registration + params catch-up on restart.
* ``autoscaler`` — the elasticity policy loop (ISSUE 16): target-band
  occupancy/shed/burn signals with hysteresis + cool-down driving
  journaled scale-out (spawn -> catch-up -> pre-warm -> join) and
  drain-in (drain -> wait-for-inflight -> replace -> retire).
* ``standby``    — the WAL-tailing hot standby (ISSUE 16): read-only
  incremental replay of the primary's journal, single-writer lease
  fencing zombie primaries, seconds-scale promotion with tenants
  served degraded-NOTA (never dropped) during the window.
"""

from induction_network_on_fewrel_tpu.fleet.autoscaler import (
    FleetAutoscaler,
)
from induction_network_on_fewrel_tpu.fleet.control import (
    FleetControl,
    FleetPublishError,
)
from induction_network_on_fewrel_tpu.fleet.journal import (
    FleetJournal,
    JournalError,
    JournalLease,
    JournalState,
    JournalTailer,
)
from induction_network_on_fewrel_tpu.fleet.standby import (
    HotStandby,
)
from induction_network_on_fewrel_tpu.fleet.placement import (
    DEAD,
    DRAINING,
    UP,
    FleetPlacement,
    placement_score,
)
from induction_network_on_fewrel_tpu.fleet.router import (
    FleetRouter,
    InProcessReplica,
    ReplicaHandle,
)
from induction_network_on_fewrel_tpu.fleet.supervisor import (
    ReplicaSupervisor,
)

__all__ = [
    "DEAD",
    "DRAINING",
    "UP",
    "FleetAutoscaler",
    "FleetControl",
    "FleetJournal",
    "FleetPlacement",
    "FleetPublishError",
    "FleetRouter",
    "HotStandby",
    "InProcessReplica",
    "JournalError",
    "JournalLease",
    "JournalState",
    "JournalTailer",
    "ReplicaHandle",
    "ReplicaSupervisor",
    "placement_score",
]
