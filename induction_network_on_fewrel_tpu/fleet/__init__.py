"""Fleet tier (ISSUE 13): multi-replica serving router + control plane.

* ``placement``  — deterministic rendezvous tenant->replica placement
  with replica health states (bounded remap on membership changes).
* ``router``     — the submit front door: placement resolution, fleet-
  level shed fairness, TraceContext propagation across the hop,
  failover to degraded NOTA verdicts, per-replica circuit breaker as
  the health feed. ``InProcessReplica`` is the tier-1/CPU transport.
* ``control``    — tenant lifecycle routed to owners + the
  all-or-nothing fan-out publish over the registry's two-phase
  prepare/commit (any replica's refusal rolls the whole fleet back).
* ``transport``  — the same ``ReplicaHandle`` interface over JSON-lines
  sockets for real multi-process replicas.
"""

from induction_network_on_fewrel_tpu.fleet.control import (
    FleetControl,
    FleetPublishError,
)
from induction_network_on_fewrel_tpu.fleet.placement import (
    DEAD,
    DRAINING,
    UP,
    FleetPlacement,
    placement_score,
)
from induction_network_on_fewrel_tpu.fleet.router import (
    FleetRouter,
    InProcessReplica,
    ReplicaHandle,
)

__all__ = [
    "DEAD",
    "DRAINING",
    "UP",
    "FleetControl",
    "FleetPlacement",
    "FleetPublishError",
    "FleetRouter",
    "InProcessReplica",
    "ReplicaHandle",
    "placement_score",
]
