"""Fleet router: one submit front door over N engine replicas (ISSUE 13
tentpole, piece b).

The router is the data plane of the fleet tier. Per submit it:

1. resolves the tenant's owning replica through the deterministic
   rendezvous placement (fleet/placement.py) — no placement table, no
   coordination; every router instance computes the same owner;
2. enforces **fleet-level shed-load fairness** on top of per-replica
   backpressure: a tenant over its fleet-wide in-flight share sheds at
   the router door (``Saturated(tenant=...)``) before touching any
   replica queue — one hot tenant cannot monopolize the fleet's combined
   admission capacity even when its owner replica still has room;
3. propagates a ``TraceContext`` across the hop: the router's head
   sampler mints the context, opens the ``fleet/route`` span, and hands
   the SAME context to the replica's submit path — the replica-side
   queue/pack/execute/respond segments join the router's trace id, so a
   fleet waterfall reads end to end;
4. **fails over**: a replica marked dead (its per-replica circuit
   breaker — the existing serving/breaker.CircuitBreaker keyed by
   replica id — opening on consecutive launch failures, or the
   ``fleet.replica_kill`` chaos point) drops out of placement; its
   tenants' traffic gets immediate degraded-mode NOTA verdicts (the
   honest "I cannot place this" answer, zero device time) until the
   control plane re-places them onto their new rendezvous owners
   (fleet/control.FleetControl.replace_tenants).

Replica transports: ``InProcessReplica`` wraps an ``InferenceEngine`` in
this process (tier-1 / CPU drills); ``fleet/transport.py`` puts the SAME
``ReplicaHandle`` interface over a JSON-lines socket for real
multi-process runs. The router is transport-agnostic by construction.

Telemetry: ``kind="fleet"`` records (utils/metrics.KNOWN_KINDS schema
doc) — one aggregate record per emit, one per-replica record (``replica``
str field) restating that replica's serving counters, and event records
(``event="fanout_publish"`` / ``"replica_dead"`` / ``"replace"`` ...)
for control-plane actions. Replica-death containment also emits
``kind="fault"`` ``action="replica_dead"`` records, which the health
watchdog latches as once-per-replica CRITICALs (re-armed by recovery).
ISSUE 17 added ``kind="hop"`` records — one per SAMPLED routed request
with router-side segments (route/queue/wire/remote/respond) tiling the
fleet-level latency exactly (see ``_emit_hop``) — and the labeled-gauge
fleet rollup (``bind_registry``): per-replica qps/occupancy/percentile/
breaker gauges in one metrics.prom scrape.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future

from induction_network_on_fewrel_tpu.obs.chaos import (
    chaos_active,
    chaos_fire,
)
from induction_network_on_fewrel_tpu.obs.spans import TraceSampler, get_tracker
from induction_network_on_fewrel_tpu.fleet.placement import (
    DEAD,
    DRAINING,
    UP,
    FleetPlacement,
)
from induction_network_on_fewrel_tpu.serving.batcher import Saturated
from induction_network_on_fewrel_tpu.serving.geometry import (
    DEFAULT_TIERS,
    tier_for,
)


class ReplicaHandle:
    """The transport-agnostic replica interface the router and control
    plane speak. ``InProcessReplica`` (below) backs it with an engine in
    this process; ``fleet/transport.SocketReplica`` backs it with a
    JSON-lines socket to another process. Every method is synchronous
    except ``submit``, which returns a Future."""

    replica_id: str

    # data plane
    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None) -> Future:
        raise NotImplementedError

    # control plane
    def register_dataset(self, dataset, tenant, max_classes=None):
        raise NotImplementedError

    def has_tenant(self, tenant) -> bool:
        raise NotImplementedError

    def set_nota_threshold(self, threshold, tenant):
        raise NotImplementedError

    def quarantine_tenant(self, tenant, reason=""):
        raise NotImplementedError

    def unquarantine_tenant(self, tenant, reason=""):
        raise NotImplementedError

    def drop_tenant(self, tenant):
        raise NotImplementedError

    # two-phase publish (fleet fan-out; target_version = the recovery
    # catch-up spelling, pinning the generation the commit lands at)
    def prepare_publish(self, params=None, ckpt_dir=None,
                        target_version=None):
        raise NotImplementedError

    def commit_publish(self, txn) -> int:
        raise NotImplementedError

    def abort_publish(self, txn) -> None:
        raise NotImplementedError

    # observability / lifecycle
    def ping(self) -> bool:
        """Cheap liveness probe (the supervisor's health loop). The
        transport raises (ConnectionError/TransportTimeout) when the
        peer is gone or wedged; an in-process replica is alive by
        construction."""
        return True

    @property
    def clock_offset_s(self) -> float:
        """Estimated (replica clock − router clock) in seconds — 0.0
        for an in-process replica (one clock, by construction). The
        socket transport estimates it per connection via the NTP-style
        handshake (fleet/transport.ClockSync, ISSUE 17); the router
        stamps it on ``kind="hop"`` records as ``offset_ms``."""
        return 0.0

    @property
    def params_version(self) -> int:
        raise NotImplementedError

    def stats_snapshot(self) -> dict:
        raise NotImplementedError

    def warmup(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class InProcessReplica(ReplicaHandle):
    """One engine replica in this process — the tier-1/CPU transport.
    The engine keeps its own batcher worker, breaker, stats, and
    registry; the handle only adapts the interface."""

    def __init__(self, replica_id: str, engine):
        self.replica_id = str(replica_id)
        self.engine = engine

    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None) -> Future:
        return self.engine.submit(
            instance, deadline_s, tenant=tenant, trace=trace
        )

    def register_dataset(self, dataset, tenant, max_classes=None):
        return self.engine.register_dataset(
            dataset, max_classes=max_classes, tenant=tenant
        )

    def has_tenant(self, tenant) -> bool:
        return self.engine.registry.has_tenant(tenant)

    def set_nota_threshold(self, threshold, tenant):
        self.engine.set_nota_threshold(threshold, tenant=tenant)

    def quarantine_tenant(self, tenant, reason=""):
        self.engine.quarantine_tenant(tenant, reason=reason)

    def unquarantine_tenant(self, tenant, reason=""):
        self.engine.unquarantine_tenant(tenant, reason=reason)

    def drop_tenant(self, tenant):
        self.engine.registry.drop_tenant(tenant)

    def prepare_publish(self, params=None, ckpt_dir=None,
                        target_version=None):
        if params is None:
            if ckpt_dir is None:
                raise ValueError("prepare_publish needs params or ckpt_dir")
            from induction_network_on_fewrel_tpu.serving.registry import (
                load_params,
            )

            params = load_params(ckpt_dir)
        return self.engine.prepare_publish(
            params, target_version=target_version
        )

    def commit_publish(self, txn) -> int:
        return self.engine.commit_publish(txn)

    def abort_publish(self, txn) -> None:
        txn.abort()

    @property
    def params_version(self) -> int:
        return self.engine.registry.params_version

    def stats_snapshot(self) -> dict:
        return self.engine.stats.snapshot(
            queue_depth=self.engine.batcher.queue_depth
        )

    def warmup(self) -> int:
        return self.engine.warmup()

    def close(self) -> None:
        self.engine.close()


def drive_tenant_state(handle, tenant: str, entry: "_TenantEntry",
                       reason: str) -> None:
    """ONE home for making a replica serve-ready for one directory
    tenant: register the support source, then carry the NOTA threshold
    and quarantine flag. Shared by failover re-placement
    (control.replace_tenants), cold-start recovery (router.recover),
    and supervised restart (supervisor._adopt) — three hand-mirrored
    copies of this block had already started to drift."""
    handle.register_dataset(
        entry.source, tenant, max_classes=entry.max_classes
    )
    if entry.nota_threshold is not None:
        handle.set_nota_threshold(entry.nota_threshold, tenant)
    if entry.quarantined:
        handle.quarantine_tenant(tenant, reason=reason)


class _TenantEntry:
    """The router's per-tenant directory row: where the tenant is
    REGISTERED (vs where placement currently points — a mismatch is a
    pending re-placement served degraded), plus everything needed to
    re-register it on a new owner after failover: the support source,
    the NOTA threshold, the quarantine flag."""

    __slots__ = ("owner", "source", "max_classes", "nota_threshold",
                 "quarantined")

    def __init__(self, owner, source, max_classes=None):
        self.owner = owner
        self.source = source
        self.max_classes = max_classes
        self.nota_threshold = None
        self.quarantined = False


class FleetRouter:
    """Submit front door + replica health + the fleet tenant directory.

    ``fleet_share`` bounds one tenant's fleet-wide IN-FLIGHT requests to
    that fraction of the fleet's combined queue capacity (sum of replica
    ``max_queue_depth``). Like the per-replica tenant share it binds
    only once a second tenant has submitted — a single-tenant fleet
    keeps full capacity.

    ``resident_budget_bytes`` (ISSUE 18): per-replica budget for
    resident class-vector bytes. Placement capacity is derived from
    BYTES, not tenant count — an int8 tenant is ~4x cheaper than its
    f32 twin, so the same replica holds ~4x the tenants. ``None``
    (default) keeps the pre-quantization behavior: unbounded residency,
    queue depth is the only capacity signal.

    ``tier_spread`` (ISSUE 19): N-tier-weighted rendezvous placement.
    When > 0, each tier's tenants concentrate onto that many "home"
    replicas (placement module doc) so no replica warms every tier's
    program family; ``tiers`` is the ladder tenant class counts map
    through (must match the replicas' engine ladder — serve.py wires
    both from the same resolved policy). 0 (default) = tier-blind.
    """

    def __init__(
        self,
        replicas: dict[str, ReplicaHandle],
        logger=None,
        breaker=None,
        fleet_share: float = 0.5,
        trace_sample: float = 0.0,
        queue_capacity_per_replica: int = 64,
        resident_budget_bytes: float | None = None,
        tier_spread: int = 0,
        tiers: tuple[int, ...] | None = DEFAULT_TIERS,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        if resident_budget_bytes is not None and resident_budget_bytes <= 0:
            raise ValueError(
                f"resident_budget_bytes must be positive, got "
                f"{resident_budget_bytes}"
            )
        self.replicas: dict[str, ReplicaHandle] = dict(replicas)
        self.placement = FleetPlacement(self.replicas)
        self._logger = logger
        self._tracer = TraceSampler(trace_sample)
        self.fleet_share = fleet_share
        self._capacity_per_replica = queue_capacity_per_replica
        self.resident_budget_bytes = resident_budget_bytes
        if tier_spread < 0:
            raise ValueError(
                f"tier_spread must be >= 0, got {tier_spread}"
            )
        self.tier_spread = tier_spread
        self.tiers = tuple(tiers) if tiers else None
        # Per-replica circuit breaker: serving/breaker.CircuitBreaker
        # keyed by REPLICA id — consecutive forwarded-launch failures
        # open it, the open transition marks the replica dead in
        # placement (the ISSUE 13 health feed), a later closed
        # transition marks it back up.
        self.breaker = breaker
        if breaker is not None:
            breaker.on_transition = self._on_breaker_transition
        self._lock = threading.Lock()
        self.directory: dict[str, _TenantEntry] = {}
        self._inflight: dict[str, int] = {}
        self._seen: set[str] = set()
        # Counters (all under _lock).
        self.submitted = 0
        self.routed: dict[str, int] = {r: 0 for r in self.replicas}
        self.degraded_served = 0      # failover NOTA verdicts from HERE
        self.shed = 0                 # fleet-share sheds at the door
        self.replica_deaths = 0
        self.replaced = 0             # tenants re-registered after a
        #                               membership/health change (churn)
        self._emit_step = 0
        # Fleet rollup state (ISSUE 17): per-replica (time, served) at
        # the last emit, for the qps column; registry families when
        # bind_registry() was called (default unbound — no new work on
        # the emit path).
        self._t0 = time.monotonic()
        self._prev_emit: dict[str, tuple[float, float]] = {}
        self._families: dict[str, object] = {}
        self._bound_registry = None
        self._bound_fns: list[tuple[str, object]] = []

    # --- capacity / fairness ----------------------------------------------

    def _fleet_capacity(self) -> int:
        n_live = max(1, len(self.placement.live()))
        return n_live * self._capacity_per_replica

    def _tenant_cap(self) -> int:
        return max(1, int(self._fleet_capacity() * self.fleet_share))

    def replica_resident_bytes(self, rid: str) -> float:
        """Bytes of resident class vectors on one replica (0.0 when the
        replica is dead or predates the resident_bytes gauge)."""
        try:
            snap = self.replicas[rid].stats_snapshot()
        except Exception:  # noqa: BLE001 — dead replica: no residency
            return 0.0
        return float(snap.get("resident_bytes", 0.0))

    # --- N-tier-weighted placement (ISSUE 19) ------------------------------

    def tier_of_source(self, source, max_classes=None) -> int | None:
        """The N-tier a tenant's support source lands on, or None when
        tier-weighted placement is off / the source is unknown (a
        params-only recovery entry places tier-blind — correct, just
        unweighted for that tenant)."""
        if self.tier_spread <= 0 or source is None:
            return None
        n = len(source.rel_names)
        if max_classes is not None:
            n = min(n, int(max_classes))
        return tier_for(n, self.tiers)

    def place_tenant(self, tenant: str, entry=None) -> str | None:
        """ONE placement spelling for every router/control call site:
        rendezvous with the tenant's N-tier weight when the directory
        (or the caller-supplied ``entry``) knows its source. Register,
        submit, failover, and recovery MUST all resolve through the
        same function — two sites disagreeing on the tier weight would
        read as a permanent pending re-placement."""
        if entry is None:
            entry = self.directory.get(tenant)
        tier = (
            self.tier_of_source(entry.source, entry.max_classes)
            if entry is not None else None
        )
        return self.placement.place(
            tenant, tier=tier, tier_spread=self.tier_spread
        )

    # --- data plane -------------------------------------------------------

    def submit(self, instance, deadline_s=None, tenant="default") -> Future:
        """Route one query to its owning replica. Raises ``ValueError``
        for unregistered tenants, ``Saturated`` at the fleet-share bound
        or with no live replica; returns the replica's Future (or an
        immediately-resolved degraded verdict during failover)."""
        entry = self.directory.get(tenant)
        if entry is None:
            raise ValueError(
                f"unknown tenant {tenant!r} — register it through the "
                f"fleet control plane first"
            )
        if chaos_active():
            owner_now = entry.owner
            if owner_now is not None and chaos_fire(
                "fleet.replica_kill", replica=owner_now,
                step=self.submitted,
            ) is not None:
                self.mark_replica_dead(owner_now, reason="chaos")
        target = self.place_tenant(tenant, entry)
        if target is None:
            raise Saturated(1.0)   # no live replica: back off, retry
        with self._lock:
            self.submitted += 1
            self._seen.add(tenant)
            if (len(self._seen) > 1
                    and self._inflight.get(tenant, 0) >= self._tenant_cap()):
                self.shed += 1
                raise Saturated(0.05, tenant=tenant)
            # RESERVE the in-flight slot under the SAME lock as the cap
            # check — check-then-act across two lock sections would let
            # N concurrent submitters all pass the check at cap-1 and
            # overshoot the share by the caller concurrency. Every exit
            # below that does not hand back a replica future releases
            # the reservation.
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        reserved = True
        probe = False
        try:
            if entry.owner != target:
                if self._admit_recovery_probe(entry.owner):
                    probe = True
                    # The owner's breaker OPEN window elapsed: route THIS
                    # request to it as the half-open recovery probe instead
                    # of a degraded verdict. Success closes the breaker,
                    # whose closed transition revives the replica in
                    # placement — a transient-failure replica heals itself
                    # without operator re-placement; failure re-opens the
                    # window and the next requests go back to degraded.
                    target = entry.owner
                elif (entry.owner in self.replicas
                      and self.placement.state(entry.owner)
                      not in (None, DEAD)):
                    # A MEMBERSHIP change (replica add / drain) moved
                    # the tenant's rendezvous placement while its
                    # registered owner is still alive and holds the
                    # support set: keep serving CORRECT verdicts from
                    # the registration until control.replace_tenants()
                    # moves it. Degraded NOTA is reserved for a dead
                    # owner — the case with nothing left to ask.
                    target = entry.owner
                else:
                    # Pending re-placement with the owner DEAD (or
                    # removed): honest degraded NOTA, zero device time,
                    # until control.replace_tenants() re-registers.
                    return self._degraded_future(tenant)
            trace = self._tracer.maybe_trace()
            handle = self.replicas[target]
            hop = None
            try:
                if trace is not None:
                    # Hop tiling stamps (ISSUE 17): t0 at mint, t1 once
                    # the fleet/route span is open (route_ms = span +
                    # placement bookkeeping), t2 once handle.submit
                    # returned (queue_ms = local enqueue: the socket
                    # transport's pool hand-off or the engine's
                    # admission). The done callback adds t3/t4 and
                    # splits t3−t2 into wire_ms + remote_ms using the
                    # replica-reported total (_emit_hop). Stamps exist
                    # ONLY on the sampled path — rate 0 stays
                    # allocation-free.
                    t0 = time.monotonic()
                    tracker = get_tracker()
                    with tracker.trace(trace):
                        with tracker.span("fleet/route", xplane=False,
                                          tenant=tenant, replica=target):
                            t1 = time.monotonic()
                            fut = handle.submit(
                                instance, deadline_s, tenant=tenant,
                                trace=trace,
                            )
                    hop = (trace, t0, t1, time.monotonic())
                else:
                    fut = handle.submit(instance, deadline_s, tenant=tenant)
            except Saturated:
                # Per-replica backpressure re-raises as-is — EXCEPT on
                # an admitted recovery probe, whose slot MUST record an
                # outcome or the breaker wedges half-open with no path
                # back. A saturated replica answered, but "queue full"
                # is not probe success: record failure (re-opens the
                # window; the next window probes again).
                if probe and self.breaker is not None:
                    self.breaker.record_failure(target)
                raise
            except BaseException:
                # A transport/submit failure (socket down, closed batcher)
                # counts against the replica's breaker — enough of them
                # opens it and placement routes around. Same owner guard
                # as _on_done: a straggler that read the OLD owner just
                # before replace_tenants() flipped it (the replica then
                # refuses the dropped tenant synchronously) must not
                # count against the healthy replica — except a probe,
                # whose consumed slot must always record.
                if self.breaker is not None and (
                        probe or entry.owner == target):
                    self.breaker.record_failure(target)
                raise
            with self._lock:
                self.routed[target] = self.routed.get(target, 0) + 1
            # Hand the reservation to the done callback BEFORE attaching
            # it — an already-resolved future fires the callback
            # synchronously, and the finally below must not release a
            # second time.
            reserved = False
            fut.add_done_callback(
                lambda f, t=tenant, r=target, p=probe, h=hop:
                    self._on_done(f, t, r, probe=p, hop=h)
            )
            return fut
        finally:
            if reserved:
                self._release_inflight(tenant)

    def classify(self, instance, deadline_s=None, tenant="default") -> dict:
        fut = self.submit(instance, deadline_s, tenant=tenant)
        return fut.result(timeout=(deadline_s or 30.0) + 30.0)

    def _release_inflight(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 1) - 1
            if n <= 0:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = n

    def _on_done(self, fut: Future, tenant: str, replica: str,
                 probe: bool = False, hop=None) -> None:
        self._release_inflight(tenant)
        if hop is not None:
            self._emit_hop(fut, tenant, replica, hop)
        if self.breaker is None:
            return
        exc = fut.exception()
        if exc is None:
            self.breaker.record_success(replica)
            return
        if probe:
            # An admitted half-open probe consumed the breaker's single
            # probe slot — EVERY failed outcome (deadline miss included)
            # must be recorded, else the breaker wedges half-open
            # forever and the replica can never be probed again.
            self.breaker.record_failure(replica)
            return
        from induction_network_on_fewrel_tpu.serving.batcher import (
            DeadlineExceeded,
            ExecuteError,
            TransportTimeout,
        )

        # ExecuteError = the replica's launch failed; OSError (incl.
        # ConnectionError from a dead SocketReplica process — raised
        # in the transport's pool thread, so it surfaces HERE via
        # the future, never via submit's synchronous except) = the
        # replica itself is unreachable. Both count. Deadline
        # misses and Saturated do not — they are load, not health.
        # DeadlineExceeded needs saying EXPLICITLY: TimeoutError IS an
        # OSError subclass, so without the carve-out a loaded replica
        # expiring requests would read as replica death and cascade a
        # false failover (ISSUE 15). TransportTimeout is the one
        # deadline that DOES count — a wedged peer answering nothing
        # within the per-call deadline is health, not load.
        if isinstance(exc, DeadlineExceeded) \
                and not isinstance(exc, TransportTimeout):
            return
        if isinstance(exc, (ExecuteError, OSError)):
            # Attribute the failure only while ``replica`` is still
            # the tenant's REGISTERED owner: after replace_tenants()
            # flips the registration, requests still queued on the
            # old (healthy) replica fail typed-retryable when its
            # tenant state is dropped — those stragglers must not
            # open the old replica's breaker and cascade a false
            # replica death.
            entry = self.directory.get(tenant)
            if entry is not None and entry.owner == replica:
                self.breaker.record_failure(replica)

    def _emit_hop(self, fut: Future, tenant: str, replica: str,
                  hop: tuple) -> None:
        """One ``kind="hop"`` record per SAMPLED routed request (ISSUE
        17 tentpole): router-side segments that tile the measured
        fleet-level latency EXACTLY — every ``*_ms`` comes off the same
        monotonic stamps, the PR 8 discipline — with
        ``hop_ms = router_ms − remote_ms``: what the fleet hop added on
        top of the replica's own measured total. ``remote_ms`` is the
        replica's verdict ``latency_ms`` (two DURATIONS subtract with
        no clock alignment needed), clamped into [0, t3−t2] so a
        replica whose reported total exceeds the observed round-trip
        (clock step mid-request) cannot drive ``wire_ms`` negative.
        ``offset_ms`` is the transport's clock-offset estimate, for
        aligning replica-side ABSOLUTE timestamps in
        tools/fleet_report.py. Failed futures emit nothing — their
        story is the fault/breaker records."""
        if self._logger is None or fut.cancelled() \
                or fut.exception() is not None:
            return
        trace, t0, t1, t2 = hop
        verdict = fut.result()
        if not isinstance(verdict, dict):
            return
        t3 = time.monotonic()
        lat = verdict.get("latency_ms")
        remote_s = (
            min(max(float(lat) / 1e3, 0.0), max(t3 - t2, 0.0))
            if isinstance(lat, (int, float)) else 0.0
        )
        offset_s = float(
            getattr(self.replicas.get(replica), "clock_offset_s", 0.0)
            or 0.0
        )
        t4 = time.monotonic()
        self._logger.log(
            self.submitted, kind="hop",
            trace_id=trace.trace_id, tenant=tenant, replica=replica,
            route_ms=round((t1 - t0) * 1e3, 3),
            queue_ms=round((t2 - t1) * 1e3, 3),
            wire_ms=round((t3 - t2 - remote_s) * 1e3, 3),
            remote_ms=round(remote_s * 1e3, 3),
            respond_ms=round((t4 - t3) * 1e3, 3),
            router_ms=round((t4 - t0) * 1e3, 3),
            hop_ms=round((t4 - t0 - remote_s) * 1e3, 3),
            offset_ms=round(offset_s * 1e3, 3),
        )

    def _degraded_future(self, tenant: str) -> Future:
        """An immediately-resolved degraded NOTA verdict — the fleet's
        failover answer while the tenant awaits re-placement. The shape
        is serving/engine.degraded_verdict (ONE home with the engine's
        quarantine path); ``failover=True`` lets clients (and the
        quality stream, which excludes degraded verdicts) tell
        router-side failover from a replica-side quarantine."""
        from induction_network_on_fewrel_tpu.serving.engine import (
            degraded_verdict,
        )

        fut: Future = Future()
        fut.set_result(degraded_verdict(tenant, failover=True))
        with self._lock:
            self.degraded_served += 1
        return fut

    # --- replica health ---------------------------------------------------

    def _admit_recovery_probe(self, replica: str | None) -> bool:
        """True when ``replica`` is a breaker-opened DEAD replica whose
        open window has elapsed and the breaker admits a half-open
        probe. Chaos/operator-killed replicas (breaker still closed)
        never probe — their recovery path is revive + re-placement, and
        auto-routing traffic back would defeat the kill drill's
        semantics."""
        if (self.breaker is None or replica is None
                or replica not in self.replicas
                or self.placement.state(replica) != DEAD):
            return False
        from induction_network_on_fewrel_tpu.serving.breaker import (
            CLOSED as BRK_CLOSED,
        )

        if self.breaker.state(replica) == BRK_CLOSED:
            return False
        return self.breaker.admit(replica) is None

    def _on_breaker_transition(self, replica, frm, to, failures, now):
        """The per-replica breaker IS the health feed: open -> dead
        (placement routes around, tenants fail over), closed -> up.
        Also mirrored as kind='fault' action='breaker' records so the
        existing watchdog latch (breaker_open, keyed by the 'tenant'
        field = replica id here) applies unchanged."""
        if self._logger is not None:
            self._logger.log(
                self.submitted, kind="fault", action="breaker",
                tenant=f"replica:{replica}", **{"from": frm, "to": to},
                failures=float(failures),
            )
        if to == "open":
            self.mark_replica_dead(
                replica, reason=f"breaker open after {failures} failures"
            )
        elif to == "closed" and self.placement.state(replica) == DEAD:
            self.revive_replica(replica, reason="breaker closed")

    def mark_replica_dead(self, replica: str, reason: str = "") -> None:
        if self.placement.state(replica) == DEAD:
            return
        self.placement.set_state(replica, DEAD)
        with self._lock:
            self.replica_deaths += 1
            affected = sum(
                1 for e in self.directory.values() if e.owner == replica
            )
        if self._logger is not None:
            self._logger.log(
                self.submitted, kind="fault", action="replica_dead",
                replica=replica, reason=reason or "operator",
                tenants=float(affected),
            )

    def revive_replica(self, replica: str, reason: str = "") -> None:
        if self.placement.state(replica) == UP:
            return
        self.placement.set_state(replica, UP)
        # Stale-params check: a replica that missed a fan-out publish
        # while dead (control._publish_targets excludes DEAD replicas)
        # re-enters placement at an OLD generation — surfaced LOUDLY
        # here at revive time, not silently discovered at the next
        # fan-out's version-skew record. The control plane holds no
        # params to auto-re-publish with (RUNBOOK §18: revive →
        # re-publish is the operator recipe); this record is the
        # enforcement hook.
        if self._logger is not None:
            try:
                mine = self.replicas[replica].params_version
                peers = [
                    h.params_version
                    for rid, h in self.replicas.items()
                    if rid != replica and self.placement.state(rid) == UP
                ]
            except Exception:  # noqa: BLE001 — an unreachable peer
                mine, peers = None, []
            if mine is not None and peers and mine < max(peers):
                self._logger.log(
                    self.submitted, kind="fault",
                    action="replica_stale_params", replica=replica,
                    params_version=float(mine),
                    fleet_version=float(max(peers)),
                )
            self._logger.log(
                self.submitted, kind="fault", action="replica_recover",
                replica=replica, reason=reason or "operator",
            )

    def drain_replica(self, replica: str) -> None:
        self.placement.set_state(replica, DRAINING)

    def remove_replica(self, replica: str) -> None:
        """Retire a replica out of the fleet for good (the autoscaler's
        drain-in endpoint): drop it from placement and the handle map.
        The caller owns the safety argument — drained first, tenants
        moved off (``replace_tenants``), in-flight work waited out."""
        if replica not in self.replicas:
            raise KeyError(f"unknown replica {replica!r}")
        self.placement.remove_replica(replica)
        with self._lock:
            self.replicas.pop(replica, None)
            self.routed.pop(replica, None)
        if self._logger is not None:
            self._logger.log(
                self.submitted, kind="fleet", event="replica_retire",
                replica=replica, replicas=float(len(self.replicas)),
            )

    def pending_failover(self) -> tuple[str, ...]:
        """Tenants whose registered owner differs from their current
        placement — the set ``control.replace_tenants()`` will move."""
        # Snapshot under _lock: the control plane inserts directory
        # entries (register_tenant) from other threads, and a CPython
        # dict raises mid-iteration when it grows underneath us.
        with self._lock:
            entries = list(self.directory.items())
        by_tenant = dict(entries)
        owners = self.placement.owners(
            [t for t, _ in entries],
            tier_of=lambda t: self.tier_of_source(
                by_tenant[t].source, by_tenant[t].max_classes
            ),
            tier_spread=self.tier_spread,
        )
        return tuple(sorted(
            t for t, e in entries
            if owners.get(t) is not None and owners[t] != e.owner
        ))

    # --- cold-start recovery (ISSUE 15) -----------------------------------

    def recover(self, journal, catch_up: bool = True,
                state=None) -> dict:
        """Rebuild the fleet's control-plane state from a
        ``fleet/journal.FleetJournal`` after a router crash/restart.

        Deterministic by construction: the journal's materialized state
        is a pure fold of the op sequence and placement is a pure
        rendezvous function, so the rebuilt directory is BITWISE the
        pre-crash directory (owner, support source, NOTA threshold,
        quarantine flag per tenant — ``directory_view()`` is the
        canonical comparison form). Three repairs happen along the way:

        * journaled replica DRAIN states re-apply to placement;
        * a tenant whose owning replica lost its registry (a restarted
          replica process answering ``has_tenant`` False) is
          RE-REGISTERED there — source, threshold, and quarantine flag
          re-driven from the journal;
        * with ``catch_up``, every live replica answering at a stale
          params_version is caught up by re-driving the journaled
          publish at the committed generation
          (``catch_up_replica``) — ``replica_stale_params`` turned
          from a warning into a repair.

        Emits one ``kind="fault"`` ``action="recovered"`` summary
        record; returns the summary dict (tenants / reregistered /
        caught_up / journal_records / snapshot_seq)."""
        from induction_network_on_fewrel_tpu.fleet.transport import (
            _dataset_from_wire,
        )

        # ``state`` lets a caller that already materialized the journal
        # (serve.py startup reads the adapt latches from the same
        # state) avoid a second full WAL parse.
        if state is None:
            state = journal.materialize()
        for rid in sorted(state.replicas):
            if rid in self.replicas and state.replicas[rid] == "draining":
                self.placement.set_state(rid, DRAINING)
        reregistered = 0
        rewarmed: set[str] = set()
        lost: list[str] = []
        unreachable: set[str] = set()
        for tenant in sorted(state.tenants):
            meta = state.tenants[tenant]
            source = (
                _dataset_from_wire(meta["source"])
                if meta.get("source") else None
            )
            # Source BEFORE placement: the rebuilt owner must resolve
            # with the same N-tier weight register_tenant used, or a
            # clean recovery would read as a pending re-placement.
            probe_entry = _TenantEntry(
                None, source, max_classes=meta.get("max_classes")
            )
            owner = self.place_tenant(tenant, probe_entry)
            entry = _TenantEntry(
                owner, source, max_classes=meta.get("max_classes")
            )
            entry.nota_threshold = meta.get("nota_threshold")
            entry.quarantined = bool(meta.get("quarantined"))
            if owner is None or source is None:
                # No live replica to place on (traffic sheds typed until
                # one revives) or a params-only source with nothing to
                # re-register from — the DIRECTORY entry still recovers
                # either way: zero tenant loss.
                lost.append(tenant)
            elif owner in unreachable:
                # Already probed and failed: do not burn another
                # transport deadline per tenant on a peer we know is
                # down — its rows recover, the supervisor owns the rest.
                pass
            else:
                # Per-tenant containment: ONE unreachable replica (a
                # socket peer still down at cold start) must not abort
                # the whole recovery — its directory rows recover, its
                # registration waits for the supervisor's restart path,
                # and every other tenant recovers fully.
                try:
                    if not self.replicas[owner].has_tenant(tenant):
                        drive_tenant_state(
                            self.replicas[owner], tenant, entry,
                            reason="journal replay",
                        )
                        reregistered += 1
                        rewarmed.add(owner)
                except Exception:  # noqa: BLE001 — supervisor's job now
                    unreachable.add(owner)
            with self._lock:
                self.directory[tenant] = entry
        for rid in sorted(unreachable):
            self.mark_replica_dead(
                rid, reason="unreachable during recovery"
            )
        # A replica that lost its registry also lost its AOT-compiled
        # query programs: warm it BEFORE it takes traffic, so the first
        # post-recovery query is not a steady-state recompile (the
        # zero-recompile invariant survives the crash).
        for rid in sorted(rewarmed):
            try:
                self.replicas[rid].warmup()
            except Exception:  # noqa: BLE001 — warmup is an optimization
                pass
        caught_up = (
            self.catch_up_replicas(state.committed) if catch_up else []
        )
        summary = {
            "tenants": len(state.tenants),
            "reregistered": reregistered,
            "unplaceable": len(lost),
            "unreachable": len(unreachable),
            "caught_up": len(caught_up),
            "params_version": int(state.committed.get(
                "params_version", 0
            )),
            "journal_records": int(state.applied),
            "snapshot_seq": int(journal.snapshot_seq),
        }
        if self._logger is not None:
            self._logger.log(
                self.submitted, kind="fault", action="recovered",
                **{k: float(v) for k, v in summary.items()},
            )
        return summary

    def catch_up_replicas(self, committed: dict) -> list[dict]:
        """Reconcile every UP replica to the journaled committed
        params_version; returns one row per replica actually caught up
        (also emitted as ``kind="fault"`` ``action="catchup"``)."""
        rows = []
        for rid in sorted(self.replicas):
            if self.placement.state(rid) != UP:
                continue
            try:
                row = self.catch_up_replica(rid, committed)
            except Exception as e:  # noqa: BLE001 — one replica's
                # failed catch-up (unreachable peer, refused restore)
                # must not abort the others': it stays stale, loudly.
                if self._logger is not None:
                    self._logger.log(
                        self.submitted, kind="fault",
                        action="replica_stale_params", replica=rid,
                        reason=f"catch-up failed: "
                               f"{type(e).__name__}: {e}",
                    )
                continue
            if row is not None:
                rows.append(row)
        return rows

    def catch_up_replica(self, rid: str, committed: dict) -> dict | None:
        """Re-drive the journaled publish on ONE stale replica: prepare
        at the committed ckpt path pinned to the committed
        params_version, then commit — the registry's zero-recompile
        hot-swap, so steady-state traffic on every other tenant is
        untouched. Returns the catch-up row, or None when the replica
        is already current (or unreachable — the supervisor's problem,
        not this path's)."""
        target = int(committed.get("params_version", 0) or 0)
        ckpt_dir = committed.get("ckpt_dir")
        handle = self.replicas[rid]
        try:
            mine = int(handle.params_version)
        except Exception:  # noqa: BLE001 — unreachable = supervisor's job
            return None
        if target <= 0 or mine >= target:
            return None
        if not ckpt_dir:
            # A params-only publish left no re-drivable artifact: the
            # skew is surfaced LOUDLY (the pre-ISSUE-15 warning), the
            # repair needs an operator re-publish.
            if self._logger is not None:
                self._logger.log(
                    self.submitted, kind="fault",
                    action="replica_stale_params", replica=rid,
                    params_version=float(mine),
                    fleet_version=float(target),
                )
            return None
        txn = handle.prepare_publish(
            ckpt_dir=ckpt_dir, target_version=target
        )
        version = handle.commit_publish(txn)
        # A committed publish CLEARS engine-level quarantine by design
        # (fresh verified weights replace the suspect vectors — ISSUE
        # 12). The catch-up re-drives an OLD publish, and the journal's
        # quarantine ops came AFTER it: re-assert the directory's
        # quarantine flags so replay order wins, not re-application
        # order.
        with self._lock:
            held = [t for t, e in self.directory.items()
                    if e.owner == rid and e.quarantined]
        for tenant in held:
            try:
                handle.quarantine_tenant(
                    tenant, reason="journal replay (post catch-up)"
                )
            except Exception:  # noqa: BLE001 — a tenant the replica
                pass           # does not hold yet has nothing to clear
        row = {"replica": rid, "from_version": mine,
               "to_version": int(version)}
        if self._logger is not None:
            self._logger.log(
                self.submitted, kind="fault", action="catchup",
                replica=rid, from_version=float(mine),
                to_version=float(version),
            )
        return row

    def directory_view(self) -> dict:
        """The tenant directory in canonical, JSON-ready form — the
        bitwise-comparison artifact the recovery drill equates across a
        kill/restart (support sources compare by their wire-form
        digest, not object identity)."""
        import hashlib

        from induction_network_on_fewrel_tpu.fleet.transport import (
            _dataset_to_wire,
        )

        with self._lock:
            entries = sorted(self.directory.items())
        view = {}
        for tenant, e in entries:
            digest = None
            if e.source is not None and hasattr(e.source, "rel_names"):
                wire = json.dumps(
                    _dataset_to_wire(e.source), sort_keys=True
                ).encode()
                digest = hashlib.sha256(wire).hexdigest()[:16]
            view[tenant] = {
                "owner": e.owner,
                "max_classes": e.max_classes,
                "nota_threshold": e.nota_threshold,
                "quarantined": bool(e.quarantined),
                "source_digest": digest,
            }
        return view

    # --- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        states = self.placement.states()
        pending = len(self.pending_failover())
        with self._lock:
            return {
                "replicas": len(self.replicas),
                "live": sum(1 for s in states.values() if s == UP),
                "dead": sum(1 for s in states.values() if s == DEAD),
                "tenants": len(self.directory),
                "submitted": self.submitted,
                "shed": self.shed,
                "degraded_served": self.degraded_served,
                "replica_deaths": self.replica_deaths,
                "replaced": self.replaced,
                "pending_failover": pending,
                "inflight": sum(self._inflight.values()),
            }

    def bind_registry(self, registry=None, prefix: str = "fleet") -> None:
        """Expose the fleet rollup through the shared obs/
        CounterRegistry (ISSUE 17): aggregate counters as pull-style
        gauges over ``snapshot()`` (ONE home for the formulas), and the
        per-replica columns as LABELED gauge families
        (``fleet_replica_*{replica="r01"}``) updated by ``emit_stats``
        — one scrape of metrics.prom shows the whole fleet."""
        from induction_network_on_fewrel_tpu.obs.export import get_registry

        reg = registry or get_registry()
        self._bound_registry = reg
        self._bound_prefix = prefix
        self._bound_fns = []

        def agg(name: str, help: str = "") -> None:
            f = lambda k=name: float(self.snapshot()[k])  # noqa: E731
            self._bound_fns.append((f"{prefix}_{name}", f))
            reg.gauge_fn(f"{prefix}_{name}", f, help)

        agg("live", "replicas UP in placement")
        agg("dead", "replicas marked dead")
        agg("tenants", "registered fleet tenants")
        agg("submitted", "requests through the fleet front door")
        agg("shed", "fleet-share door sheds")
        agg("degraded_served", "failover NOTA verdicts from the router")
        agg("pending_failover", "tenants awaiting re-placement")
        agg("inflight", "fleet-wide in-flight requests")
        for col, help in (
            ("qps", "served/s over the last emit interval"),
            ("p50_ms", "median replica latency"),
            ("p99_ms", "tail replica latency"),
            ("batch_occupancy", "real rows / bucket slots"),
            ("queue_depth", "replica admission queue depth"),
            ("shed", "replica-level shed-load rejections"),
            ("steady_recompiles", "programs compiled after warmup"),
            ("routed", "requests routed to the replica"),
            ("up", "1 = UP in placement"),
            ("breaker_open", "1 = breaker open, 0.5 = half-open"),
            ("resident_bytes", "bytes of resident class vectors"),
            ("quant_agreement", "sampled quantized-vs-f32 verdict agreement"),
        ):
            self._families[col] = reg.labeled_gauge(
                f"{prefix}_replica_{col}", help=help
            )

    def unbind_registry(self) -> None:
        """Release the gauge_fn closures (identity-checked) and the
        labeled families — the ServingStats.unbind_registry discipline,
        so a closed router stops rendering stale fleet values."""
        reg = self._bound_registry
        if reg is None:
            return
        for name, f in self._bound_fns:
            reg.unregister(name, fn=f)
        prefix = getattr(self, "_bound_prefix", "fleet")
        for col, fam in self._families.items():
            reg.unregister(f"{prefix}_replica_{col}", inst=fam)
        self._bound_registry = None
        self._bound_fns = []
        self._families = {}

    def emit_stats(self, step: int | None = None) -> None:
        """One aggregate ``kind="fleet"`` record + one per-replica record
        (``replica`` field) restating that replica's serving counters —
        the fleet section of tools/obs_report.py splits on the field.
        ISSUE 17 grew the per-replica shape into the fleet ROLLUP: qps
        (served delta over the emit interval), shed, deadline_missed,
        and the router's breaker state string; when ``bind_registry``
        was called the same columns update the labeled gauge families,
        so metrics.prom restates this record per replica."""
        if self._logger is None:
            return
        step = self.submitted if step is None else step
        self._logger.log(step, kind="fleet", **self.snapshot())
        states = self.placement.states()
        now = time.monotonic()
        for rid in sorted(self.replicas):
            try:
                snap = self.replicas[rid].stats_snapshot()
            except Exception:  # noqa: BLE001 — a dead replica has no stats
                snap = {}
            served = float(snap.get("served", 0.0))
            prev_t, prev_served = self._prev_emit.get(
                rid, (self._t0, 0.0)
            )
            dt = max(now - prev_t, 1e-9)
            qps = max(served - prev_served, 0.0) / dt
            self._prev_emit[rid] = (now, served)
            row: dict = {
                "state": states.get(rid, "removed"),
                "routed": float(self.routed.get(rid, 0)),
                "qps": round(qps, 3),
            }
            row.update({
                k: snap[k] for k in (
                    "served", "p50_ms", "p99_ms", "batch_occupancy",
                    "steady_recompiles", "queue_depth", "degraded",
                    "shed", "deadline_missed", "resident_bytes",
                    "quant_probes", "quant_agreement",
                ) if k in snap
            })
            if self.breaker is not None:
                row["breaker"] = str(self.breaker.state(rid))
            self._logger.log(step, kind="fleet", replica=rid, **row)
            if self._families:
                self._update_families(rid, row)

    def _update_families(self, rid: str, row: dict) -> None:
        brk = row.get("breaker")
        values = {
            "qps": row.get("qps", 0.0),
            "p50_ms": row.get("p50_ms", 0.0),
            "p99_ms": row.get("p99_ms", 0.0),
            "batch_occupancy": row.get("batch_occupancy", 0.0),
            "queue_depth": row.get("queue_depth", 0.0),
            "shed": row.get("shed", 0.0),
            "steady_recompiles": row.get("steady_recompiles", 0.0),
            "routed": row.get("routed", 0.0),
            "up": 1.0 if row.get("state") == UP else 0.0,
            "breaker_open": {"open": 1.0, "half_open": 0.5}.get(brk, 0.0),
            "resident_bytes": row.get("resident_bytes", 0.0),
            "quant_agreement": row.get("quant_agreement", 1.0),
        }
        for col, v in values.items():
            fam = self._families.get(col)
            if fam is not None:
                fam.set(float(v), replica=rid)

    def close(self) -> None:
        self.unbind_registry()
        for handle in self.replicas.values():
            try:
                handle.close()
            except Exception:  # noqa: BLE001 — close every replica anyway
                pass
