"""Durable control plane, layer 1 (ISSUE 15): a write-ahead log of every
fleet control-plane op, so a router crash loses NOTHING.

Every piece of fleet state the router holds in memory — the tenant
directory (support sources, NOTA thresholds, quarantine flags), replica
membership/drain states, the committed params_version + checkpoint path,
adaptation exhaustion latches — is exactly the state Geng 2019's
per-relation class vectors and Gao 2019's per-tenant NOTA/DA knobs hang
off, and before this module it lived only in process memory. The journal
makes it an append-only on-disk log with:

* **Per-record framing**: ``[u32 length][u32 crc32][payload]`` per
  record, payload = canonical JSON (sorted keys, no timestamps). A torn
  tail — a crash mid-write, a truncated disk flush, the injected
  ``journal.torn_write`` chaos point — fails the length or CRC check at
  exactly one record, and replay TRUNCATES there: everything before the
  tear is recovered, nothing after it can poison the directory
  (``kind="fault"`` ``action="journal_truncated"`` tells the operator).
* **An fsync policy knob** (``fsync=``): ``"always"`` fsyncs every
  append (maximum durability, one disk sync per control-plane op),
  ``"commit"`` (default) fsyncs only generation-changing ops
  (``publish_commit``) and compactions — tenant churn rides the OS page
  cache, the committed generation never does — and ``"off"`` leaves
  syncing to the OS (drills/tests). The tradeoff is RUNBOOK §20's.
* **Snapshot compaction**: ``compact()`` folds the materialized state
  into ``snapshot.json`` (atomic tmp+rename) and truncates the WAL;
  replay = snapshot + remaining WAL ops, proven equivalent to the full
  log (test-pinned). ``compact_every=N`` auto-compacts when the WAL
  exceeds N records, bounding replay time and disk growth.
* **Deterministic replay**: ``materialize()`` is a pure function of the
  recorded op sequence — no clocks, no RNG, no process state — so every
  router restart, every test, and every compacted/uncompacted pair
  rebuilds the SAME state, and placement stays the pure rendezvous
  function it already was (placements are never journaled; they are
  recomputed from tenant ids + the replayed replica states).

The journal never imports the router/transport layers: callers hand it
JSON-ready payloads (``fleet/control.py`` converts datasets to their
wire form before journaling) so this module has no import cycle and no
serialization opinions of its own.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from pathlib import Path

from induction_network_on_fewrel_tpu.obs.chaos import chaos_fire

WAL_NAME = "wal.log"
SNAPSHOT_NAME = "snapshot.json"
LEASE_NAME = "lease.json"

_HEADER = struct.Struct("<II")   # (payload length, crc32(payload))

FSYNC_POLICIES = ("always", "commit", "off")

# Ops whose loss a crash must never cause — under fsync="commit" these
# (and compactions) are the only syncs: the committed generation, and
# the PERMANENT adaptation-exhaustion latch (rare by construction — one
# append per burned retry budget — and the whole point of journaling it
# is surviving exactly the crash class an unsynced page cache loses).
_COMMIT_OPS = frozenset({"publish_commit", "adapt_exhausted"})

# The full control-plane op vocabulary. An op outside this set is a
# programming error at append time (the FeedFaults rule: refusing loudly
# beats replaying garbage later).
KNOWN_OPS = frozenset({
    "tenant_register",      # tenant, source (wire dict), max_classes,
    #                         nota_threshold (optional)
    "tenant_threshold",     # tenant, threshold
    "tenant_quarantine",    # tenant, reason
    "tenant_unquarantine",  # tenant, reason
    "tenant_drop",          # tenant
    "replica_add",          # replica, meta (optional address dict)
    "replica_drain",        # replica
    "replica_revive",       # replica
    "replica_retire",       # replica (drained out of membership for good)
    "replica_forgive",      # replica (supervisor restart-budget re-arm;
    #                         an audit record — replay-neutral, because
    #                         supervisor budgets are process-local)
    "publish_commit",       # params_version, ckpt_dir (nullable)
    "adapt_exhausted",      # tenant, attempts (the permanent latch)
})


class JournalError(RuntimeError):
    """A journal-layer refusal: unknown op, bad knob, replaying an
    inconsistent prefix (which a CRC-clean journal cannot produce), or
    appending to a journal whose tail was torn by the injected
    ``journal.torn_write`` fault (the simulated crash ends this
    process's writes; recovery reopens the directory)."""


class JournalState:
    """The materialized control-plane state: a pure fold of the op
    sequence. Canonical (``to_dict`` sorts everything), so two replays
    of the same ops compare byte-identical through ``json.dumps``."""

    def __init__(self):
        # tenant -> {source, max_classes, nota_threshold, quarantined}
        self.tenants: dict[str, dict] = {}
        self.replicas: dict[str, str] = {}   # replica -> up|draining
        # The last committed publish: params_version + the checkpoint
        # path a catch-up can re-drive it from (None for params-only
        # publishes — version reconciliation still works, re-driving
        # does not; recovery surfaces that as replica_stale_params).
        self.committed: dict = {"params_version": 0, "ckpt_dir": None}
        self.adapt_exhausted: dict[str, float] = {}   # tenant -> attempts
        self.applied = 0    # ops folded in (snapshot base + WAL)

    def apply(self, rec: dict) -> None:
        op = rec.get("op")
        t = rec.get("tenant")
        if op == "tenant_register":
            self.tenants[t] = {
                "source": rec.get("source"),
                "max_classes": rec.get("max_classes"),
                "nota_threshold": rec.get("nota_threshold"),
                "quarantined": False,
            }
        elif op == "tenant_threshold":
            self._tenant(rec)["nota_threshold"] = rec.get("threshold")
        elif op == "tenant_quarantine":
            self._tenant(rec)["quarantined"] = True
        elif op == "tenant_unquarantine":
            self._tenant(rec)["quarantined"] = False
        elif op == "tenant_drop":
            self.tenants.pop(t, None)
        elif op == "replica_add":
            self.replicas[str(rec.get("replica"))] = "up"
        elif op == "replica_drain":
            self.replicas[str(rec.get("replica"))] = "draining"
        elif op == "replica_revive":
            self.replicas[str(rec.get("replica"))] = "up"
        elif op == "replica_retire":
            self.replicas.pop(str(rec.get("replica")), None)
        elif op == "replica_forgive":
            pass   # audit-only: restart budgets are process-local state
        elif op == "publish_commit":
            self.committed = {
                "params_version": int(rec["params_version"]),
                "ckpt_dir": rec.get("ckpt_dir"),
            }
        elif op == "adapt_exhausted":
            self.adapt_exhausted[t] = float(rec.get("attempts", 0))
        else:
            raise JournalError(f"unknown journal op {op!r} in replay")
        self.applied += 1

    def _tenant(self, rec: dict) -> dict:
        entry = self.tenants.get(rec.get("tenant"))
        if entry is None:
            # Unreachable through the framing: truncation only removes a
            # TAIL, so every CRC-clean prefix is self-consistent.
            raise JournalError(
                f"journal op {rec.get('op')!r} for unregistered tenant "
                f"{rec.get('tenant')!r}"
            )
        return entry

    def to_dict(self) -> dict:
        return {
            "tenants": {t: dict(self.tenants[t])
                        for t in sorted(self.tenants)},
            "replicas": {r: self.replicas[r]
                         for r in sorted(self.replicas)},
            "committed": dict(self.committed),
            "adapt_exhausted": {t: self.adapt_exhausted[t]
                                for t in sorted(self.adapt_exhausted)},
            "applied": self.applied,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JournalState":
        st = cls()
        st.tenants = {t: dict(v) for t, v in d.get("tenants", {}).items()}
        st.replicas = dict(d.get("replicas", {}))
        st.committed = dict(
            d.get("committed", {"params_version": 0, "ckpt_dir": None})
        )
        st.adapt_exhausted = dict(d.get("adapt_exhausted", {}))
        st.applied = int(d.get("applied", 0))
        return st


class FleetJournal:
    """One journal directory: ``wal.log`` (framed records) +
    ``snapshot.json`` (the compaction base). Thread-safe — control-plane
    ops journal from client threads, the supervisor from its loop."""

    def __init__(self, out_dir: str | Path, fsync: str = "commit",
                 compact_every: int = 0, logger=None):
        if fsync not in FSYNC_POLICIES:
            raise JournalError(
                f"unknown fsync policy {fsync!r} (one of {FSYNC_POLICIES})"
            )
        if compact_every < 0:
            raise JournalError("compact_every must be >= 0 (0 = manual)")
        self.dir = Path(out_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fsync = fsync
        self.compact_every = compact_every
        self._logger = logger
        self._lock = threading.RLock()
        self._fh = None
        self._torn = False   # set by the injected torn write: the
        #                      "process" died mid-append; reopen to heal
        self.snapshot_seq = 0     # ops folded into snapshot.json
        self._wal_records = 0
        self._lease_owner = None   # set by acquire_lease/adopt_lease
        self._lease_epoch = None
        snap = self.dir / SNAPSHOT_NAME
        if snap.exists():
            self.snapshot_seq = int(
                json.loads(snap.read_text()).get("applied", 0)
            )
        # Opening IS recovery: a torn tail from a previous crash is
        # truncated now, so appends land on a clean frame boundary.
        self._recover_tail()

    # --- write side -------------------------------------------------------

    @property
    def records(self) -> int:
        """WAL records on disk (excludes ops folded into the snapshot)."""
        return self._wal_records

    @property
    def seq(self) -> int:
        """Total ops this journal holds (snapshot base + WAL)."""
        return self.snapshot_seq + self._wal_records

    def append(self, op: str, **fields) -> int:
        """Append one op; returns its 0-based sequence number. Fields
        must be JSON-ready (callers serialize datasets to wire form
        first) and must not carry timestamps — replay is deterministic
        by contract."""
        if op not in KNOWN_OPS:
            raise JournalError(
                f"unknown journal op {op!r} (known: "
                f"{', '.join(sorted(KNOWN_OPS))})"
            )
        with self._lock:
            if self._torn:
                raise JournalError(
                    "journal tail is torn (injected journal.torn_write): "
                    "the writing process is 'dead' — reopen the journal "
                    "directory to truncate and recover"
                )
            self._check_lease()
            seq = self.seq
            payload = json.dumps(
                {"op": op, "seq": seq, **fields}, sort_keys=True
            ).encode()
            header = _HEADER.pack(len(payload), zlib.crc32(payload))
            fired = chaos_fire("journal.torn_write", op=op, step=seq)
            fh = self._open()
            if fired is not None:
                # The simulated crash: the header claims the full record
                # but only half the payload reaches disk. This journal
                # object refuses further writes (the process died);
                # recovery = reopen, which truncates the tear.
                fh.write(header + payload[: max(len(payload) // 2, 1)])
                fh.flush()
                self._torn = True
                return seq
            fh.write(header + payload)
            fh.flush()
            if self.fsync == "always" or (
                self.fsync == "commit" and op in _COMMIT_OPS
            ):
                os.fsync(fh.fileno())
            self._wal_records += 1
            if self.compact_every and self._wal_records >= self.compact_every:
                self._compact_locked()
        # One kind="fleet" event="journal_op" record per append (ISSUE
        # 17): the WAL payload itself carries NO timestamp (replay is
        # deterministic by contract, above), so THIS record is where a
        # control-plane decision acquires its wall-clock position on
        # the fleet timeline — tools/fleet_report.py orders journal ops
        # by these records and cross-checks op/seq against the replayed
        # WAL. Emitted outside the journal lock: the logger has its own
        # lock, and a slow metrics disk must not serialize appends.
        if self._logger is not None:
            self._logger.log(
                seq, kind="fleet", event="journal_op", op=op,
                seq=float(seq),
            )
        return seq

    def sync(self) -> None:
        """Force an fsync regardless of policy (operator barrier)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    # --- single-writer lease ----------------------------------------------

    def acquire_lease(self, owner: str) -> int:
        """Take (or take over) the journal's single-writer lease. Every
        acquisition bumps the epoch; from then on every ``append`` checks
        the lease file and REFUSES when another writer holds a newer
        epoch — a fenced-off zombie primary cannot split-brain the log."""
        with self._lock:
            epoch = JournalLease(self.dir).acquire(owner)
            self._lease_owner = owner
            self._lease_epoch = epoch
            return epoch

    def adopt_lease(self, owner: str, epoch: int) -> None:
        """Bind to a lease already acquired out-of-band (the standby
        acquires BEFORE opening its own ``FleetJournal``, so the fence is
        up during the catch-up replay, not after)."""
        with self._lock:
            self._lease_owner = owner
            self._lease_epoch = int(epoch)

    def _check_lease(self) -> None:
        if self._lease_epoch is None:
            return   # unleased journal: single-process mode, no fence
        held = JournalLease(self.dir).read()
        if (held.get("epoch") != self._lease_epoch
                or held.get("owner") != self._lease_owner):
            raise JournalError(
                f"journal lease lost: held by "
                f"{held.get('owner')!r} epoch {held.get('epoch')} "
                f"(we are {self._lease_owner!r} epoch {self._lease_epoch}) "
                "— split-brain append refused"
            )

    def _open(self):
        if self._fh is None or self._fh.closed:
            self._fh = open(self.dir / WAL_NAME, "ab")
        return self._fh

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()

    # --- read side --------------------------------------------------------

    def _scan(self, repair: bool) -> tuple[list[dict], int]:
        """Parse the WAL: (records, bytes of clean prefix). A short or
        CRC-failing record is a TEAR: everything from its frame start is
        dropped; with ``repair`` the file is truncated there (and the
        truncation is told as a kind='fault' record)."""
        path = self.dir / WAL_NAME
        if not path.exists():
            return [], 0
        blob = path.read_bytes()
        records: list[dict] = []
        off = 0
        clean = 0
        reason = None
        while off + _HEADER.size <= len(blob):
            length, crc = _HEADER.unpack_from(blob, off)
            start, end = off + _HEADER.size, off + _HEADER.size + length
            if end > len(blob):
                reason = "short payload (torn write)"
                break
            payload = blob[start:end]
            if zlib.crc32(payload) != crc:
                reason = "crc mismatch (corrupt record)"
                break
            try:
                rec = json.loads(payload)
            except json.JSONDecodeError:
                reason = "unparseable payload"
                break
            records.append(rec)
            off = end
            clean = off
        else:
            if off < len(blob):
                reason = "trailing partial header"
        if clean < len(blob) and repair:
            dropped = len(blob) - clean
            with open(path, "r+b") as f:
                f.truncate(clean)
            if self._logger is not None:
                self._logger.log(
                    len(records), kind="fault", action="journal_truncated",
                    reason=reason or "torn tail",
                    bytes_dropped=float(dropped),
                    records_kept=float(len(records)),
                )
        return records, clean

    def _recover_tail(self) -> None:
        records, _ = self._scan(repair=True)
        self._wal_records = len(records)
        self._torn = False

    def replay(self) -> list[dict]:
        """The WAL records (clean prefix only; repairs a torn tail in
        place, exactly like construction does)."""
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.flush()
            records, _ = self._scan(repair=True)
            self._wal_records = len(records)
            return records

    def materialize(self) -> JournalState:
        """Snapshot base + WAL ops folded into one ``JournalState`` —
        the pure, deterministic replay every recovery path runs."""
        with self._lock:
            snap_path = self.dir / SNAPSHOT_NAME
            if snap_path.exists():
                state = JournalState.from_dict(
                    json.loads(snap_path.read_text())
                )
            else:
                state = JournalState()
            for rec in self.replay():
                state.apply(rec)
            return state

    # --- compaction -------------------------------------------------------

    def compact(self) -> JournalState:
        """Fold the full log into ``snapshot.json`` and truncate the
        WAL. Crash-safe: the snapshot lands by atomic rename BEFORE the
        WAL truncates, so a crash between the two replays snapshot + the
        (re-applied, idempotent-by-construction) WAL ops — every op
        apply is a plain overwrite, so double-application of a suffix
        cannot diverge the state."""
        with self._lock:
            return self._compact_locked()

    def _compact_locked(self) -> JournalState:
        state = self.materialize()
        tmp = self.dir / (SNAPSHOT_NAME + ".tmp")
        snap = json.dumps(state.to_dict(), sort_keys=True, indent=1)
        with open(tmp, "w") as f:
            f.write(snap + "\n")
            f.flush()
            if self.fsync != "off":
                os.fsync(f.fileno())
        os.replace(tmp, self.dir / SNAPSHOT_NAME)
        if self._fh is not None and not self._fh.closed:
            self._fh.close()
            self._fh = None
        with open(self.dir / WAL_NAME, "wb") as f:
            if self.fsync != "off":
                f.flush()
                os.fsync(f.fileno())
        self.snapshot_seq = state.applied
        self._wal_records = 0
        if self._logger is not None:
            self._logger.log(
                state.applied, kind="fleet", event="journal_compact",
                snapshot_seq=float(state.applied),
                tenants=float(len(state.tenants)),
            )
        return state


class JournalLease:
    """The journal directory's single-writer latch: ``lease.json`` holds
    ``{"owner", "epoch"}``, written by atomic tmp+rename. ``acquire``
    bumps the epoch, so a standby taking over FENCES the old primary —
    the zombie's next ``append`` reads a lease it no longer holds and
    raises instead of split-braining the log. This is a cooperative
    lease (every writer goes through ``FleetJournal.append``'s check),
    which is exactly the guarantee a single-host drill can prove."""

    def __init__(self, journal_dir: str | Path):
        self.path = Path(journal_dir) / LEASE_NAME

    def read(self) -> dict:
        """The current lease ({"owner": None, "epoch": 0} when unheld)."""
        if not self.path.exists():
            return {"owner": None, "epoch": 0}
        try:
            d = json.loads(self.path.read_text())
        except (json.JSONDecodeError, OSError):
            return {"owner": None, "epoch": 0}
        return {"owner": d.get("owner"), "epoch": int(d.get("epoch", 0))}

    def acquire(self, owner: str) -> int:
        """Take the lease as ``owner``; returns the new (bumped) epoch."""
        epoch = self.read()["epoch"] + 1
        tmp = self.path.with_suffix(".tmp")
        with open(tmp, "w") as f:
            json.dump({"owner": owner, "epoch": epoch}, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return epoch


class JournalTailer:
    """A READ-ONLY incremental reader of a journal another process owns —
    the hot standby's view of the primary's WAL. Unlike ``FleetJournal``,
    the tailer NEVER truncates: a short or CRC-failing tail here is most
    likely an append in progress by the live primary, so the tailer stops
    at the last clean frame and retries from there next ``poll``.

    Compaction-aware: when the primary folds the WAL into
    ``snapshot.json`` (the WAL shrinks under our offset, or the snapshot
    base advances past ours), the tailer rebases — reload the snapshot,
    re-read the fresh WAL from byte 0. A compaction racing a single poll
    can transiently rebase on the pre-compact snapshot; the next poll
    reads the settled pair and self-heals (``JournalState.apply`` is a
    pure overwrite-fold, so re-application cannot diverge the state)."""

    def __init__(self, journal_dir: str | Path):
        self.dir = Path(journal_dir)
        self.state = JournalState()
        self._offset = 0         # clean WAL bytes folded into state
        self._snap_applied = 0   # snapshot base folded into state

    @property
    def applied(self) -> int:
        """Total ops folded into the tailed state (mirrors journal.seq)."""
        return self.state.applied

    def poll(self) -> int:
        """Fold newly committed ops into the tailed state; returns how
        many ops the state advanced by this call."""
        before = self.state.applied
        snap = None
        snap_path = self.dir / SNAPSHOT_NAME
        if snap_path.exists():
            try:
                snap = json.loads(snap_path.read_text())
            except (json.JSONDecodeError, OSError):
                snap = None   # racing the atomic rename; next poll wins
        snap_applied = int(snap.get("applied", 0)) if snap else 0
        wal = self.dir / WAL_NAME
        wal_size = wal.stat().st_size if wal.exists() else 0
        if snap_applied > self._snap_applied or wal_size < self._offset:
            # The primary compacted: rebase on the snapshot, restart the
            # WAL read from byte 0.
            self.state = (JournalState.from_dict(snap) if snap
                          else JournalState())
            self._snap_applied = snap_applied
            self._offset = 0
        records, clean = self._read_from(self._offset)
        for rec in records:
            self.state.apply(rec)
        self._offset = clean
        return self.state.applied - before

    def records(self) -> list[dict]:
        """The WAL's clean-frame records from byte 0, read-only — no
        state fold, no truncation. tools/fleet_report.py's replay
        source: each record carries ``op`` and ``seq``, cross-checked
        against the router's ``kind="fleet"`` ``event="journal_op"``
        telemetry (ISSUE 17). Ops folded into a snapshot are NOT here;
        the snapshot's ``applied`` count says how many seqs precede the
        WAL."""
        return self._read_from(0)[0]

    def _read_from(self, offset: int) -> tuple[list[dict], int]:
        """Parse complete frames from ``offset``; returns (records, new
        clean offset). Read-only — a torn/in-progress tail is left for
        the next poll, never truncated."""
        path = self.dir / WAL_NAME
        if not path.exists():
            return [], offset
        with open(path, "rb") as f:
            f.seek(offset)
            blob = f.read()
        records: list[dict] = []
        off = 0
        clean = 0
        while off + _HEADER.size <= len(blob):
            length, crc = _HEADER.unpack_from(blob, off)
            start, end = off + _HEADER.size, off + _HEADER.size + length
            if end > len(blob):
                break
            payload = blob[start:end]
            if zlib.crc32(payload) != crc:
                break
            try:
                rec = json.loads(payload)
            except json.JSONDecodeError:
                break
            records.append(rec)
            off = end
            clean = off
        return records, offset + clean
