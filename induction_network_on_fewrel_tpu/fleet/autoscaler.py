"""Elasticity tier, piece 1 (ISSUE 16): the SLO-driven autoscaler.

A policy loop on an INJECTABLE clock that consumes the fleet signals the
stack already emits — per-replica batch occupancy + queue depth
(``serving/stats.ServingStats.snapshot``), the router's shed-load
counter (fleet-share door sheds), and the SLOEngine's fast-window burn
rates — and drives scale-out / drain-in through the journaled
``FleetControl`` ops, so the serving capacity behind Geng 2019's
induction verdicts follows load instead of being fixed at boot.

Policy shape (the classic target-band controller, deliberately boring):

* **Target band + hysteresis**: a tick classifies as PRESSURE
  (occupancy >= high band, or door sheds since the last tick, or any
  tenant's fast burn >= the SLO engine's page threshold) or IDLE
  (occupancy <= low band AND no sheds AND queues empty AND no burn).
  A decision needs ``high_windows`` / ``low_windows`` CONSECUTIVE
  classifications — one hot tick never scales, one cool tick never
  drains.
* **Cool-down**: every completed decision opens a ``cooldown_s`` window
  in which no NEW decision starts (an in-progress one continues), so a
  load step cannot flap the fleet through add/retire cycles faster than
  the signals can settle.
* **Scale-out = spawn -> catch-up -> pre-warm -> join -> replace.** The
  newcomer is caught up to the journaled committed params_version,
  pre-registered with exactly the tenants the rendezvous will hand it
  (``placement_score`` is pure, so "who moves" is computable BEFORE the
  replica joins placement), and AOT-warmed — all before ``replica_add``
  makes it routable. The zero-recompile invariant holds THROUGH the
  scale event: the first query the newcomer serves hits a compiled
  program.
* **Drain-in = drain -> wait-for-inflight -> replace -> retire.** The
  victim leaves placement (journaled ``replica_drain``) but KEEPS its
  tenant registrations — the router serves a draining owner's tenants
  from the owner until ``replace_tenants`` moves them, so nothing
  queued there can be dropped by an early re-registration. Only when
  its queue is EMPTY do the tenants move (rendezvous churn bound) and
  ``replica_retire`` removes it for good — in-flight work is pinned
  through the whole sequence, never dropped.
* **Bounds + stuck latch**: ``min_replicas``/``max_replicas`` clamp the
  policy; a decision that cannot complete within ``scale_budget_s``
  (spawn_fn failing, a drain that never empties) emits ONE
  ``kind="fault"`` ``action="scale_stuck"`` — the watchdog latches it
  CRITICAL until a later completed scale event re-arms it — and the
  loop keeps retrying rather than abandoning the fleet mid-decision.

Every tick emits one ``kind="scale"`` record (the replica-count
timeline); decisions emit ``event="scale_out"`` / ``event="drain_in"``
with the trigger signals that justified them. Deterministic testing is
the same trick the supervisor uses: inject ``clock=`` and (for drills)
pass explicit ``signals=`` into ``tick`` — the policy arithmetic is
pure; only ``observe()`` touches live counters.
"""

from __future__ import annotations

import time

from induction_network_on_fewrel_tpu.fleet.placement import (
    UP,
    placement_score,
)
from induction_network_on_fewrel_tpu.fleet.router import drive_tenant_state


class FleetAutoscaler:
    """The policy loop. ``control`` is the journaled ``FleetControl``;
    ``spawn_fn(replica_id) -> ReplicaHandle`` builds a fresh replica
    (the supervisor's ``restart_fn`` discipline — process/engine
    creation stays the deployment's business)."""

    def __init__(
        self,
        control,
        spawn_fn,
        *,
        slo=None,
        min_replicas: int = 1,
        max_replicas: int = 4,
        high_occupancy: float = 0.75,
        low_occupancy: float = 0.20,
        high_windows: int = 2,
        low_windows: int = 3,
        cooldown_s: float = 30.0,
        scale_budget_s: float = 60.0,
        clock=time.monotonic,
        logger=None,
    ):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not (0.0 <= low_occupancy < high_occupancy <= 1.0):
            raise ValueError(
                "need 0 <= low_occupancy < high_occupancy <= 1"
            )
        if high_windows < 1 or low_windows < 1:
            raise ValueError("hysteresis windows must be >= 1")
        self.control = control
        self.spawn_fn = spawn_fn
        self.slo = slo
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.high_occupancy = high_occupancy
        self.low_occupancy = low_occupancy
        self.high_windows = high_windows
        self.low_windows = low_windows
        self.cooldown_s = cooldown_s
        self.scale_budget_s = scale_budget_s
        self.clock = clock
        self._logger = logger
        self._last_shed = int(control.router.snapshot()["shed"])
        self._cooldown_until = float("-inf")
        self._high_streak = 0
        self._low_streak = 0
        self._pending: dict | None = None
        self._retired: set[str] = set()
        self._ticks = 0
        self.scale_outs = 0
        self.drain_ins = 0
        self.last_event: dict | None = None   # latest completed decision

    # --- signals ----------------------------------------------------------

    def observe(self) -> dict:
        """One reading of the live fleet signals. Occupancy/queue depth
        average over UP replicas; ``shed_delta`` is door sheds since the
        last reading; ``burn_fast`` is the max fast-window burn across
        SLO tenants (0 without an SLO engine)."""
        router = self.control.router
        snap = router.snapshot()
        occs: list[float] = []
        qds: list[float] = []
        for rid in sorted(router.replicas):
            if router.placement.state(rid) != UP:
                continue
            try:
                s = router.replicas[rid].stats_snapshot()
            except Exception:  # noqa: BLE001 — supervisor's problem
                continue
            occs.append(float(s.get("batch_occupancy") or 0.0))
            qds.append(float(s.get("queue_depth") or 0))
        shed = int(snap["shed"])
        shed_delta = shed - self._last_shed
        self._last_shed = shed
        burn = 0.0
        if self.slo is not None:
            for tenant in self.slo.tenants():
                rates = self.slo.burn_rates(tenant)
                if rates:
                    burn = max(burn, float(rates["burn_fast"]))
        return {
            "replicas": int(snap["replicas"]),
            "live": int(snap["live"]),
            "occupancy": sum(occs) / len(occs) if occs else 0.0,
            "queue_depth": sum(qds) / len(qds) if qds else 0.0,
            "shed_delta": shed_delta,
            "burn_fast": burn,
        }

    def _burn_hot(self, sig: dict) -> bool:
        if self.slo is None:
            return False
        return float(sig.get("burn_fast", 0.0)) >= self.slo.fast_burn

    # --- the policy tick --------------------------------------------------

    def tick(self, signals: dict | None = None) -> dict:
        """One policy evaluation on the injected clock; returns the
        decision summary (``action`` + the signals it was based on).
        ``signals`` overrides ``observe()`` — the drill/test seam: the
        policy arithmetic is pure given the reading."""
        now = self.clock()
        self._ticks += 1
        sig = self.observe() if signals is None else {
            "replicas": len(self.control.router.replicas),
            "live": len(self.control.router.placement.live()),
            "occupancy": 0.0,
            "queue_depth": 0.0,
            "shed_delta": 0,
            "burn_fast": 0.0,
            **signals,
        }
        pressure = (
            sig["occupancy"] >= self.high_occupancy
            or sig["shed_delta"] > 0
            or self._burn_hot(sig)
        )
        idle = (
            sig["occupancy"] <= self.low_occupancy
            and sig["shed_delta"] <= 0
            and sig["queue_depth"] == 0
            and not self._burn_hot(sig)
        )
        if pressure:
            self._high_streak += 1
            self._low_streak = 0
        elif idle:
            self._low_streak += 1
            self._high_streak = 0
        else:
            self._high_streak = 0
            self._low_streak = 0
        if self._pending is not None:
            action = self._continue_pending(sig, now)
        elif now < self._cooldown_until:
            action = "cooldown"
        elif pressure and self._high_streak >= self.high_windows:
            if sig["live"] >= self.max_replicas:
                action = "at_max"
            else:
                action = self._start_scale_out(sig, now)
        elif idle and self._low_streak >= self.low_windows:
            if sig["live"] <= self.min_replicas:
                action = "at_min"
            else:
                action = self._start_drain_in(sig, now)
        else:
            action = "none"
        if self._logger is not None:
            self._logger.log(
                self._ticks, kind="scale",
                replicas=float(len(self.control.router.replicas)),
                live=float(len(self.control.router.placement.live())),
                occupancy=float(sig["occupancy"]),
                queue_depth=float(sig["queue_depth"]),
                shed_delta=float(sig["shed_delta"]),
                burn_fast=float(sig["burn_fast"]),
                pressure=float(pressure),
                idle=float(idle),
                high_streak=float(self._high_streak),
                low_streak=float(self._low_streak),
                action=action,
            )
        return {"action": action, **sig}

    def _continue_pending(self, sig: dict, now: float) -> str:
        if self._pending["direction"] == "scale_out":
            return self._continue_scale_out(sig, now)
        return self._continue_drain_in(sig, now)

    def _complete(self, now: float) -> None:
        self._pending = None
        self._cooldown_until = now + self.cooldown_s
        self._high_streak = 0
        self._low_streak = 0

    def _maybe_stuck(self, now: float, reason: str) -> None:
        p = self._pending
        waited = now - p["started"]
        if waited < self.scale_budget_s or p["stuck"]:
            return
        p["stuck"] = True
        if self._logger is not None:
            self._logger.log(
                self._ticks, kind="fault", action="scale_stuck",
                direction=p["direction"],
                replica=p.get("replica") or "",
                reason=reason,
                waited_s=float(round(waited, 3)),
                budget_s=float(self.scale_budget_s),
            )

    # --- scale-out --------------------------------------------------------

    def _next_replica_id(self) -> str:
        taken = set(self.control.router.replicas) | self._retired
        n = 0
        while f"r{n:02d}" in taken:
            n += 1
        return f"r{n:02d}"

    def _start_scale_out(self, sig: dict, now: float) -> str:
        self._pending = {
            "direction": "scale_out", "started": now, "replica": None,
            "stuck": False,
            "trigger": {
                k: sig[k] for k in ("occupancy", "shed_delta", "burn_fast")
            },
        }
        return self._continue_scale_out(sig, now)

    def _continue_scale_out(self, sig: dict, now: float) -> str:
        p = self._pending
        try:
            rid = p["replica"] or self._next_replica_id()
            p["replica"] = rid
            handle = self.spawn_fn(rid)
            warm = self._join(rid, handle)
        except Exception as e:  # noqa: BLE001 — retried next tick
            self._maybe_stuck(now, f"spawn failed: {type(e).__name__}: {e}")
            return "pending"
        moved = self.control.replace_tenants()
        self.scale_outs += 1
        self.last_event = {
            "event": "scale_out", "replica": p["replica"],
            "scale_s": round(now - p["started"], 3),
            "warm_compiles": int(warm), "moved": int(moved),
            "trigger": dict(p["trigger"]),
        }
        if self._logger is not None:
            self._logger.log(
                self._ticks, kind="scale", event="scale_out",
                replica=p["replica"],
                scale_s=float(round(now - p["started"], 3)),
                warm_compiles=float(warm),
                moved=float(moved),
                replicas=float(len(self.control.router.replicas)),
                **{k: float(v) for k, v in p["trigger"].items()},
            )
        self._complete(now)
        return "scale_out"

    def _join(self, rid: str, handle) -> int:
        """Everything that must happen BEFORE the newcomer is routable:
        catch up to the committed generation, pre-register the tenants
        the rendezvous will hand it, AOT-warm their programs — then
        join placement (``replica_add``). Returns warmup compiles."""
        router = self.control.router
        if self.control.journal is not None:
            self._catch_up_handle(
                handle, self.control.journal.materialize().committed
            )
        live = router.placement.live()
        with router._lock:
            entries = list(router.directory.items())
        for tenant, entry in entries:
            best = max(
                (placement_score(tenant, r) for r in live), default=None
            )
            if best is None or placement_score(tenant, rid) > best:
                if entry.source is None:
                    continue   # routing-only stub: nothing to pre-warm
                drive_tenant_state(handle, tenant, entry,
                                   reason="pre-warm")
        warm = int(handle.warmup())
        self.control.add_replica(handle)
        return warm

    @staticmethod
    def _catch_up_handle(handle, committed: dict) -> None:
        """``FleetRouter.catch_up_replica`` for a handle that has not
        joined yet (same pinned-version re-drive, no router entry)."""
        target = int(committed.get("params_version", 0) or 0)
        ckpt_dir = committed.get("ckpt_dir")
        if target <= 0 or not ckpt_dir:
            return
        if int(handle.params_version) >= target:
            return
        txn = handle.prepare_publish(
            ckpt_dir=ckpt_dir, target_version=target
        )
        handle.commit_publish(txn)

    # --- drain-in ---------------------------------------------------------

    def _start_drain_in(self, sig: dict, now: float) -> str:
        router = self.control.router
        up = [r for r in sorted(router.replicas)
              if router.placement.state(r) == UP]
        victim = up[-1]   # LIFO: drain-in reverses scale-out
        self.control.drain_replica(victim)
        self._pending = {
            "direction": "drain_in", "started": now, "replica": victim,
            "moved": 0, "stuck": False,
        }
        return self._continue_drain_in(sig, now)

    def _continue_drain_in(self, sig: dict, now: float) -> str:
        # Order is drain -> WAIT -> replace -> retire: while DRAINING
        # the victim still owns (and correctly serves) its tenants, so
        # waiting for an empty queue BEFORE replace_tenants() means no
        # queued request can be dropped by its registration moving.
        p = self._pending
        victim = p["replica"]
        handle = self.control.router.replicas.get(victim)
        if handle is not None:
            try:
                depth = int(
                    handle.stats_snapshot().get("queue_depth") or 0
                )
            except Exception as e:  # noqa: BLE001 — retried next tick
                self._maybe_stuck(
                    now, f"stats unreachable: {type(e).__name__}: {e}"
                )
                return "pending"
            if depth > 0:
                self._maybe_stuck(now, f"{depth} request(s) in flight")
                return "pending"
            p["moved"] += self.control.replace_tenants()
            self.control.retire_replica(victim)
            self._retired.add(victim)
        self.drain_ins += 1
        self.last_event = {
            "event": "drain_in", "replica": victim,
            "drain_s": round(now - p["started"], 3),
            "moved": int(p["moved"]),
        }
        if self._logger is not None:
            self._logger.log(
                self._ticks, kind="scale", event="drain_in",
                replica=victim,
                drain_s=float(round(now - p["started"], 3)),
                moved=float(p["moved"]),
                replicas=float(len(self.control.router.replicas)),
            )
        self._complete(now)
        return "drain_in"
