"""Replica supervision: probe, restart, re-register, catch up (ISSUE 15
tentpole, layer 3).

The fleet router already CONTAINS a dead replica (breaker-fed failover,
degraded NOTA, re-placement) — but nothing brought one back except an
operator following RUNBOOK §18 by hand. The supervisor closes that loop
for process-per-replica (socket-mode) fleets, and for any fleet whose
replicas can be rebuilt by a ``restart_fn``:

* **Health probe** — every ``poll()`` pings each UP replica
  (``ReplicaHandle.ping``; the socket transport raises
  ``ConnectionError``/``TransportTimeout`` when the peer is gone or
  wedged — the per-call deadline means a wedged peer cannot hang the
  probe loop). A failed probe feeds ``router.mark_replica_dead`` — the
  existing failover path takes over immediately.
* **Restart with exponential backoff + deterministic jitter** — a DEAD
  replica is restarted through ``restart_fn(replica_id) -> handle``
  after ``backoff_s * 2^(attempt-1)`` (capped), plus a jitter that is a
  pure hash of (replica id, attempt) — reproducible in tests and
  drills, no thundering herd across supervisors, no RNG. The clock is
  injectable (the obs/ detector discipline), so tests compress hours
  into arithmetic.
* **Bounded restart budget** — ``restart_budget`` consecutive failed
  restarts degrade the replica to PERMANENT-dead: the supervisor stops
  trying (one ``action="replica_restart_exhausted"`` record), and the
  router's existing failover keeps answering for its tenants.
  ``forgive()`` is the operator escape hatch.
* **Re-registration + catch-up on every restart** — the fresh process
  has an empty registry at params_version 0. The supervisor re-drives
  every directory tenant owned by the replica (support source, NOTA
  threshold, quarantine flag), catches the replica up to the journaled
  committed generation (``router.catch_up_replica`` re-driving the
  journaled publish — zero recompiles on the rest of the fleet), warms
  the new process, resets its breaker history, and revives it in
  placement. ``kind="fault"`` ``action="replica_restarted"`` /
  ``action="catchup"`` tell the stream.

``poll()`` is the unit of work (drills and tests call it directly);
``start()`` runs it on a daemon thread every ``probe_interval_s``.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable

from induction_network_on_fewrel_tpu.fleet.placement import DEAD, UP


def deterministic_jitter(replica: str, attempt: int) -> float:
    """A [0, 1) fraction that is a pure function of (replica, attempt) —
    the jitter source: reproducible, process-independent, RNG-free."""
    h = hashlib.blake2b(
        f"{replica}|{attempt}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


class _Watch:
    __slots__ = ("attempts", "next_attempt_at", "exhausted")

    def __init__(self):
        self.attempts = 0          # consecutive FAILED restart attempts
        self.next_attempt_at = 0.0
        self.exhausted = False     # permanent-dead: budget burned


class ReplicaSupervisor:
    """Supervise one router's replicas. ``restart_fn(replica_id)``
    returns a fresh ``ReplicaHandle`` (spawning a process + dialing a
    ``SocketReplica`` in a real fleet; building a fresh engine in
    drills) or raises — a raise counts as a failed attempt against the
    budget."""

    def __init__(
        self,
        router,
        restart_fn: Callable[[str], object],
        journal=None,
        probe_interval_s: float = 1.0,
        backoff_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        restart_budget: int = 3,
        jitter_frac: float = 0.25,
        clock: Callable[[], float] = time.monotonic,
        logger=None,
    ):
        if restart_budget < 1:
            raise ValueError(
                f"restart_budget must be >= 1, got {restart_budget}"
            )
        if backoff_s <= 0 or probe_interval_s <= 0:
            raise ValueError("backoff_s/probe_interval_s must be > 0")
        self.router = router
        self.restart_fn = restart_fn
        self.journal = journal
        self.probe_interval_s = probe_interval_s
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.restart_budget = restart_budget
        self.jitter_frac = jitter_frac
        self._clock = clock
        self._logger = logger if logger is not None else router._logger
        self._watch: dict[str, _Watch] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.restarts = 0          # successful restarts (lifetime)

    # --- policy -----------------------------------------------------------

    def next_delay(self, replica: str, attempts: int) -> float:
        """The wait before attempt ``attempts + 1``: exponential in the
        FAILED attempt count, capped, plus deterministic jitter."""
        base = min(
            self.backoff_s * (2.0 ** max(attempts - 1, 0)),
            self.backoff_cap_s,
        )
        return base * (
            1.0 + self.jitter_frac * deterministic_jitter(replica, attempts)
        )

    def exhausted(self, replica: str) -> bool:
        with self._lock:
            w = self._watch.get(replica)
            return bool(w is not None and w.exhausted)

    def forgive(self, replica: str) -> None:
        """Operator escape hatch: clear the budget so the next poll may
        try again (the adapt controller's ``unquarantine`` discipline)."""
        with self._lock:
            self._watch.pop(replica, None)

    # --- the work unit ----------------------------------------------------

    def poll(self) -> dict:
        """One supervision pass: probe UP replicas, restart due DEAD
        ones. Returns {"probed": n, "marked_dead": [...],
        "restarted": [...], "failed": [...], "exhausted": [...]}."""
        out = {"probed": 0, "marked_dead": [], "restarted": [],
               "failed": [], "exhausted": []}
        now = self._clock()
        states = self.router.placement.states()
        for rid in sorted(self.router.replicas):
            state = states.get(rid)
            if state == UP:
                out["probed"] += 1
                try:
                    alive = self.router.replicas[rid].ping()
                except Exception:  # noqa: BLE001 — any transport error
                    alive = False  # is the answer "not alive"
                if alive:
                    with self._lock:
                        self._watch.pop(rid, None)   # healthy: clean slate
                else:
                    self.router.mark_replica_dead(
                        rid, reason="supervisor probe failed"
                    )
                    out["marked_dead"].append(rid)
                continue
            if state != DEAD:
                continue            # draining: operator's business
            with self._lock:
                w = self._watch.setdefault(rid, _Watch())
                if w.exhausted or now < w.next_attempt_at:
                    continue
            self._attempt_restart(rid, w, now, out)
        return out

    def _attempt_restart(self, rid: str, w: _Watch, now: float,
                         out: dict) -> None:
        attempt = w.attempts + 1
        try:
            handle = self.restart_fn(rid)
            self._adopt(rid, handle)
        except Exception as e:  # noqa: BLE001 — a failed restart is data
            with self._lock:
                w.attempts = attempt
                if attempt >= self.restart_budget:
                    w.exhausted = True
                else:
                    w.next_attempt_at = now + self.next_delay(rid, attempt)
            if self._logger is not None:
                self._logger.log(
                    self.router.submitted, kind="fault",
                    action="replica_restarted", replica=rid, ok=0.0,
                    attempt=float(attempt),
                    reason=f"{type(e).__name__}: {e}",
                )
            if w.exhausted:
                out["exhausted"].append(rid)
                if self._logger is not None:
                    self._logger.log(
                        self.router.submitted, kind="fault",
                        action="replica_restart_exhausted", replica=rid,
                        attempts=float(attempt),
                    )
            else:
                out["failed"].append(rid)
            return
        with self._lock:
            self._watch.pop(rid, None)
            self.restarts += 1
        out["restarted"].append(rid)
        if self._logger is not None:
            self._logger.log(
                self.router.submitted, kind="fault",
                action="replica_restarted", replica=rid, ok=1.0,
                attempt=float(attempt),
            )

    def _adopt(self, rid: str, handle) -> None:
        """Swap the fresh handle in and make it SERVE-READY before it
        re-enters placement: re-register the replica's directory
        tenants, catch up to the journaled committed params_version,
        warm the query programs, reset the breaker, revive. Order
        matters — reviving first would route live traffic at an empty
        registry."""
        from induction_network_on_fewrel_tpu.fleet.router import (
            drive_tenant_state,
        )

        router = self.router
        old = router.replicas.get(rid)
        router.replicas[rid] = handle
        if old is not None and old is not handle:
            try:
                old.close()
            except Exception:  # noqa: BLE001 — the old process is dead
                pass
        # Snapshot under the ROUTER lock: the control plane inserts
        # directory entries from client threads, and a CPython dict
        # raises mid-iteration when it grows underneath us — which the
        # blanket restart-failure handler would miscount as a burned
        # budget attempt.
        with router._lock:
            mine = sorted(
                (t, e) for t, e in router.directory.items()
                if e.owner == rid
            )
        for tenant, entry in mine:
            if entry.source is None:       # nothing to re-register from
                continue
            if handle.has_tenant(tenant):  # survived (in-place restart)
                continue
            drive_tenant_state(handle, tenant, entry,
                               reason="carried over restart")
        if self.journal is not None:
            router.catch_up_replica(
                rid, self.journal.materialize().committed
            )
        try:
            handle.warmup()
        except Exception:  # noqa: BLE001 — warmup is an optimization;
            pass           # steady-state gates catch a broken replica
        if router.breaker is not None:
            router.breaker.reset(rid)
        router.revive_replica(rid, reason="supervised restart")

    # --- loop -------------------------------------------------------------

    def start(self) -> "ReplicaSupervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="replica-supervisor"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — the supervisor must
                # outlive any single poll's surprise — but SILENTLY
                # no-oping forever would be indistinguishable from
                # healthy supervision: say so in the fault stream.
                if self._logger is not None:
                    try:
                        self._logger.log(
                            self.router.submitted, kind="fault",
                            action="supervisor_poll_error",
                            reason=f"{type(e).__name__}: {e}",
                        )
                    except Exception:  # noqa: BLE001 — last resort
                        pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
