"""Elasticity tier, piece 2 (ISSUE 16): the WAL-tailing hot standby.

The durable control plane (ISSUE 15) made the router rebuildable from
its journal — but a COLD rebuild pays the full replay + re-register +
warm at the worst possible moment. The hot standby closes the last
single point of failure by paying almost all of that cost BEFORE the
primary dies:

* **Tail the same WAL.** ``journal.JournalTailer`` incrementally folds
  committed ops into an in-memory ``JournalState`` — READ-ONLY (a torn
  tail here is usually an append in progress on the live primary, so
  the tailer stops at the last clean frame; it never truncates another
  process's log). Each ``poll()`` keeps the standby's directory view
  seconds-fresh at the cost of parsing only the new bytes.
* **Leadership latch.** Promotion FIRST takes the journal's
  single-writer lease (``journal.JournalLease`` — atomic tmp+rename,
  epoch bumped on every acquisition). From that instant the old
  primary is fenced: its next ``append`` re-reads a lease it no longer
  holds and raises ``JournalError`` instead of split-braining the log.
* **Promotion = final catch-up + recover + take the front door.** One
  last ``poll()`` folds whatever committed between the death and the
  takeover, then a fresh ``FleetRouter`` over the surviving replica
  handles runs ``recover()`` on the TAILED state — the same
  deterministic rebuild the cold path uses (directory bitwise-equal to
  the dead primary's, drains re-applied, stale replicas caught up,
  lost registries re-registered and re-warmed).
* **Degraded-NOTA window, never dropped.** Until ``promote()``
  returns, ``submit()`` answers every known tenant with the shared
  degraded NOTA verdict (``serving.engine.degraded_verdict``,
  ``failover=True``) — the FewRel 2.0 none-of-the-above contract:
  during the takeover a tenant gets "no relation, degraded" in
  milliseconds, not a dropped request. After promotion ``submit``
  delegates to the promoted router.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from pathlib import Path

from induction_network_on_fewrel_tpu.fleet.journal import (
    FleetJournal,
    JournalLease,
    JournalTailer,
)
from induction_network_on_fewrel_tpu.fleet.router import FleetRouter
from induction_network_on_fewrel_tpu.serving.engine import degraded_verdict


class HotStandby:
    """A warm shadow of the fleet control plane. Construct it next to
    (or on a host away from) the primary, ``poll()`` it on a timer, and
    call ``promote(handles)`` when the primary is declared dead."""

    def __init__(self, journal_dir, *, owner: str = "standby",
                 logger=None, clock=time.monotonic):
        self.dir = Path(journal_dir)
        self.owner = owner
        self.tailer = JournalTailer(self.dir)
        self._logger = logger
        self._clock = clock
        self.router: FleetRouter | None = None
        self.journal: FleetJournal | None = None
        self.promoted = False
        self.lease_epoch: int | None = None
        self.degraded_served = 0
        self._polls = 0

    # --- the warm side ----------------------------------------------------

    @property
    def state(self):
        """The tailed ``JournalState`` (live view — advances on poll)."""
        return self.tailer.state

    @property
    def applied(self) -> int:
        return self.tailer.applied

    def poll(self) -> int:
        """Fold newly committed primary ops into the standby's state;
        returns ops applied. Emits a ``kind="scale"`` ``event="tail"``
        record when the state advanced."""
        n = self.tailer.poll()
        self._polls += 1
        if n and self._logger is not None:
            self._logger.log(
                self._polls, kind="scale", event="tail",
                applied=float(self.tailer.applied), ops=float(n),
            )
        return n

    def tenants(self) -> tuple[str, ...]:
        return tuple(sorted(self.tailer.state.tenants))

    # --- the front door ---------------------------------------------------

    def submit(self, instance, deadline_s=None,
               tenant: str = "default") -> Future:
        """Before promotion: a degraded-NOTA future for any tailed
        tenant (the promotion-window contract — served, never dropped).
        After promotion: the promoted router's real submit."""
        if self.router is not None:
            return self.router.submit(
                instance, deadline_s=deadline_s, tenant=tenant
            )
        if tenant not in self.tailer.state.tenants:
            raise ValueError(
                f"unknown tenant {tenant!r} (standby has tailed "
                f"{len(self.tailer.state.tenants)} tenants)"
            )
        self.degraded_served += 1
        fut: Future = Future()
        fut.set_result(degraded_verdict(tenant, failover=True))
        return fut

    def classify(self, instance, deadline_s=None,
                 tenant: str = "default") -> dict:
        return self.submit(
            instance, deadline_s=deadline_s, tenant=tenant
        ).result()

    # --- promotion --------------------------------------------------------

    def promote(self, handles, *, breaker=None, catch_up: bool = True,
                fsync: str = "commit", **router_kw) -> dict:
        """Take over as primary. Order matters:

        1. ACQUIRE THE LEASE — the zombie primary is fenced before we
           touch anything (its appends now raise, so nothing can land
           behind our final catch-up read).
        2. Final catch-up ``poll()`` — fold every op that committed up
           to the death.
        3. Open the journal as the new single writer (this repairs any
           torn tail — safe now, we hold the lease) and bind it to our
           lease epoch.
        4. Build a ``FleetRouter`` over the surviving replica handles
           and ``recover()`` it FROM THE TAILED STATE — re-register /
           warm / catch-up, bitwise the dead primary's directory.

        Returns the recovery summary + promotion timings; afterwards
        ``submit`` routes for real and ``self.journal`` accepts
        journaled control ops (build a ``FleetControl`` on top)."""
        if self.promoted:
            raise RuntimeError("standby already promoted")
        t0 = self._clock()
        self.lease_epoch = JournalLease(self.dir).acquire(self.owner)
        tail_ops = self.tailer.poll()
        journal = FleetJournal(self.dir, fsync=fsync, logger=self._logger)
        journal.adopt_lease(self.owner, self.lease_epoch)
        router = FleetRouter(
            dict(handles), logger=self._logger, breaker=breaker,
            **router_kw,
        )
        summary = router.recover(
            journal, catch_up=catch_up, state=self.tailer.state
        )
        self.journal = journal
        self.router = router
        self.promoted = True
        # Identity hand-off (ISSUE 17): from this instant this process IS
        # the fleet front door — records it emits (hop, fleet rollups)
        # must say "router", not "standby", or fleet_report's timeline
        # attributes post-promotion routing to a process that no longer
        # exists in that role. The pre-promotion records keep "standby",
        # so the transition itself is visible in the timeline.
        set_ident = getattr(self._logger, "set_identity", None)
        if callable(set_ident):
            set_ident("router")
        promote_s = self._clock() - t0
        if self._logger is not None:
            self._logger.log(
                self._polls, kind="scale", event="promotion",
                promote_s=float(round(promote_s, 4)),
                tenants=float(len(self.tailer.state.tenants)),
                replicas=float(len(router.replicas)),
                applied=float(self.tailer.applied),
                lease_epoch=float(self.lease_epoch),
                final_tail_ops=float(tail_ops),
            )
        return {
            "promote_s": promote_s,
            "lease_epoch": self.lease_epoch,
            "applied": self.tailer.applied,
            "final_tail_ops": tail_ops,
            **summary,
        }
