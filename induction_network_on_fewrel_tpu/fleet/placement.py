"""Tenant -> replica placement: deterministic rendezvous hashing with
replica health states (ISSUE 13 tentpole, piece a).

**Rendezvous (highest-random-weight) hashing** — every (tenant, replica)
pair hashes to a 64-bit score (blake2b of ``"tenant|replica"``; no RNG,
no process state) and the tenant is owned by the LIVE replica with the
highest score. Two properties make it the right placement primitive for
a 10k-tenant fleet:

* **Determinism** — placement is a pure function of (tenant id, live
  replica set). Every router process, every restart, every test replays
  the same map; there is no placement table to replicate or lose.
* **Bounded remap** — adding a replica moves exactly the tenants whose
  new scores win (expectation T/(R+1), the minimum any balanced scheme
  can move); removing one moves exactly ITS tenants and nobody else's
  (every surviving pair's score is unchanged, so every surviving argmax
  is unchanged). Both bounds are test-pinned in tests/test_fleet.py.

**Health states** — ``up`` (eligible), ``draining`` (operator-initiated:
excluded from placement so its tenants remap away at the rendezvous
bound, while the process keeps serving whatever is still in flight) and
``dead`` (excluded; fed by the router's per-replica circuit breaker —
the existing serving/breaker.CircuitBreaker keyed by replica id — or by
the ``fleet.replica_kill`` chaos point). Dead/draining replicas stay in
the table so a revive is one state flip with the same bounded remap.
"""

from __future__ import annotations

import hashlib
import threading

UP = "up"
DRAINING = "draining"
DEAD = "dead"

_STATES = (UP, DRAINING, DEAD)


def placement_score(tenant: str, replica: str) -> int:
    """The rendezvous weight of one (tenant, replica) pair: a 64-bit
    digest of the joined ids. Pure and process-independent — every
    router, restart, and test computes the same score."""
    h = hashlib.blake2b(
        f"{tenant}|{replica}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big")


class FleetPlacement:
    """The fleet's replica table + the rendezvous placement function.

    Thread-safety: the router resolves placement on client threads while
    breaker transitions / control-plane ops mutate states — one lock,
    no I/O under it. ``place`` is two dict reads plus R hash calls (R =
    replicas, single digits to low tens); at fleet scale the per-submit
    cost is placement-table-free by design.
    """

    def __init__(self, replicas=()):
        self._lock = threading.Lock()
        self._states: dict[str, str] = {}
        # Monotonic generation: bumped on every membership/state change,
        # so callers (router owner cache, reports) can cheaply detect
        # that placements may have moved.
        self.generation = 0
        for rid in replicas:
            self._states[str(rid)] = UP

    # --- membership / health ---------------------------------------------

    def add_replica(self, replica: str, state: str = UP) -> None:
        self._set(replica, state, must_exist=False)

    def set_state(self, replica: str, state: str) -> None:
        self._set(replica, state, must_exist=True)

    def _set(self, replica: str, state: str, must_exist: bool) -> None:
        if state not in _STATES:
            raise ValueError(
                f"unknown replica state {state!r} (one of {_STATES})"
            )
        with self._lock:
            if must_exist and replica not in self._states:
                raise ValueError(f"unknown replica {replica!r}")
            if self._states.get(replica) == state:
                return
            self._states[replica] = state
            self.generation += 1

    def remove_replica(self, replica: str) -> None:
        with self._lock:
            if replica not in self._states:
                raise ValueError(f"unknown replica {replica!r}")
            del self._states[replica]
            self.generation += 1

    def state(self, replica: str) -> str | None:
        with self._lock:
            return self._states.get(replica)

    def replicas(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._states))

    def live(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(
                sorted(r for r, s in self._states.items() if s == UP)
            )

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._states)

    # --- placement --------------------------------------------------------

    def place(self, tenant: str) -> str | None:
        """The live replica owning ``tenant`` (highest rendezvous score),
        or None when no replica is up. Ties (astronomically unlikely at
        64 bits) break toward the lexically-smallest id so the map stays
        a pure function of the inputs."""
        with self._lock:
            live = [r for r, s in self._states.items() if s == UP]
        if not live:
            return None
        return max(
            sorted(live), key=lambda r: placement_score(tenant, r)
        )

    def owners(self, tenants) -> dict[str, str | None]:
        """Batch placement (one lock acquisition, one live-set)."""
        with self._lock:
            live = sorted(
                r for r, s in self._states.items() if s == UP
            )
        if not live:
            return {t: None for t in tenants}
        return {
            t: max(live, key=lambda r: placement_score(t, r))
            for t in tenants
        }

    @staticmethod
    def churn(before: dict[str, str | None],
              after: dict[str, str | None]) -> int:
        """Tenants whose owner changed between two placement maps — the
        remap cost of a membership change (FLEET artifacts record it as
        a fraction of tenants; the rendezvous bound is what the tests
        pin)."""
        return sum(
            1 for t, r in before.items() if after.get(t) != r
        )
