"""Tenant -> replica placement: deterministic rendezvous hashing with
replica health states (ISSUE 13 tentpole, piece a).

**Rendezvous (highest-random-weight) hashing** — every (tenant, replica)
pair hashes to a 64-bit score (blake2b of ``"tenant|replica"``; no RNG,
no process state) and the tenant is owned by the LIVE replica with the
highest score. Two properties make it the right placement primitive for
a 10k-tenant fleet:

* **Determinism** — placement is a pure function of (tenant id, live
  replica set). Every router process, every restart, every test replays
  the same map; there is no placement table to replicate or lose.
* **Bounded remap** — adding a replica moves exactly the tenants whose
  new scores win (expectation T/(R+1), the minimum any balanced scheme
  can move); removing one moves exactly ITS tenants and nobody else's
  (every surviving pair's score is unchanged, so every surviving argmax
  is unchanged). Both bounds are test-pinned in tests/test_fleet.py.

**Health states** — ``up`` (eligible), ``draining`` (operator-initiated:
excluded from placement so its tenants remap away at the rendezvous
bound, while the process keeps serving whatever is still in flight) and
``dead`` (excluded; fed by the router's per-replica circuit breaker —
the existing serving/breaker.CircuitBreaker keyed by replica id — or by
the ``fleet.replica_kill`` chaos point). Dead/draining replicas stay in
the table so a revive is one state flip with the same bounded remap.

**N-tier-weighted placement** (ISSUE 19, optional) — with tier-blind
rendezvous a fleet of mixed-geometry tenants sprays every N-tier onto
every replica, so each replica warms the full tiers x buckets x dtypes
program family. ``place(tenant, tier=..., tier_spread=s)`` first picks
the tier's ``s`` "home" replicas by rendezvous ON THE TIER KEY, then
rendezvous-places the tenant within that home set: each tier lands on
at most ``s`` replicas (a replica serves ~``s·T/R`` of ``T`` tiers),
while both levels keep rendezvous determinism and the bounded-remap
property (replica death remaps only the dead replica's tenants/home
slots). ``tier=None`` or ``tier_spread=0`` is exactly the tier-blind
map.
"""

from __future__ import annotations

import hashlib
import threading

UP = "up"
DRAINING = "draining"
DEAD = "dead"

_STATES = (UP, DRAINING, DEAD)


def placement_score(tenant: str, replica: str) -> int:
    """The rendezvous weight of one (tenant, replica) pair: a 64-bit
    digest of the joined ids. Pure and process-independent — every
    router, restart, and test computes the same score."""
    h = hashlib.blake2b(
        f"{tenant}|{replica}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big")


class FleetPlacement:
    """The fleet's replica table + the rendezvous placement function.

    Thread-safety: the router resolves placement on client threads while
    breaker transitions / control-plane ops mutate states — one lock,
    no I/O under it. ``place`` is two dict reads plus R hash calls (R =
    replicas, single digits to low tens); at fleet scale the per-submit
    cost is placement-table-free by design.
    """

    def __init__(self, replicas=()):
        self._lock = threading.Lock()
        self._states: dict[str, str] = {}
        # Monotonic generation: bumped on every membership/state change,
        # so callers (router owner cache, reports) can cheaply detect
        # that placements may have moved.
        self.generation = 0
        for rid in replicas:
            self._states[str(rid)] = UP

    # --- membership / health ---------------------------------------------

    def add_replica(self, replica: str, state: str = UP) -> None:
        self._set(replica, state, must_exist=False)

    def set_state(self, replica: str, state: str) -> None:
        self._set(replica, state, must_exist=True)

    def _set(self, replica: str, state: str, must_exist: bool) -> None:
        if state not in _STATES:
            raise ValueError(
                f"unknown replica state {state!r} (one of {_STATES})"
            )
        with self._lock:
            if must_exist and replica not in self._states:
                raise ValueError(f"unknown replica {replica!r}")
            if self._states.get(replica) == state:
                return
            self._states[replica] = state
            self.generation += 1

    def remove_replica(self, replica: str) -> None:
        with self._lock:
            if replica not in self._states:
                raise ValueError(f"unknown replica {replica!r}")
            del self._states[replica]
            self.generation += 1

    def state(self, replica: str) -> str | None:
        with self._lock:
            return self._states.get(replica)

    def replicas(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._states))

    def live(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(
                sorted(r for r, s in self._states.items() if s == UP)
            )

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._states)

    # --- placement --------------------------------------------------------

    @staticmethod
    def _pool(live, tier, tier_spread):
        """The candidate replicas a tenant rendezvous-places within:
        all live replicas when tier-blind, else the tier's top-
        ``tier_spread`` home replicas by rendezvous on the tier key."""
        if tier is None or tier_spread <= 0 or tier_spread >= len(live):
            return live
        return sorted(
            live,
            key=lambda r: placement_score(f"tier:{tier}", r),
            reverse=True,
        )[:tier_spread]

    def place(self, tenant: str, tier: int | None = None,
              tier_spread: int = 0) -> str | None:
        """The live replica owning ``tenant`` (highest rendezvous score),
        or None when no replica is up. Ties (astronomically unlikely at
        64 bits) break toward the lexically-smallest id so the map stays
        a pure function of the inputs. ``tier``/``tier_spread`` opt into
        N-tier-weighted placement (module doc): the tenant places within
        its tier's home set instead of the whole fleet."""
        with self._lock:
            live = [r for r, s in self._states.items() if s == UP]
        if not live:
            return None
        pool = self._pool(sorted(live), tier, tier_spread)
        return max(
            pool, key=lambda r: placement_score(tenant, r)
        )

    def owners(self, tenants, tier_of=None,
               tier_spread: int = 0) -> dict[str, str | None]:
        """Batch placement (one lock acquisition, one live-set).
        ``tier_of`` maps tenant -> N-tier (or None) for tier-weighted
        placement; None keeps the tier-blind map."""
        with self._lock:
            live = sorted(
                r for r, s in self._states.items() if s == UP
            )
        if not live:
            return {t: None for t in tenants}
        return {
            t: max(
                self._pool(
                    live,
                    tier_of(t) if tier_of is not None else None,
                    tier_spread,
                ),
                key=lambda r: placement_score(t, r),
            )
            for t in tenants
        }

    @staticmethod
    def churn(before: dict[str, str | None],
              after: dict[str, str | None]) -> int:
        """Tenants whose owner changed between two placement maps — the
        remap cost of a membership change (FLEET artifacts record it as
        a fraction of tenants; the rendezvous bound is what the tests
        pin)."""
        return sum(
            1 for t, r in before.items() if after.get(t) != r
        )
