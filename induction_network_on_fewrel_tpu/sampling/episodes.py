"""Seeded episodic N-way K-shot sampler with NA/NOTA mixing.

Replaces the reference's ``FewRelDataset.__getitem__`` + torch DataLoader
worker processes (SURVEY.md §3.4): on TPU the right shape is a host-side
numpy generator producing fixed-shape, device-ready batches that cross the
jit boundary once per step — no multiprocessing, no collate_fn, no
per-tensor ``.cuda()`` copies.

Episode semantics (SURVEY.md §2.1 "Episodic sampler", FewRel paper):

* draw N distinct relations;
* per relation draw K support + Q query instances without overlap;
* with ``na_rate > 0``, add ``na_rate * Q`` extra queries drawn from
  relations *outside* the episode's N, labeled with class id N
  (none-of-the-above, FewRel 2.0);
* queries are shuffled within the episode.

The whole dataset is tokenized once up front into per-relation array blocks,
so per-episode work is pure integer indexing — fast enough that no worker
processes are needed to keep a v5e fed.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer


class EpisodeBatch(NamedTuple):
    """One batch of B episodes, all int32/float32 numpy, fixed shapes.

    support_*: [B, N, K, L]; query_*: [B, TQ, L]; label: [B, TQ]
    with TQ = N*Q + na_rate*Q.
    """

    support_word: np.ndarray
    support_pos1: np.ndarray
    support_pos2: np.ndarray
    support_mask: np.ndarray
    query_word: np.ndarray
    query_pos1: np.ndarray
    query_pos2: np.ndarray
    query_mask: np.ndarray
    label: np.ndarray


class _RelationBlock(NamedTuple):
    word: np.ndarray  # [M, L] int32
    pos1: np.ndarray
    pos2: np.ndarray
    mask: np.ndarray  # [M, L] float32


def check_episode_feasibility(sizes, n, k, q, na_rate, names=None):
    """Validate that a corpus can furnish N-way K-shot (+NOTA) episodes.

    ``sizes``: per-relation instance counts; ``names``: optional relation
    labels for the error message. The single source of this check — every
    sampler (python/native, token/index) validates through it, so the
    backends accept and reject identical configs.
    """
    need = n + (1 if na_rate > 0 else 0)
    if len(sizes) < need:
        raise ValueError(
            f"need >= {need} relations for N={n} with na_rate={na_rate}, "
            f"got {len(sizes)}"
        )
    for i, m in enumerate(sizes):
        if m < k + q:
            label = names[i] if names is not None else f"#{i}"
            raise ValueError(f"relation {label}: {m} instances < K+Q={k + q}")


class EpisodeSampler:
    def __init__(
        self,
        dataset: FewRelDataset,
        tokenizer: GloveTokenizer,
        n: int,
        k: int,
        q: int,
        batch_size: int = 1,
        na_rate: int = 0,
        seed: int = 0,
    ):
        check_episode_feasibility(
            [len(dataset.instances[r]) for r in dataset.rel_names],
            n, k, q, na_rate, names=dataset.rel_names,
        )
        self.n, self.k, self.q = n, k, q
        self.batch_size, self.na_rate = batch_size, na_rate
        self.rng = np.random.default_rng(seed)
        self.rel_names = dataset.rel_names

        self.blocks: list[_RelationBlock] = []
        for rel in dataset.rel_names:
            toks = [tokenizer(inst) for inst in dataset.instances[rel]]
            self.blocks.append(
                _RelationBlock(
                    np.stack([t.word for t in toks]),
                    np.stack([t.pos1 for t in toks]),
                    np.stack([t.pos2 for t in toks]),
                    np.stack([t.mask for t in toks]),
                )
            )

    @property
    def total_q(self) -> int:
        return self.n * self.q + self.na_rate * self.q

    def _sample_episode(self):
        n, k, q = self.n, self.k, self.q
        rng = self.rng
        rel_ids = rng.choice(len(self.blocks), n, replace=False)

        sup = [[], [], [], []]
        qry = [[], [], [], []]
        labels = []
        for cls, rid in enumerate(rel_ids):
            blk = self.blocks[rid]
            idx = rng.choice(blk.word.shape[0], k + q, replace=False)
            for a, field in zip(sup, blk):
                a.append(field[idx[:k]])
            for a, field in zip(qry, blk):
                a.append(field[idx[k:]])
            labels.extend([cls] * q)

        if self.na_rate > 0:
            # NOTA negatives: sample from relations outside the episode.
            outside = np.setdiff1d(np.arange(len(self.blocks)), rel_ids)
            for _ in range(self.na_rate * q):
                rid = int(rng.choice(outside))
                blk = self.blocks[rid]
                i = int(rng.integers(blk.word.shape[0]))
                for a, field in zip(qry, blk):
                    a.append(field[i : i + 1])
                labels.append(n)

        support = [np.stack(a).reshape(n, k, -1) for a in sup]
        query = [np.concatenate(a, axis=0) for a in qry]
        label = np.asarray(labels, dtype=np.int32)

        perm = rng.permutation(label.shape[0])
        query = [a[perm] for a in query]
        return support, [a for a in query], label[perm]

    def sample_batch(self) -> EpisodeBatch:
        eps = [self._sample_episode() for _ in range(self.batch_size)]
        sup = [np.stack([e[0][f] for e in eps]) for f in range(4)]
        qry = [np.stack([e[1][f] for e in eps]) for f in range(4)]
        label = np.stack([e[2] for e in eps])
        return EpisodeBatch(*sup, *qry, label)

    def __iter__(self) -> Iterator[EpisodeBatch]:
        while True:
            yield self.sample_batch()

    # --- datapipe cursor protocol (datapipe/cursor.py): the generator's
    # bit-generator state IS the stream position — exact O(1) resume.

    def feed_state(self) -> dict:
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            rng_feed_state,
        )

        return rng_feed_state(self.rng)

    def restore_feed_state(self, state: dict) -> None:
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            restore_rng_feed_state,
        )

        restore_rng_feed_state(self.rng, state)


class InstanceBatch(NamedTuple):
    """A batch of M unlabeled instances (domain-adaptation side channel)."""

    word: np.ndarray  # [M, L] int32
    pos1: np.ndarray
    pos2: np.ndarray
    mask: np.ndarray  # [M, L] float32


class InstanceSampler:
    """Uniform unlabeled instance batches from a FewRel-schema dataset.

    Feeds the FewRel 2.0 adversarial adaptation loop: the domain
    discriminator sees (source, target) instance batches with no relation
    labels, so this sampler flattens the dataset across relations and draws
    uniformly. Same host-side discipline as EpisodeSampler: tokenize once
    up front, per-batch work is integer indexing into fixed-shape blocks.
    """

    def __init__(
        self,
        dataset: FewRelDataset,
        tokenizer: GloveTokenizer,
        batch_size: int,
        seed: int = 0,
    ):
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed)
        toks = [
            tokenizer(inst)
            for rel in dataset.rel_names
            for inst in dataset.instances[rel]
        ]
        self.word = np.stack([t.word for t in toks])
        self.pos1 = np.stack([t.pos1 for t in toks])
        self.pos2 = np.stack([t.pos2 for t in toks])
        self.mask = np.stack([t.mask for t in toks])

    def sample_batch(self) -> InstanceBatch:
        idx = self.rng.integers(self.word.shape[0], size=self.batch_size)
        return InstanceBatch(
            self.word[idx], self.pos1[idx], self.pos2[idx], self.mask[idx]
        )

    def __iter__(self) -> Iterator[InstanceBatch]:
        while True:
            yield self.sample_batch()
