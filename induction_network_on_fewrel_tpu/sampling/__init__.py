from induction_network_on_fewrel_tpu.sampling.episodes import (  # noqa: F401
    EpisodeBatch,
    EpisodeSampler,
    InstanceBatch,
    InstanceSampler,
)
