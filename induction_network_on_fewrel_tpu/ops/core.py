"""Small numerical ops shared across modules.

``squash`` is the capsule-network nonlinearity at the heart of the induction
module's dynamic routing (SURVEY.md §2.1 "Induction module":
``squash(x) = ||x||^2/(1+||x||^2) * x/||x||``). The masked reductions keep
padded token positions out of pooling/attention while preserving static
shapes (TPU/XLA discipline: mask, never slice to a dynamic length).

All ops are dtype-polymorphic; squash promotes its norm computation to f32
because ``||x||^2`` underflows fast in bf16.
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -1e30


def squash(x: jnp.ndarray, axis: int = -1, eps: float = 1e-12) -> jnp.ndarray:
    """Capsule squash along ``axis``: scales norm into [0, 1), keeps direction."""
    x32 = x.astype(jnp.float32)
    sq = jnp.sum(jnp.square(x32), axis=axis, keepdims=True)
    scale = sq / (1.0 + sq) / jnp.sqrt(sq + eps)
    return (x32 * scale).astype(x.dtype)


def masked_softmax(scores: jnp.ndarray, mask: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Softmax over ``axis`` treating mask==0 positions as -inf."""
    scores = jnp.where(mask > 0, scores, _NEG_INF)
    scores = scores - jnp.max(scores, axis=axis, keepdims=True)
    e = jnp.exp(scores) * (mask > 0)
    return e / (jnp.sum(e, axis=axis, keepdims=True) + 1e-13)


def masked_max(x: jnp.ndarray, mask: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Max over ``axis`` ignoring mask==0 positions (mask broadcasts to x)."""
    return jnp.max(jnp.where(mask > 0, x, _NEG_INF), axis=axis)


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray, axis: int) -> jnp.ndarray:
    s = jnp.sum(x * (mask > 0), axis=axis)
    return s / (jnp.sum(mask > 0, axis=axis) + 1e-13)


def gradient_reversal(x: jnp.ndarray, scale: float = 1.0) -> jnp.ndarray:
    """Identity forward; gradient multiplied by ``-scale`` on the way back.

    The DANN trick (Ganin & Lempitsky 2015) that lets one optimizer train
    adversary and encoder in a single backward pass: the domain discriminator
    upstream of this op minimizes its loss normally, while everything
    downstream (the sentence encoder) receives the negated gradient and so
    *maximizes* domain confusion. Replaces the reference family's three
    alternating optimizers for FewRel 2.0 adaptation with one jitted step.
    """
    import jax

    @jax.custom_vjp
    def _rev(x):
        return x

    def _fwd(x):
        return x, None

    def _bwd(_, g):
        return (jax.tree_util.tree_map(lambda t: -scale * t, g),)

    _rev.defvjp(_fwd, _bwd)
    return _rev(x)
