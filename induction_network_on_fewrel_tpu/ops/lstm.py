"""LSTM recurrence: `lax.scan` reference + fused Pallas TPU kernel.

The BiLSTM is the FLOPs-dominant op of the flagship encoder (SURVEY.md §3.2
"lstm fwd+bwd over L — sequential scan, HOT"). The TPU-shaped decomposition:

1. The input projection ``xg = emb @ W_ih + b`` has no sequential dependency,
   so it is hoisted OUT of the recurrence into one large [M*L, D] x [D, 4u]
   MXU matmul that XLA schedules freely.
2. Only the true recurrence ``a_t = xg_t + h_{t-1} @ W_hh`` runs per-step.
   The Pallas kernel keeps h/c (and the [u, 4u] recurrent weights) resident
   in VMEM across the whole time loop — one kernel for all L steps per row
   tile, instead of L dispatches with h/c bouncing through HBM.
3. The backward pass is a second Pallas kernel scanning time in reverse.
   The forward saves only h/c residuals (2u per row-step); the backward
   RECOMPUTES the gate activations from xg + h_{t-1} @ whh — one extra
   MXU matmul per step in exchange for 3x less forward HBM write traffic
   (the kernel is bandwidth-bound, not FLOP-bound).

**Grouped recurrence** (the bidirectional case): ``lstm_recurrence_grouped``
takes ``xg [Gc, M, L, 4u]`` and per-group weights ``whh [Gc, u, 4u]`` and
runs ALL groups in ONE kernel call. Each group's rows are padded to the row
tile independently, so a tile never straddles groups, and the BlockSpec
index map picks the group's weight slab (``i // tiles_per_group``) — the
per-step matmul shape is unchanged vs the shared-weight layout. This is how
the BiLSTM gives its forward and backward directions INDEPENDENT recurrent
weights (torch ``nn.LSTM(bidirectional=True)`` has separate ``*_reverse``
tensors) without giving up the fused single-dispatch structure.

**Time-major recurrence** (``bilstm_recurrence_tm`` — the production
encoder path): same kernel bodies, but the input is the natural-time
[L, M, 8u] direction-concatenated projection and the per-direction time
reversal + direction-slab select live entirely in the BlockSpec index maps.
The grouped entry's host-side stack/flip/pad/transpose pipeline (profiled
at ~25% of headline device time) disappears, the hidden states come out
already concatenated [L, M, 2u] in natural time order, and the row tile is
chosen per shape to divide M exactly when possible (``_pick_tm``), removing
the pad copies too.

Gate order is [i, f, g, o] (sigmoid, sigmoid, tanh, sigmoid) — the same
convention as torch.nn.LSTM, which the golden test exploits. All recurrence
arithmetic is float32: bf16 cell state drifts over long sequences.

``lstm_recurrence(xg, whh, backend=...)`` selects: "scan" (pure XLA,
differentiable by tracing), "pallas" (compiled TPU kernel, custom VJP), or
"interpret" (Pallas interpreter — same kernel code, runs on CPU; used by the
test suite so the kernel logic is exercised without a chip).

Gradient-precision note (bf16 mode): the backward kernel recomputes gate
activations from the bf16-rounded hs/cs residuals while the forward
recurrence ran on f32 VMEM state, so the returned cotangents are gradients
of a slightly different (bf16-rounded) forward — an intentional bandwidth
tradeoff. Measured mean relative grad error is ~10-15% on random inputs
(tests/test_lstm.py::test_pallas_bf16_io_close_to_f32); training-quality
parity should be monitored via final val accuracy in bf16 runs, not only
throughput. The f32 path is exact to 1e-5 against `lax.scan`.

**Not saving ``cs`` via inversion — evaluated and REJECTED (round 6).**
Dropping the cell-state residual and reconstructing it from saved hs
requires the inversion ``c_t = atanh(h_t / o_t)`` — ill-conditioned
exactly where LSTMs live: d(atanh x)/dx = cosh²(c), so a 1-ulp rounding
of h inflates to a cell error of eps·cosh²(c) (~20 ABSOLUTE at c = 10,
f32), and for |c| ≳ 8.3 tanh(c) rounds to ±1.0 in f32 and the inversion
returns inf — while the factor da_f = dc_t·c_prev·f·(1-f) it feeds is
NOT zero there. Measured on a saturating sequence (tests/test_lstm.py::
test_cs_recompute_from_hs_rejected): reconstruction error exceeds 1.0
absolute within 40 steps of a forget-dominant cell.

**Windowed-cs remat (round 8 — the sound alternative, landed).** The
fused encoder path (``bilstm_encoder_tm``) accepts ``cs_window = W > 0``:
the forward writes hs (the user-facing output) plus one (h, c)
CHECKPOINT PAIR per W-step window — the state at each window's
kernel-last step — and no full residual streams at all. The backward is
a dual-sweep kernel: on entering a window (walking kernel time
backwards) it re-runs the forward recurrence ASCENDING from the
checkpoint seed, holding the window's (h, c) in VMEM scratch, then the
per-step gradient sweep reads cell state and h_prev from that scratch
instead of HBM. Recompute ascends FORWARD from a saved seed — the exact
opposite of the rejected atanh inversion, so the conditioning argument
above does not apply (in f32 the recomputed cells are the forward's own
arithmetic replayed; parity vs lax.scan stays at 1e-5 for any W —
tests/test_lstm.py window sweep {1, 8, T}, T % W != 0 included). Flagship
bytes (utils/roofline.py, W=8 bf16 residuals): kernel fwd 146 -> 97,
kernel bwd 227 -> 113 MB/step — the backward streams only d(hs), the
checkpoints, and the embedding block, which the recompute and gradient
sweeps share from VMEM. Windows are defined as NATURAL-time blocks so
both directions' residual reads stay block-aligned (a kernel-time
window of the reverse direction is exactly a natural-time block read
backwards); the last block is ragged when W does not divide L and the
kernel masks it. ``cs_window = 0`` keeps the round-6 full-cs design
(the A/B twin).

**Residual dtype (``residual_dtype``)**: the checkpoint pairs (windowed
mode) or the cs stream (full-cs mode) are stored in this dtype — bf16
halves their HBM traffic independently of the compute dtype; all VMEM
carries and the in-window recompute stay f32, so bf16 residuals round
only the window SEEDS (vs every step in the round-6 bf16 path). Policed
at run time by the --grad_probe_every grad-cosine machinery
(train/steps.py) and bounded in tests/test_lstm.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-tile size. 128 matches the MXU systolic dimension; smaller inputs are
# padded up to one tile (fine: the flagship config runs M = 800 rows).
_TM = 128


def _gates(a: jnp.ndarray, u: int):
    i = jax.nn.sigmoid(a[..., 0 * u : 1 * u])
    f = jax.nn.sigmoid(a[..., 1 * u : 2 * u])
    g = jnp.tanh(a[..., 2 * u : 3 * u])
    o = jax.nn.sigmoid(a[..., 3 * u : 4 * u])
    return i, f, g, o


# ---------------------------------------------------------------------------
# Reference implementation: lax.scan (differentiable through tracing).
# ---------------------------------------------------------------------------


def lstm_scan(xg: jnp.ndarray, whh: jnp.ndarray) -> jnp.ndarray:
    """([M, L, 4u] pre-projected inputs, [u, 4u]) -> hidden states [M, L, u].

    Zero initial state; float32 recurrence regardless of input dtype.
    """
    M, L, G = xg.shape
    u = G // 4
    xg32 = xg.astype(jnp.float32)
    whh32 = whh.astype(jnp.float32)

    def step(carry, x_t):
        h, c = carry
        a = x_t + h @ whh32
        i, f, g, o = _gates(a, u)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    init = (jnp.zeros((M, u), jnp.float32), jnp.zeros((M, u), jnp.float32))
    _, hs = jax.lax.scan(step, init, jnp.swapaxes(xg32, 0, 1))  # [L, M, u]
    return jnp.swapaxes(hs, 0, 1)


# ---------------------------------------------------------------------------
# Pallas kernels. whh blocks are [1, u, 4u]: the leading axis is the GROUP
# axis (e.g. BiLSTM direction), selected per row tile by the index map.
# ---------------------------------------------------------------------------


def _fwd_kernel(xg_ref, whh_ref, hs_ref, cs_ref, h_scr, c_scr):
    # All tensor blocks are TIME-MAJOR [1, TM, *]: the iterated (time) axis
    # must be a leading block dim of size 1 — the TPU lowering constrains
    # only the LAST TWO block dims to (8k, 128k)-divisible-or-full, which a
    # middle time axis of block 1 violates (bench-caught on real v5e).
    #
    # Training forward. Residuals written to HBM are hs and cs ONLY (2u per
    # row-step); the gate activations (4u more) are NOT saved — the backward
    # kernel recomputes them from xg + h_{t-1} @ whh, one extra MXU matmul
    # per step. The kernel is HBM-bandwidth-bound, not FLOP-bound, so
    # trading a matmul for 3x less forward write traffic is a clear win
    # (measured ~1.2x end-to-end on the tunneled v5e).
    t = pl.program_id(1)
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    a = xg_ref[0].astype(jnp.float32) + jnp.dot(
        h_scr[...], whh_ref[0], preferred_element_type=jnp.float32
    )
    i, f, g, o = _gates(a, u)
    c = f * c_scr[...] + i * g
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    hs_ref[0] = h.astype(hs_ref.dtype)
    cs_ref[0] = c.astype(cs_ref.dtype)


def _fwd_kernel_infer(xg_ref, whh_ref, hs_ref, h_scr, c_scr):
    """hs-only forward for inference: no cs/gates residuals leave VMEM.

    The custom-VJP primal runs this variant — pallas_call is opaque to XLA,
    so dead residual outputs in the training kernel could not be DCE'd and
    would cost ~5x the output bytes on every no-grad call (eval episodes).
    """
    t = pl.program_id(1)
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    a = xg_ref[0].astype(jnp.float32) + jnp.dot(
        h_scr[...], whh_ref[0], preferred_element_type=jnp.float32
    )
    i, f, g, o = _gates(a, u)
    c = f * c_scr[...] + i * g
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    hs_ref[0] = h.astype(hs_ref.dtype)


def _bwd_kernel(
    dhs_ref, xg_ref, cs_ref, cs_prev_ref, hs_prev_ref, whh_ref,
    dxg_ref, dwhh_ref, dh_scr, dc_scr, dwhh_scr,
):
    t = pl.program_id(1)
    L = pl.num_programs(1)
    rt = L - 1 - t  # walking time backwards
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)
        dwhh_scr[...] = jnp.zeros_like(dwhh_scr)

    c_t = cs_ref[0].astype(jnp.float32)
    tc = jnp.tanh(c_t)
    # The rt-1 index maps clamp at 0; mask the rt == 0 step to the true
    # zero initial state.
    first = (rt == 0).astype(jnp.float32)
    c_prev = cs_prev_ref[0].astype(jnp.float32) * (1.0 - first)
    h_prev = hs_prev_ref[0].astype(jnp.float32) * (1.0 - first)

    # Recompute the gate activations the forward did not save: one extra
    # [TM, u] x [u, 4u] matmul instead of reading 4u residuals from HBM.
    a = xg_ref[0].astype(jnp.float32) + jnp.dot(
        h_prev, whh_ref[0], preferred_element_type=jnp.float32
    )
    i, f, g, o = _gates(a, u)

    dh_t = dhs_ref[0].astype(jnp.float32) + dh_scr[...]
    da_o = dh_t * tc * o * (1.0 - o)
    dct = dc_scr[...] + dh_t * o * (1.0 - tc * tc)
    da_i = dct * g * i * (1.0 - i)
    da_g = dct * i * (1.0 - g * g)
    da_f = dct * c_prev * f * (1.0 - f)
    da = jnp.concatenate([da_i, da_f, da_g, da_o], axis=-1)  # [TM, 4u]

    dxg_ref[0] = da.astype(dxg_ref.dtype)
    dh_scr[...] = jax.lax.dot_general(
        da, whh_ref[0], (((1,), (1,)), ((), ())),  # da @ whh^T
        preferred_element_type=jnp.float32,
    )
    dc_scr[...] = dct * f
    dwhh_scr[...] += jax.lax.dot_general(
        h_prev, da, (((0,), (0,)), ((), ())),  # h_prev^T @ da
        preferred_element_type=jnp.float32,
    )
    dwhh_ref[0] = dwhh_scr[...]


def _to_time_major(x: jnp.ndarray):
    """[Gc, M, L, *] -> time-major padded [L, Gc*Mp, *].

    Each group is padded to the row tile INDEPENDENTLY so a tile never
    straddles two groups — the per-tile weight index map relies on this.
    """
    Gc, M, L = x.shape[:3]
    pad = (-M) % _TM
    if pad:
        widths = ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2)
        x = jnp.pad(x, widths)
    Mp = M + pad
    flat = x.reshape((Gc * Mp, L) + x.shape[3:])
    return jnp.swapaxes(flat, 0, 1), Mp


def _from_time_major(x_t: jnp.ndarray, Gc: int, M: int):
    """Inverse of _to_time_major: [L, Gc*Mp, *] -> [Gc, M, L, *]."""
    L, GMp = x_t.shape[:2]
    Mp = GMp // Gc
    flat = jnp.swapaxes(x_t, 0, 1)  # [Gc*Mp, L, *]
    return flat.reshape((Gc, Mp, L) + x_t.shape[2:])[:, :M]


def _fwd_call(xg: jnp.ndarray, whh: jnp.ndarray, interpret: bool):
    """Grouped forward. xg [Gc, M, L, 4u], whh [Gc, u, 4u] -> (hs
    [Gc, M, L, u], residuals xg_t/hs_t/cs_t all TIME-MAJOR [L, Gc*Mp, *]).
    Gate activations are recomputed in the backward kernel.

    Dtype-polymorphic: hs/cs residuals and outputs carry xg's dtype (the
    VMEM recurrence math is always float32). In bf16 compute mode that
    halves the kernel's HBM traffic and removes the f32<->bf16 convert
    passes XLA otherwise wraps around the kernel; in f32 mode nothing
    changes (golden tests pin that path at 1e-5)."""
    Gc, M, L, G = xg.shape
    u = G // 4
    dt = xg.dtype
    xg_t, Mp = _to_time_major(xg)  # [L, Gc*Mp, G]
    H = Mp // _TM  # row tiles per group
    grid = (Gc * H, L)
    out = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TM, G), lambda i, t: (t, i, 0)),
            pl.BlockSpec((1, u, G), lambda i, t: (i // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TM, u), lambda i, t: (t, i, 0)),
            pl.BlockSpec((1, _TM, u), lambda i, t: (t, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, Gc * Mp, u), dt),  # hs
            jax.ShapeDtypeStruct((L, Gc * Mp, u), dt),  # cs
        ],
        scratch_shapes=[
            pltpu.VMEM((_TM, u), jnp.float32),
            pltpu.VMEM((_TM, u), jnp.float32),
        ],
        interpret=interpret,
    )(xg_t, whh.astype(jnp.float32))
    hs, cs = out
    # Residuals stay time-major/padded — the backward kernel consumes them
    # as-is; only the user-facing hs is transposed back.
    return _from_time_major(hs, Gc, M), xg_t, hs, cs


def _fwd_call_infer(xg: jnp.ndarray, whh: jnp.ndarray, interpret: bool):
    Gc, M, L, G = xg.shape
    u = G // 4
    xg_t, Mp = _to_time_major(xg)  # [L, Gc*Mp, G]
    H = Mp // _TM
    grid = (Gc * H, L)
    hs = pl.pallas_call(
        _fwd_kernel_infer,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TM, G), lambda i, t: (t, i, 0)),
            pl.BlockSpec((1, u, G), lambda i, t: (i // H, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TM, u), lambda i, t: (t, i, 0)),
        out_shape=jax.ShapeDtypeStruct((L, Gc * Mp, u), xg.dtype),
        scratch_shapes=[
            pltpu.VMEM((_TM, u), jnp.float32),
            pltpu.VMEM((_TM, u), jnp.float32),
        ],
        interpret=interpret,
    )(xg_t, whh.astype(jnp.float32))
    return _from_time_major(hs, Gc, M)


def _bwd_call(dhs, xg_t, cs_t, hs_t, whh, interpret: bool):
    """dhs: [Gc, M, L, u] cotangent; xg_t/cs_t/hs_t: TIME-MAJOR padded
    residuals [L, Gc*Mp, *] straight from the forward call."""
    Gc, M, L, u = dhs.shape
    G = 4 * u
    dhs_t, Mp = _to_time_major(dhs)  # [L, Gc*Mp, u]
    H = Mp // _TM
    ntiles = Gc * H
    grid = (ntiles, L)
    rev = lambda i, t: (L - 1 - t, i, 0)           # noqa: E731
    rev_prev = lambda i, t: (max_0(L - 2 - t), i, 0)  # noqa: E731
    dxg, dwhh_p = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TM, u), rev),       # dhs
            pl.BlockSpec((1, _TM, G), rev),       # xg (gates recomputed)
            pl.BlockSpec((1, _TM, u), rev),       # cs
            pl.BlockSpec((1, _TM, u), rev_prev),  # cs_{t-1} (clamped)
            pl.BlockSpec((1, _TM, u), rev_prev),  # hs_{t-1} (clamped)
            pl.BlockSpec((1, u, G), lambda i, t: (i // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TM, G), rev),
            pl.BlockSpec((1, u, G), lambda i, t: (i, 0, 0)),
        ],
        out_shape=[
            # dxg matches xg's dtype (the custom-VJP contract); dwhh stays
            # f32 — it is the cotangent of the f32 weight param.
            jax.ShapeDtypeStruct((L, Gc * Mp, G), xg_t.dtype),
            jax.ShapeDtypeStruct((ntiles, u, G), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TM, u), jnp.float32),
            pltpu.VMEM((_TM, u), jnp.float32),
            pltpu.VMEM((u, G), jnp.float32),
        ],
        interpret=interpret,
        # cs appears twice: once at rt, once at rt-1 (separate index maps).
    )(dhs_t, xg_t, cs_t, cs_t, hs_t, whh.astype(jnp.float32))
    dwhh = dwhh_p.reshape(Gc, H, u, G).sum(axis=1)  # per-group tile sums
    return _from_time_major(dxg, Gc, M), dwhh


def max_0(v):
    """Clamp a (possibly traced) index to >= 0 for prev-step block maps."""
    return jnp.maximum(v, 0)


# Dtype-polymorphic custom VJP on GROUPED shapes: hs (and dxg) carry xg's
# dtype; whh and dwhh are always float32 (the param dtype). The VMEM
# recurrence math is float32 in every mode.
@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _lstm_pallas(xg, whh, interpret=False):
    # Primal (no-grad) path: hs-only kernel, no residuals to HBM. Under
    # jax.grad the fwd rule below runs instead and saves residuals.
    return _fwd_call_infer(xg, whh, interpret)


def _lstm_pallas_fwd(xg, whh, interpret):
    hs, xg_t, hs_t, cs_t = _fwd_call(xg, whh, interpret)
    return hs, (xg_t, hs_t, cs_t, whh)


def _lstm_pallas_bwd(interpret, res, dhs):
    xg_t, hs_t, cs_t, whh = res
    return _bwd_call(dhs, xg_t, cs_t, hs_t, whh, interpret)


_lstm_pallas.defvjp(_lstm_pallas_fwd, _lstm_pallas_bwd)


def lstm_recurrence_grouped(
    xg: jnp.ndarray, whh: jnp.ndarray, backend: str = "scan"
) -> jnp.ndarray:
    """Run Gc independent LSTM recurrences with per-group weights.

    xg: [Gc, M, L, 4u] pre-projected gate inputs; whh: [Gc, u, 4u].
    Returns hidden states [Gc, M, L, u]. All groups run in ONE Pallas
    dispatch (the weight index map picks the group slab per row tile), so
    the BiLSTM's two directions cost one kernel call, same as the old
    shared-weight layout — but with independent parameters per direction.
    """
    if backend == "scan":
        return jax.vmap(lstm_scan)(xg, whh)
    if backend == "pallas":
        return _lstm_pallas(xg, whh.astype(jnp.float32), False)
    if backend == "interpret":
        return _lstm_pallas(xg, whh.astype(jnp.float32), True)
    raise ValueError(f"unknown lstm backend {backend!r}")


# ---------------------------------------------------------------------------
# Time-major bidirectional entry. The grouped API above wants [Gc, M, L, 4u]
# with the reverse direction's gates pre-flipped in time — building that
# layout from the encoder's natural [M, L, 8u] projection cost a stack, a
# flip, a pad and a [*, 512]-wide transpose per encoder call (profiled at
# ~25% of headline device time, tools/profile_headline.py). Here the SAME
# kernel bodies run over a natural-time [L, M, 8u] array: the per-direction
# time reversal and the direction-slab select live entirely in the BlockSpec
# index maps (block col g picks the direction's 4u gate columns; block time
# is t for the forward direction and L-1-t for the reverse), and the hidden
# states come out already direction-concatenated [L, M, 2u] in natural time
# order. No data movement outside the kernel at all beyond a row pad to the
# tile size.
# ---------------------------------------------------------------------------


def _pick_tm(M: int, u: int, itemsize: int, D: int = 0, W: int = 0) -> int:
    """Row-tile for the time-major kernels: avoid padding when possible.

    The TPU grid runs sequentially (pipelined), so fewer, larger row tiles
    are strictly better until VMEM pressure — and a tile that divides M
    exactly (or covers the full row axis, which the (8,128)-divisibility
    rule exempts) removes the M -> ceil(M/128)*128 pad copies entirely
    (profiled at ~10% of headline device time at M=1600). Candidates are
    sublane-aligned divisors of M plus the full axis, capped by a bwd-kernel
    VMEM estimate; fallback is the classic pad-to-_TM path.

    ``D > 0`` models the FUSED projection+recurrence backward (the caller
    is bilstm_encoder_tm): its kernel additionally holds emb/demb [tm, D]
    blocks, the wih/b/whh weight blocks with their f32 cotangent outputs,
    and (D, 4u)+(1, 4u) accumulator scratch. ``D = 0`` models the split
    recurrence backward (xg in + dxg out). At the flagship shape the 8 MB
    cap's slack absorbed the difference, but a larger embedding dim could
    otherwise pick a tile that exceeds VMEM at compile time (advisor
    finding, round 3).

    ``W > 0`` models the WINDOWED-CS fused backward (cs_window): the emb
    block becomes a [W, tm, D] window, the per-step [tm, u] cs/hs-prev
    residual blocks are replaced by two [1, tm, u] checkpoint blocks, and
    the recompute holds the window's (h, c) in two [W, tm, u] f32
    scratches — at W = L (full recompute) the scratch term dominates and
    this model is what clamps tm instead of the compiler faulting.
    """
    q = 16 if itemsize == 2 else 8
    cap = 8 * 2**20  # leave VMEM headroom for the compiler's own buffers

    def fits(tm: int) -> bool:
        G = 4 * u
        if D and W:
            # windowed fused bwd, double-buffered: dhs [tm, u] + 2x ckpt
            # [tm, u] ins, emb window [W, tm, D] in + demb [tm, D] out,
            # weight ins with f32 cot outs; scratch adds the window's
            # (h, c) pair [W, tm, u] f32 each.
            blocks = (3 * tm * u + (W + 1) * tm * D) * itemsize * 2
            blocks += (D * G + G + u * G) * (itemsize + 4) * 2
            scratch = (2 * tm * u + u * G + D * G + G) * 4
            scratch += 2 * W * tm * u * 4
        elif D:
            # fused bwd, double-buffered: 4x [tm, u] state/cot ins, emb in
            # + demb out [tm, D], weight ins (emb-dtype wih + f32 b/whh ~
            # itemsize each, conservatively f32) with f32 dwih/db/dwhh
            # outs; scratch includes the dwih/db accumulators.
            blocks = (4 * tm * u + 2 * tm * D) * itemsize * 2
            blocks += (D * G + G + u * G) * (itemsize + 4) * 2
            scratch = (2 * tm * u + u * G + D * G + G) * 4
        else:
            # split bwd: 4x [tm, u] state/cot ins, [tm, 4u] xg in + dxg
            # out, plus f32 scratch 2x[tm, u] + [u, 4u].
            blocks = (4 * tm * u + 2 * tm * G) * itemsize * 2
            scratch = (2 * tm * u + u * G) * 4
        return blocks + scratch <= cap

    cands = [tm for tm in range(q, min(M, 1024) + 1, q) if M % tm == 0 and fits(tm)]
    if M <= 1024 and fits(M):
        cands.append(M)  # full-axis block: no divisibility constraint
    return max(cands) if cands else _TM


def _tm_dims(xg_t: jnp.ndarray, whh: jnp.ndarray, tm: int):
    L, Mp, G2 = xg_t.shape
    Gc, u, G = whh.shape
    if G2 != Gc * G:
        raise ValueError(f"xg last dim {G2} != Gc*4u {Gc * G}")
    H = Mp // tm
    return L, Mp, Gc, u, G, H


def _tm_fwd_specs(L, u, G, H, tm):
    def xg_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, L - 1 - t, t), i % H, g)

    whh_idx = lambda i, t: (i // H, 0, 0)  # noqa: E731
    out_idx = xg_idx  # hs/cs blocks: same (nat-time, row, direction) walk
    in_specs = [
        pl.BlockSpec((1, tm, G), xg_idx),
        pl.BlockSpec((1, u, G), whh_idx),
    ]
    out_spec = pl.BlockSpec((1, tm, u), out_idx)
    return in_specs, out_spec


def _fwd_call_tm(xg_t: jnp.ndarray, whh: jnp.ndarray, interpret: bool, tm: int):
    L, Mp, Gc, u, G, H = _tm_dims(xg_t, whh, tm)
    dt = xg_t.dtype
    in_specs, out_spec = _tm_fwd_specs(L, u, G, H, tm)
    hs, cs = pl.pallas_call(
        _fwd_kernel,
        grid=(Gc * H, L),
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((L, Mp, Gc * u), dt),  # hs, nat time
            jax.ShapeDtypeStruct((L, Mp, Gc * u), dt),  # cs, nat time
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
        ],
        interpret=interpret,
    )(xg_t, whh.astype(jnp.float32))
    return hs, cs


def _fwd_call_tm_infer(xg_t: jnp.ndarray, whh: jnp.ndarray, interpret: bool, tm: int):
    L, Mp, Gc, u, G, H = _tm_dims(xg_t, whh, tm)
    in_specs, out_spec = _tm_fwd_specs(L, u, G, H, tm)
    return pl.pallas_call(
        _fwd_kernel_infer,
        grid=(Gc * H, L),
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((L, Mp, Gc * u), xg_t.dtype),
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
        ],
        interpret=interpret,
    )(xg_t, whh.astype(jnp.float32))


def _bwd_call_tm(dhs, xg_t, cs, hs, whh, interpret: bool, tm: int):
    """All tensors natural-time: dhs [L, Mp, Gc*u]; xg_t [L, Mp, Gc*4u];
    cs/hs the forward's residuals [L, Mp, Gc*u]."""
    L, Mp, Gc, u, G, H = _tm_dims(xg_t, whh, tm)
    ntiles = Gc * H

    # Backward grid step t undoes kernel time kt = L-1-t. The natural-time
    # position of kt is kt for the forward direction and L-1-kt = t for the
    # reverse one; the prev-state (kernel time kt-1) position clamps at the
    # sequence edge, where the kernel masks the state to zero anyway.
    def p_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, t, L - 1 - t), i % H, g)

    def p_prev_idx(i, t):
        g = i // H
        nat = jnp.where(
            g == 1, jnp.minimum(t + 1, L - 1), jnp.maximum(L - 2 - t, 0)
        )
        return (nat, i % H, g)

    whh_idx = lambda i, t: (i // H, 0, 0)  # noqa: E731
    dxg, dwhh_p = pl.pallas_call(
        _bwd_kernel,
        grid=(ntiles, L),
        in_specs=[
            pl.BlockSpec((1, tm, u), p_idx),       # dhs
            pl.BlockSpec((1, tm, G), p_idx),       # xg (gates recomputed)
            pl.BlockSpec((1, tm, u), p_idx),       # cs
            pl.BlockSpec((1, tm, u), p_prev_idx),  # cs_{kt-1}
            pl.BlockSpec((1, tm, u), p_prev_idx),  # hs_{kt-1}
            pl.BlockSpec((1, u, G), whh_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, tm, G), p_idx),
            pl.BlockSpec((1, u, G), lambda i, t: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, Mp, Gc * G), xg_t.dtype),
            jax.ShapeDtypeStruct((ntiles, u, G), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((u, G), jnp.float32),
        ],
        interpret=interpret,
    )(dhs, xg_t, cs, cs, hs, whh.astype(jnp.float32))
    dwhh = dwhh_p.reshape(Gc, H, u, G).sum(axis=1)
    return dxg, dwhh


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _bilstm_pallas_tm(xg_t, whh, interpret=False, tm=_TM):
    return _fwd_call_tm_infer(xg_t, whh, interpret, tm)


def _bilstm_tm_fwd(xg_t, whh, interpret, tm):
    hs, cs = _fwd_call_tm(xg_t, whh, interpret, tm)
    return hs, (xg_t, hs, cs, whh)


def _bilstm_tm_bwd(interpret, tm, res, dhs):
    xg_t, hs, cs, whh = res
    return _bwd_call_tm(dhs, xg_t, cs, hs, whh, interpret, tm)


_bilstm_pallas_tm.defvjp(_bilstm_tm_fwd, _bilstm_tm_bwd)


def bilstm_recurrence_tm(
    xg_t: jnp.ndarray, whh: jnp.ndarray, backend: str = "scan"
) -> jnp.ndarray:
    """Bidirectional recurrence over natural-time gate inputs.

    xg_t: [L, M, 8u] — the direction-concatenated input projection in
    natural time order (cols [0:4u] forward gates, [4u:8u] reverse gates;
    the reverse direction is NOT pre-flipped — the kernel walks it
    backwards via its index maps). whh: [2, u, 4u] per-direction recurrent
    weights. Returns [L, M, 2u]: both directions' hidden states in natural
    time order (cols [0:u] forward, [u:2u] reverse), in xg's dtype for the
    pallas/interpret backends and float32 for scan.
    """
    L, M, G2 = xg_t.shape
    Gc, u, G = whh.shape
    if backend == "scan":
        fwd = jnp.swapaxes(xg_t[..., :G], 0, 1)                # [M, L, 4u]
        bwd = jnp.swapaxes(jnp.flip(xg_t[..., G:], 0), 0, 1)   # reversed
        h_f = lstm_scan(fwd, whh[0])
        h_b = jnp.flip(lstm_scan(bwd, whh[1]), axis=1)         # nat time
        return jnp.swapaxes(jnp.concatenate([h_f, h_b], -1), 0, 1)
    if backend not in ("pallas", "interpret"):
        raise ValueError(f"unknown lstm backend {backend!r}")
    tm = _pick_tm(M, u, jnp.dtype(xg_t.dtype).itemsize)
    pad = (-M) % tm
    if pad:
        xg_t = jnp.pad(xg_t, ((0, 0), (0, pad), (0, 0)))
    out = _bilstm_pallas_tm(
        xg_t, whh.astype(jnp.float32), backend == "interpret", tm
    )
    return out[:, :M] if pad else out


# ---------------------------------------------------------------------------
# Fully-fused time-major BiLSTM: input projection + recurrence in ONE kernel.
# The split design materializes the projected gates xg [L, M, 8u] in HBM
# (262 MB bf16 at the headline shape) and then streams them through the
# recurrence kernel forward AND backward, plus separate dxg / dW / db
# passes — profiled at >50% of remaining step time, all bandwidth. Here the
# kernels read the D-wide embedding block (D=60: ~17x fewer bytes than 8u),
# compute the gate pre-activations on the fly (one extra [tm, D] x [D, 4u]
# MXU matmul per step), and accumulate dW_ih / db / dW_hh in VMEM scratch —
# xg, dxg, and the dW/db reduction passes never exist in HBM at all.
# ---------------------------------------------------------------------------


def _fused_fwd_kernel(emb_ref, wih_ref, b_ref, whh_ref, hs_ref, cs_ref, h_scr, c_scr):
    t = pl.program_id(1)
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    a = (
        jnp.dot(emb_ref[0], wih_ref[0], preferred_element_type=jnp.float32)
        + b_ref[0]
        + jnp.dot(h_scr[...], whh_ref[0], preferred_element_type=jnp.float32)
    )
    i, f, g, o = _gates(a, u)
    c = f * c_scr[...] + i * g
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    hs_ref[0] = h.astype(hs_ref.dtype)
    cs_ref[0] = c.astype(cs_ref.dtype)


def _fused_fwd_kernel_infer(emb_ref, wih_ref, b_ref, whh_ref, hs_ref, h_scr, c_scr):
    t = pl.program_id(1)
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    a = (
        jnp.dot(emb_ref[0], wih_ref[0], preferred_element_type=jnp.float32)
        + b_ref[0]
        + jnp.dot(h_scr[...], whh_ref[0], preferred_element_type=jnp.float32)
    )
    i, f, g, o = _gates(a, u)
    c = f * c_scr[...] + i * g
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    hs_ref[0] = h.astype(hs_ref.dtype)


def _fused_bwd_kernel(
    dhs_ref, emb_ref, cs_ref, cs_prev_ref, hs_prev_ref, wih_ref, b_ref, whh_ref,
    demb_ref, dwih_ref, db_ref, dwhh_ref,
    dh_scr, dc_scr, dwih_scr, db_scr, dwhh_scr,
):
    t = pl.program_id(1)
    L = pl.num_programs(1)
    rt = L - 1 - t  # kernel time being undone
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)
        dwih_scr[...] = jnp.zeros_like(dwih_scr)
        db_scr[...] = jnp.zeros_like(db_scr)
        dwhh_scr[...] = jnp.zeros_like(dwhh_scr)

    c_t = cs_ref[0].astype(jnp.float32)
    tc = jnp.tanh(c_t)
    first = (rt == 0).astype(jnp.float32)
    c_prev = cs_prev_ref[0].astype(jnp.float32) * (1.0 - first)
    h_prev = hs_prev_ref[0].astype(jnp.float32) * (1.0 - first)

    emb = emb_ref[0]
    a = (
        jnp.dot(emb, wih_ref[0], preferred_element_type=jnp.float32)
        + b_ref[0]
        + jnp.dot(h_prev, whh_ref[0], preferred_element_type=jnp.float32)
    )
    i, f, g, o = _gates(a, u)

    dh_t = dhs_ref[0].astype(jnp.float32) + dh_scr[...]
    da_o = dh_t * tc * o * (1.0 - o)
    dct = dc_scr[...] + dh_t * o * (1.0 - tc * tc)
    da_i = dct * g * i * (1.0 - i)
    da_g = dct * i * (1.0 - g * g)
    da_f = dct * c_prev * f * (1.0 - f)
    da = jnp.concatenate([da_i, da_f, da_g, da_o], axis=-1)  # [tm, 4u]

    demb_ref[0, 0] = jax.lax.dot_general(
        da, wih_ref[0], (((1,), (1,)), ((), ())),  # da @ wihᵀ -> [tm, D]
        preferred_element_type=jnp.float32,
    ).astype(demb_ref.dtype)
    dwih_scr[...] += jax.lax.dot_general(
        emb.astype(jnp.float32), da, (((0,), (0,)), ((), ())),  # embᵀ @ da
        preferred_element_type=jnp.float32,
    )
    db_scr[...] += jnp.sum(da, axis=0, keepdims=True)
    dh_scr[...] = jax.lax.dot_general(
        da, whh_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dc_scr[...] = dct * f
    dwhh_scr[...] += jax.lax.dot_general(
        h_prev, da, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dwih_ref[0] = dwih_scr[...]
    db_ref[0] = db_scr[...]
    dwhh_ref[0] = dwhh_scr[...]


def _fused_specs(L, D, u, G, H, tm):
    def emb_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, L - 1 - t, t), i % H, 0)

    def out_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, L - 1 - t, t), i % H, g)

    per_dir = lambda i, t: (i // H, 0, 0)  # noqa: E731
    in_specs = [
        pl.BlockSpec((1, tm, D), emb_idx),
        pl.BlockSpec((1, D, G), per_dir),   # wih
        pl.BlockSpec((1, 1, G), per_dir),   # bias
        pl.BlockSpec((1, u, G), per_dir),   # whh
    ]
    return in_specs, out_idx, emb_idx, per_dir


def _fused_fwd_call(emb_t, wih, b, whh, interpret: bool, tm: int, res_dt=None):
    L, Mp, D = emb_t.shape
    Gc, u, G = whh.shape
    H = Mp // tm
    dt = emb_t.dtype
    in_specs, out_idx, _, _ = _fused_specs(L, D, u, G, H, tm)
    out_spec = pl.BlockSpec((1, tm, u), out_idx)
    hs, cs = pl.pallas_call(
        _fused_fwd_kernel,
        grid=(Gc * H, L),
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((L, Mp, Gc * u), dt),
            # cs is residual-only: it may ride a narrower dtype than the
            # user-facing hs (cs_window=0 + residual_dtype=bf16 mode).
            jax.ShapeDtypeStruct((L, Mp, Gc * u), res_dt or dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
        ],
        interpret=interpret,
    )(emb_t, wih, b, whh.astype(jnp.float32))
    return hs, cs


def _fused_fwd_call_infer(emb_t, wih, b, whh, interpret: bool, tm: int):
    L, Mp, D = emb_t.shape
    Gc, u, G = whh.shape
    H = Mp // tm
    in_specs, out_idx, _, _ = _fused_specs(L, D, u, G, H, tm)
    return pl.pallas_call(
        _fused_fwd_kernel_infer,
        grid=(Gc * H, L),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, tm, u), out_idx),
        out_shape=jax.ShapeDtypeStruct((L, Mp, Gc * u), emb_t.dtype),
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
        ],
        interpret=interpret,
    )(emb_t, wih, b, whh.astype(jnp.float32))


def _fused_bwd_call(dhs, emb_t, cs, hs, wih, b, whh, interpret: bool, tm: int):
    L, Mp, D = emb_t.shape
    Gc, u, G = whh.shape
    H = Mp // tm
    ntiles = Gc * H

    def p_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, t, L - 1 - t), i % H, g)

    def p_emb_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, t, L - 1 - t), i % H, 0)

    def p_prev_idx(i, t):
        g = i // H
        nat = jnp.where(
            g == 1, jnp.minimum(t + 1, L - 1), jnp.maximum(L - 2 - t, 0)
        )
        return (nat, i % H, g)

    def p_demb_idx(i, t):
        g = i // H
        return (g, jnp.where(g == 1, t, L - 1 - t), i % H, 0)

    per_dir = lambda i, t: (i // H, 0, 0)  # noqa: E731
    per_tile = lambda i, t: (i, 0, 0)      # noqa: E731
    demb, dwih_p, db_p, dwhh_p = pl.pallas_call(
        _fused_bwd_kernel,
        grid=(ntiles, L),
        in_specs=[
            pl.BlockSpec((1, tm, u), p_idx),       # dhs
            pl.BlockSpec((1, tm, D), p_emb_idx),   # emb (gates recomputed)
            pl.BlockSpec((1, tm, u), p_idx),       # cs
            pl.BlockSpec((1, tm, u), p_prev_idx),  # cs_{kt-1}
            pl.BlockSpec((1, tm, u), p_prev_idx),  # hs_{kt-1}
            pl.BlockSpec((1, D, G), per_dir),      # wih
            pl.BlockSpec((1, 1, G), per_dir),      # bias
            pl.BlockSpec((1, u, G), per_dir),      # whh
        ],
        out_specs=[
            pl.BlockSpec((1, 1, tm, D), p_demb_idx),
            pl.BlockSpec((1, D, G), per_tile),
            pl.BlockSpec((1, 1, G), per_tile),
            pl.BlockSpec((1, u, G), per_tile),
        ],
        out_shape=[
            # Per-direction demb slabs; both directions read the SAME emb,
            # so their contributions sum OUTSIDE the kernel (an output
            # block may not be revisited across non-adjacent grid steps).
            jax.ShapeDtypeStruct((Gc, L, Mp, D), emb_t.dtype),
            jax.ShapeDtypeStruct((ntiles, D, G), jnp.float32),
            jax.ShapeDtypeStruct((ntiles, 1, G), jnp.float32),
            jax.ShapeDtypeStruct((ntiles, u, G), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((D, G), jnp.float32),
            pltpu.VMEM((1, G), jnp.float32),
            pltpu.VMEM((u, G), jnp.float32),
        ],
        interpret=interpret,
    )(dhs, emb_t, cs, cs, hs, wih, b, whh.astype(jnp.float32))
    demb = demb[0] + demb[1]                                  # [L, Mp, D]
    dwih = dwih_p.reshape(Gc, H, D, G).sum(axis=1)            # [Gc, D, G]
    db = db_p.reshape(Gc, H, G).sum(axis=1)                   # [Gc, G]
    dwhh = dwhh_p.reshape(Gc, H, u, G).sum(axis=1)            # [Gc, u, G]
    return demb, dwih.astype(wih.dtype), db, dwhh


# ---------------------------------------------------------------------------
# Windowed-cs remat (round 8, module doc): the forward saves only one (h, c)
# checkpoint pair per W-step window; the backward recomputes the window's
# states ascending in VMEM from the seed, then runs the gradient sweep from
# scratch. Windows are NATURAL-time blocks [bW, bW+W) so both directions'
# block reads stay aligned: a natural block IS a contiguous kernel-time
# window for the reverse direction too, just walked the other way. Per
# direction, a checkpoint slot b holds the state at the block's kernel-LAST
# step (highest nat for the forward direction, lowest nat for the reverse) —
# exactly the seed the NEXT kernel-time window's recompute needs.
# ---------------------------------------------------------------------------


def _win_fwd_nat(i, t, H, L):
    """Natural-time position of forward grid step t for tile i (the fused
    forward's kernel time IS t; the reverse direction flips it)."""
    return jnp.where(i // H == 1, L - 1 - t, t)


def _fused_win_fwd_kernel(
    emb_ref, wih_ref, b_ref, whh_ref, hs_ref, ch_ref, cc_ref, h_scr, c_scr
):
    # Identical recurrence to _fused_fwd_kernel; the only residuals that
    # leave VMEM are the checkpoint pair blocks, written every step — the
    # block flushes to HBM when its (window) index changes, so the
    # surviving value is the window's kernel-last state, at 1/W the
    # full-cs write traffic.
    t = pl.program_id(1)
    u = whh_ref.shape[1]

    @pl.when(t == 0)
    def _():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    a = (
        jnp.dot(emb_ref[0], wih_ref[0], preferred_element_type=jnp.float32)
        + b_ref[0]
        + jnp.dot(h_scr[...], whh_ref[0], preferred_element_type=jnp.float32)
    )
    i, f, g, o = _gates(a, u)
    c = f * c_scr[...] + i * g
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    hs_ref[0] = h.astype(hs_ref.dtype)
    ch_ref[0] = h.astype(ch_ref.dtype)
    cc_ref[0] = c.astype(cc_ref.dtype)


def _fused_win_bwd_kernel(
    dhs_ref, emb_ref, ch_ref, cc_ref, wih_ref, b_ref, whh_ref,
    demb_ref, dwih_ref, db_ref, dwhh_ref,
    dh_scr, dc_scr, dwih_scr, db_scr, dwhh_scr, h_win, c_win,
    *, W: int, H: int,
):
    i = pl.program_id(0)
    t = pl.program_id(1)
    L = pl.num_programs(1)
    u = whh_ref.shape[1]
    rev = i // H == 1
    nat = jnp.where(rev, t, L - 1 - t)  # natural position being undone
    base = (nat // W) * W
    Wb = jnp.minimum(L - base, W)       # ragged last natural block
    o = nat - base

    @pl.when(t == 0)
    def _():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)
        dwih_scr[...] = jnp.zeros_like(dwih_scr)
        db_scr[...] = jnp.zeros_like(db_scr)
        dwhh_scr[...] = jnp.zeros_like(dwhh_scr)

    wih = wih_ref[0]
    bias = b_ref[0]
    whh = whh_ref[0]

    # Seed = the checkpoint pair of the kernel-PREVIOUS natural block (the
    # index map points there); masked to the true zero initial state when
    # this window is the direction's kernel-first one (fwd: block 0; rev:
    # the last natural block — its window starts at kernel time 0).
    first_win = jnp.where(rev, base + Wb >= L, base == 0)
    live = jnp.where(first_win, 0.0, 1.0)
    seed_h = ch_ref[0].astype(jnp.float32) * live
    seed_c = cc_ref[0].astype(jnp.float32) * live

    # Window entry (the window's kernel-LAST step, reached first walking
    # backwards): replay the forward recurrence ascending in kernel time,
    # stashing (h, c) per natural offset in VMEM. f32 throughout — bf16
    # residuals round only the seeds. Ragged-block lanes (j >= Wb) read
    # out-of-bounds emb rows whose values are unspecified; jnp.where
    # SELECTS the carried state (no arithmetic with the garbage), and
    # their stores land in never-read slots.
    @pl.when(jnp.where(rev, o == 0, o == Wb - 1))
    def _():
        def step(j, carry):
            h_prev, c_prev = carry
            pos = jnp.clip(jnp.where(rev, Wb - 1 - j, j), 0, W - 1)
            e = emb_ref[pl.ds(pos, 1)][0]
            a = (
                jnp.dot(e, wih, preferred_element_type=jnp.float32)
                + bias
                + jnp.dot(h_prev, whh, preferred_element_type=jnp.float32)
            )
            ig, fg, gg, og = _gates(a, u)
            c = jnp.where(j < Wb, fg * c_prev + ig * gg, c_prev)
            h = jnp.where(j < Wb, og * jnp.tanh(c), h_prev)
            h_win[pl.ds(pos, 1)] = h[None]
            c_win[pl.ds(pos, 1)] = c[None]
            return h, c

        jax.lax.fori_loop(0, W, step, (seed_h, seed_c))

    # Gradient step: same math as _fused_bwd_kernel, but c_t / (h, c)_prev
    # come from the recomputed window scratch (or the seed at the window's
    # kernel-first step) instead of HBM residual streams.
    at_seed = jnp.where(rev, o == Wb - 1, o == 0)
    o_prev = jnp.where(rev, jnp.minimum(o + 1, W - 1), jnp.maximum(o - 1, 0))
    c_t = c_win[pl.ds(o, 1)][0]
    tc = jnp.tanh(c_t)
    h_prev = jnp.where(at_seed, seed_h, h_win[pl.ds(o_prev, 1)][0])
    c_prev = jnp.where(at_seed, seed_c, c_win[pl.ds(o_prev, 1)][0])

    emb = emb_ref[pl.ds(o, 1)][0]
    a = (
        jnp.dot(emb, wih, preferred_element_type=jnp.float32)
        + bias
        + jnp.dot(h_prev, whh, preferred_element_type=jnp.float32)
    )
    i_g, f, g, o_g = _gates(a, u)

    dh_t = dhs_ref[0].astype(jnp.float32) + dh_scr[...]
    da_o = dh_t * tc * o_g * (1.0 - o_g)
    dct = dc_scr[...] + dh_t * o_g * (1.0 - tc * tc)
    da_i = dct * g * i_g * (1.0 - i_g)
    da_g = dct * i_g * (1.0 - g * g)
    da_f = dct * c_prev * f * (1.0 - f)
    da = jnp.concatenate([da_i, da_f, da_g, da_o], axis=-1)  # [tm, 4u]

    demb_ref[0, 0] = jax.lax.dot_general(
        da, wih, (((1,), (1,)), ((), ())),  # da @ wihᵀ -> [tm, D]
        preferred_element_type=jnp.float32,
    ).astype(demb_ref.dtype)
    dwih_scr[...] += jax.lax.dot_general(
        emb.astype(jnp.float32), da, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    db_scr[...] += jnp.sum(da, axis=0, keepdims=True)
    dh_scr[...] = jax.lax.dot_general(
        da, whh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dc_scr[...] = dct * f
    dwhh_scr[...] += jax.lax.dot_general(
        h_prev, da, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dwih_ref[0] = dwih_scr[...]
    db_ref[0] = db_scr[...]
    dwhh_ref[0] = dwhh_scr[...]


def _fused_win_fwd_call(emb_t, wih, b, whh, interpret: bool, tm: int,
                        W: int, res_dt):
    L, Mp, D = emb_t.shape
    Gc, u, G = whh.shape
    H = Mp // tm
    nB = -(-L // W)
    in_specs, out_idx, _, _ = _fused_specs(L, D, u, G, H, tm)
    ck_idx = lambda i, t: (_win_fwd_nat(i, t, H, L) // W, i % H, i // H)  # noqa: E731
    ck_spec = pl.BlockSpec((1, tm, u), ck_idx)
    hs, ch, cc = pl.pallas_call(
        _fused_win_fwd_kernel,
        grid=(Gc * H, L),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, tm, u), out_idx), ck_spec, ck_spec],
        out_shape=[
            jax.ShapeDtypeStruct((L, Mp, Gc * u), emb_t.dtype),
            jax.ShapeDtypeStruct((nB, Mp, Gc * u), res_dt),
            jax.ShapeDtypeStruct((nB, Mp, Gc * u), res_dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
        ],
        interpret=interpret,
    )(emb_t, wih, b, whh.astype(jnp.float32))
    return hs, ch, cc


def _fused_win_bwd_call(dhs, emb_t, ch, cc, wih, b, whh,
                        interpret: bool, tm: int, W: int):
    L, Mp, D = emb_t.shape
    Gc, u, G = whh.shape
    H = Mp // tm
    ntiles = Gc * H
    nB = ch.shape[0]

    def p_idx(i, t):
        g = i // H
        return (jnp.where(g == 1, t, L - 1 - t), i % H, g)

    def p_demb_idx(i, t):
        g = i // H
        return (g, jnp.where(g == 1, t, L - 1 - t), i % H, 0)

    def blk_of(i, t):
        return jnp.where(i // H == 1, t, L - 1 - t) // W

    def emb_win_idx(i, t):
        return (blk_of(i, t), i % H, 0)

    def seed_idx(i, t):
        # The kernel-previous natural block's checkpoint: one block down
        # in natural time for the forward direction, one block UP for the
        # reverse (its kernel time ascends as nat descends). Clamped at
        # the edges, where the kernel masks the seed to zero anyway.
        g = i // H
        b = blk_of(i, t)
        return (
            jnp.clip(jnp.where(g == 1, b + 1, b - 1), 0, nB - 1),
            i % H, g,
        )

    per_dir = lambda i, t: (i // H, 0, 0)  # noqa: E731
    per_tile = lambda i, t: (i, 0, 0)      # noqa: E731
    demb, dwih_p, db_p, dwhh_p = pl.pallas_call(
        partial(_fused_win_bwd_kernel, W=W, H=H),
        grid=(ntiles, L),
        in_specs=[
            pl.BlockSpec((1, tm, u), p_idx),       # dhs
            pl.BlockSpec((W, tm, D), emb_win_idx),  # emb window
            pl.BlockSpec((1, tm, u), seed_idx),    # ckpt h seed
            pl.BlockSpec((1, tm, u), seed_idx),    # ckpt c seed
            pl.BlockSpec((1, D, G), per_dir),      # wih
            pl.BlockSpec((1, 1, G), per_dir),      # bias
            pl.BlockSpec((1, u, G), per_dir),      # whh
        ],
        out_specs=[
            pl.BlockSpec((1, 1, tm, D), p_demb_idx),
            pl.BlockSpec((1, D, G), per_tile),
            pl.BlockSpec((1, 1, G), per_tile),
            pl.BlockSpec((1, u, G), per_tile),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Gc, L, Mp, D), emb_t.dtype),
            jax.ShapeDtypeStruct((ntiles, D, G), jnp.float32),
            jax.ShapeDtypeStruct((ntiles, 1, G), jnp.float32),
            jax.ShapeDtypeStruct((ntiles, u, G), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((tm, u), jnp.float32),
            pltpu.VMEM((D, G), jnp.float32),
            pltpu.VMEM((1, G), jnp.float32),
            pltpu.VMEM((u, G), jnp.float32),
            pltpu.VMEM((W, tm, u), jnp.float32),  # recomputed window h
            pltpu.VMEM((W, tm, u), jnp.float32),  # recomputed window c
        ],
        interpret=interpret,
    )(dhs, emb_t, ch, cc, wih, b, whh.astype(jnp.float32))
    demb = demb[0] + demb[1]                                  # [L, Mp, D]
    dwih = dwih_p.reshape(Gc, H, D, G).sum(axis=1)            # [Gc, D, G]
    db = db_p.reshape(Gc, H, G).sum(axis=1)                   # [Gc, G]
    dwhh = dwhh_p.reshape(Gc, H, u, G).sum(axis=1)            # [Gc, u, G]
    return demb, dwih.astype(wih.dtype), db, dwhh


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _bilstm_fused_tm(emb_t, wih, b, whh, interpret=False, tm=_TM,
                     cs_window=0, res_dt=None):
    # Primal (no-grad) path is residual-free either way; the knobs only
    # shape what the fwd RULE saves.
    return _fused_fwd_call_infer(emb_t, wih, b, whh, interpret, tm)


def _bilstm_fused_fwd(emb_t, wih, b, whh, interpret, tm, cs_window, res_dt):
    res_dt = emb_t.dtype if res_dt is None else res_dt
    if cs_window:
        hs, ch, cc = _fused_win_fwd_call(
            emb_t, wih, b, whh, interpret, tm, cs_window, res_dt
        )
        return hs, (emb_t, ch, cc, wih, b, whh)
    hs, cs = _fused_fwd_call(emb_t, wih, b, whh, interpret, tm, res_dt)
    return hs, (emb_t, hs, cs, wih, b, whh)


def _bilstm_fused_bwd(interpret, tm, cs_window, res_dt, res, dhs):
    if cs_window:
        emb_t, ch, cc, wih, b, whh = res
        demb, dwih, db, dwhh = _fused_win_bwd_call(
            dhs, emb_t, ch, cc, wih, b, whh, interpret, tm, cs_window
        )
    else:
        emb_t, hs, cs, wih, b, whh = res
        demb, dwih, db, dwhh = _fused_bwd_call(
            dhs, emb_t, cs, hs, wih, b, whh, interpret, tm
        )
    return demb, dwih, db.reshape(b.shape), dwhh


_bilstm_fused_tm.defvjp(_bilstm_fused_fwd, _bilstm_fused_bwd)


def bilstm_encoder_tm(
    emb_t: jnp.ndarray,
    wih: jnp.ndarray,
    b: jnp.ndarray,
    whh: jnp.ndarray,
    backend: str = "scan",
    cs_window: int = 0,
    residual_dtype=None,
) -> jnp.ndarray:
    """Projection + bidirectional recurrence over natural-time embeddings.

    emb_t: [L, M, D] time-major token embeddings; wih: [2, D, 4u]
    per-direction input projections; b: [2, 1, 4u] biases; whh: [2, u, 4u].
    Returns [L, M, 2u] natural-time hidden states (cols [0:u] forward,
    [u:2u] reverse). The pallas/interpret backends never materialize the
    projected gates in HBM (see the fused-kernel section comment); the
    scan backend computes them explicitly and reuses the tm scan twin —
    identical math, different fp rounding order.

    ``cs_window``: 0 = save the full hs/cs residual streams for the
    backward (round-6 design); W > 0 = windowed-cs remat (module doc):
    only one (h, c) checkpoint pair per W natural-time steps is saved and
    the backward recomputes each window's states in VMEM. W is clamped to
    L (W >= L means one window recomputed from the zero initial state).
    ``residual_dtype``: storage dtype of the residual streams/checkpoints
    (None = emb's dtype); carries and recompute stay f32. Both are pure
    runtime knobs — parameters, outputs, and checkpoints are identical
    across settings (pinned in tests/test_lstm.py); the scan backend
    keeps no residuals and ignores them.
    """
    L, M, D = emb_t.shape
    Gc, u, G = whh.shape
    if backend == "scan":
        w_cat = jnp.concatenate([wih[0], wih[1]], axis=-1)    # [D, 8u]
        b_cat = jnp.concatenate([b[0, 0], b[1, 0]], axis=-1)  # [8u]
        xg_t = emb_t @ w_cat.astype(emb_t.dtype) + b_cat.astype(emb_t.dtype)
        return bilstm_recurrence_tm(xg_t, whh, backend="scan")
    if backend not in ("pallas", "interpret"):
        raise ValueError(f"unknown lstm backend {backend!r}")
    W = min(int(cs_window), L) if cs_window else 0
    res_dt = jnp.dtype(residual_dtype) if residual_dtype is not None else None
    tm = _pick_tm(M, u, jnp.dtype(emb_t.dtype).itemsize, D=D, W=W)
    pad = (-M) % tm
    if pad:
        # Pad rows feed zero embeddings through the recurrence; their
        # nonzero (bias-driven) hidden states are sliced off and their
        # cotangents are zero, so gradients are untouched.
        emb_t = jnp.pad(emb_t, ((0, 0), (0, pad), (0, 0)))
    out = _bilstm_fused_tm(
        emb_t,
        wih.astype(emb_t.dtype),
        b.astype(jnp.float32),
        whh.astype(jnp.float32),
        backend == "interpret",
        tm,
        W,
        res_dt,
    )
    return out[:, :M] if pad else out


def lstm_recurrence(
    xg: jnp.ndarray, whh: jnp.ndarray, backend: str = "scan"
) -> jnp.ndarray:
    """Single-group LSTM recurrence over pre-projected gate inputs.

    backend: "scan" (XLA reference, float32 out) | "pallas" (compiled TPU
    kernel) | "interpret" (Pallas interpreter, any backend — used in
    tests). The pallas/interpret output is [M, L, u] in xg's dtype (f32 in
    -> f32 out; bf16 in -> bf16 out with f32 internal recurrence).
    """
    if backend == "scan":
        return lstm_scan(xg, whh)
    return lstm_recurrence_grouped(xg[None], whh[None], backend)[0]
