from induction_network_on_fewrel_tpu.ops.attn import (  # noqa: F401
    masked_selfattn_tm,
)
from induction_network_on_fewrel_tpu.ops.core import (  # noqa: F401
    gradient_reversal,
    masked_max,
    masked_mean,
    masked_softmax,
    squash,
)
