"""Embedding lookup whose BACKWARD is an MXU matmul, not a scatter-add.

Autodiff's transpose of ``table[ids]`` is ``zeros.at[ids].add(cot)`` — a
serialized scatter. Profiled on the headline config (tools/profile_headline.py,
v5e): the four position-table scatters ([64000 tokens] -> [80, 5]) cost
111 ms EACH per 256-step fused call and the two lazy word-table scatters
([64000] -> [~1.7k, 50]) 119 ms each — together ~19% of device time, more
than the whole LSTM forward. A segment-sum over U rows is algebraically
``one_hot(ids, U)ᵀ @ cot``: for small/medium U that matmul is trivial MXU
work (2·T·U·D FLOPs), so ``lookup_matmul_grad`` wraps the gather in a
custom VJP whose backward builds the one-hot in chunks (bounding the
[chunk, U] intermediate) and accumulates with a ``lax.scan``.

Crossover: the matmul costs O(T·U·D) vs the scatter's O(T·D) serialized
updates — a win while U stays in the tens of thousands (measured: U=80
scatter 111 ms -> sub-ms; U=1654 119 ms -> ~2 ms). ``MATMUL_GRAD_MAX_ROWS``
gates callers that see data-dependent table sizes; the full 400k GloVe
table must keep the native scatter (5 TFLOP of one-hot matmul loses).

Forward semantics are exactly ``table[ids]``; backward sums the same
per-token cotangent terms as the scatter, in f32, in a different order —
bitwise-different but within float tolerance (pinned by
tests/test_segsum.py against the scatter reference).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# Above this many rows the one-hot matmul's O(T*U*D) FLOPs stop beating the
# scatter's serialized O(T*D) updates (headroom: at U=32k, T=64k tokens the
# matmul is ~200 GFLOP ~= a few ms on v5e, still well under the measured
# 119 ms scatter; at U=400k it is ~5 TFLOP and loses).
MATMUL_GRAD_MAX_ROWS = 32768

# One-hot intermediate budget. The chunk length adapts to the table: a
# fixed small chunk turns the backward into hundreds of scan iterations
# whose per-iteration overhead dwarfs the matmul (profiled: 125 chunks x
# 32000 grid trips for the [80, 5] position tables cost ~60 ms/table per
# fused call — more than the matmul work by orders of magnitude). Budgeting
# the [chunk, U] one-hot at ~32 MB gives ONE chunk for tiny tables and a
# handful for the compact word table, with the same math.
_ONEHOT_BYTES = 32 * 2**20
_MIN_CHUNK = 1024


def _segment_sum_matmul(cot: jnp.ndarray, ids: jnp.ndarray, num_rows: int) -> jnp.ndarray:
    """sum_t one_hot(ids[t]) * cot[t] -> [num_rows, D], f32, via chunked matmul."""
    T, D = ids.size, cot.shape[-1]
    chunk = max(_MIN_CHUNK, _ONEHOT_BYTES // (num_rows * cot.dtype.itemsize))
    if chunk >= T:
        # Single-chunk: contract over the token dims AS THEY ARE — no
        # [.., T_sharded, ..] -> [T] flatten. The flatten merged a
        # batch-SHARDED token dim with its unsharded neighbors, a layout
        # GSPMD cannot represent, so the partitioner replicated both ids
        # and the cotangent first — at the flagship shape that was the
        # 26 MB [L, M, word_dim] f32 all-gather per step per device
        # (COMMS_r06). Contracting the original dims keeps both operands
        # batch-sharded; the partial products meet in ONE compact
        # [num_rows, D] all-reduce.
        onehot = jax.nn.one_hot(ids, num_rows, dtype=cot.dtype)  # ids.shape+[U]
        nd = ids.ndim
        return jax.lax.dot_general(
            onehot, cot,
            ((tuple(range(nd)), tuple(range(nd))), ((), ())),  # onehotᵀ @ cot
            preferred_element_type=jnp.float32,
        )
    # Chunked path (one-hot would blow the budget): flattening is fine on a
    # single device / inside shard_map (where everything is local); sharded
    # GSPMD callers with big tables route through the compact-demb wrapper
    # (parallel/sharding.make_compact_demb_lookup), which runs THIS code
    # per-shard under shard_map and psums the [num_rows, D] result.
    cot2 = cot.reshape(-1, cot.shape[-1])
    flat = ids.reshape(-1)
    pad = (-T) % chunk
    if pad:
        cot2 = jnp.pad(cot2, ((0, pad), (0, 0)))
        # Padded ids point at row 0 but their cotangent rows are zero.
        flat = jnp.pad(flat, (0, pad))
    n_chunks = (T + pad) // chunk
    ids_c = flat.reshape(n_chunks, chunk)
    cot_c = cot2.reshape(n_chunks, chunk, D)

    def body(acc, ch):
        cids, ccot = ch
        onehot = jax.nn.one_hot(cids, num_rows, dtype=ccot.dtype)  # [C, U]
        acc = acc + jax.lax.dot_general(
            onehot, ccot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, None

    init = jnp.zeros((num_rows, D), jnp.float32)
    out, _ = jax.lax.scan(body, init, (ids_c, cot_c))
    return out


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _lookup(num_rows: int, dtype_name: str, table, ids):
    return table[ids]


def _lookup_fwd(num_rows, dtype_name, table, ids):
    return table[ids], ids


def _lookup_bwd(num_rows, dtype_name, ids, cot):
    dtable = _segment_sum_matmul(cot, ids, num_rows).astype(dtype_name)
    return dtable, np.zeros(ids.shape, jax.dtypes.float0)


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def lookup_matmul_grad(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """``table[ids]`` with a matmul (not scatter) gradient for the table.

    table: [U, D] float; ids: int array of any shape. Returns
    ``table[ids]`` with shape ``ids.shape + (D,)``. Use only when
    ``U <= MATMUL_GRAD_MAX_ROWS`` (see module docstring for the crossover).
    """
    return _lookup(table.shape[0], jnp.dtype(table.dtype).name, table, ids)
