"""Fused structured self-attention over the time axis (Pallas TPU kernel).

The BiLSTM encoder's attention — ``scores = w2·tanh(W1·h_t)``, masked
softmax over L, ``out = Σ_t a_t h_t`` (Lin et al. 2017 form, SURVEY.md
§2.1 "BiLSTM + self-attention") — is HBM-bandwidth-bound, not FLOP-bound:
the round-5 roofline ledger puts its fwd+bwd at ~362 MB/step of the
flagship's 894 MB total, with XLA reading the [L, M, 2u] hidden states
twice forward (projection pass + weighted-sum pass) and ~three times
backward. This kernel computes the whole thing in ONE pass over L each
way using a flash-attention-style ONLINE softmax over the time axis:

  forward   m, d, acc  ← running max / normalizer / weighted sum; H read
            once, out [M, 2u] written once. Row max/normalizer (tiny,
            [M] each) are the only extra residuals.
  backward  a_t is reconstructed per step from the saved (max, denom) —
            no second online pass — and dH_t = a_t·dout + (ds_t path
            through tanh/W1) is written in one pass; dW1/dw2 accumulate
            in VMEM scratch (no HBM traffic), per-tile partials summed
            outside (the ops/lstm.py dwhh pattern).

Numerics match ops.core.masked_softmax exactly in exact arithmetic: the
online normalizer ends at Σ exp(s_t − max) and the denominator adds the
same 1e-13; fully-masked rows produce exact zeros (e is multiplied by
the 0/1 mask AFTER the shift, so the all-masked normalizer is 0 and
out = 0/1e-13 = 0). Internal math is float32 regardless of H's dtype.

Layout follows ops/lstm.py: everything TIME-MAJOR, the iterated time axis
a leading block dim of size 1, rows padded to the 128-row MXU tile with
padded rows masked (their outputs and gradients are exact zeros).

Backends: "xla" (two-pass reference, pure jnp — also the scan twin the
tests compare against), "pallas" (compiled TPU kernel), "interpret"
(same kernel code on the Pallas interpreter; CPU-runnable), and the
round-6 RECOMPUTE-IN-BACKWARD hybrids "xla_remat" / "xla_remat_interpret"
(``--remat_attn``): forward runs the two-pass XLA form — the flat
[L·M, 2u] MXU matmuls that beat the chunked kernel forward on chip
(BASELINE.md round 5) — but through a custom VJP that saves ONLY the
[M] softmax stats (running max + normalizer) instead of the [L, M, A]
tanh projection and the [L, M] attention weights XLA's autodiff would
keep; the backward is the one-pass Pallas kernel above, which
rebuilds both from the already-saved H inside VMEM. Byte arithmetic at
the flagship shape (utils/roofline.py): fwd 149 -> 133 MB (no
projection/att residual writes), bwd 213 -> 134 MB (H read once +
dH write once vs XLA's three H passes + saved-projection read).

A plain ``jax.checkpoint``-style remat of the two-pass form was
evaluated and REJECTED by the same arithmetic: the saved projection is
A/2u = 1/4 the width of the H rows its recomputation must re-read, so
XLA-level remat trades a 16 MB residual for an extra 66 MB H pass plus
re-materializing the projection in the backward anyway (~ +82 MB/step).
Recompute only pays when the recompute pass SHARES its H read with the
gradient uses — i.e. inside the fused kernel. That is what xla_remat
does.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TM = 128  # row tile (MXU systolic dimension); rows pad up to one tile
_TL = 8    # time steps per grid invocation: 1000 -> 125 grid steps at the
           # flagship shape, and each projection matmul sees TL*TM rows
           # (the per-time-step form lost 0.81x to XLA - grid overhead
           # swamped the byte savings; chip-measured round 5)
_NEG = -1e30


def masked_selfattn_tm(
    H_t: jnp.ndarray,      # [L, M, D] hidden states, time-major
    mask: jnp.ndarray,     # [M, L] (any numeric; >0 = valid token)
    w1: jnp.ndarray,       # [D, A] projection (f32 param)
    w2: jnp.ndarray,       # [A, 1] score vector (f32 param)
    backend: str = "xla",
) -> jnp.ndarray:          # [M, D] sentence vectors
    if backend == "xla":
        return _attn_reference(H_t, mask, w1, w2)
    if backend in ("pallas", "interpret"):
        return _attn_pallas(H_t, mask, w1, w2, backend == "interpret")
    if backend in ("xla_remat", "xla_remat_interpret"):
        return _attn_xla_remat(
            H_t, mask, w1, w2, backend == "xla_remat_interpret"
        )
    raise ValueError(f"unknown attention backend {backend!r}")


def _attn_reference(H_t, mask, w1, w2):
    """Two-pass jnp twin (f32 internal math, same as the kernel)."""
    H32 = H_t.astype(jnp.float32)
    s = (jnp.tanh(H32 @ w1) @ w2)[..., 0]               # [L, M]
    mk = (jnp.swapaxes(mask, 0, 1) > 0)                 # [L, M]
    s = jnp.where(mk, s, _NEG)
    e = jnp.exp(s - jnp.max(s, axis=0, keepdims=True)) * mk
    a = e / (jnp.sum(e, axis=0, keepdims=True) + 1e-13)
    return jnp.einsum("lm,lmd->md", a, H32).astype(H_t.dtype)


# --- kernels ---------------------------------------------------------------


def _score(h32, mask_col, w1_ref, w2_ref):
    """[TL, TM, D] f32 rows + [TL, TM, 1] 0/1 mask -> masked scores
    [TL, TM, 1] and the tanh projection [TL, TM, A] (backward reuses it).
    The projection runs as ONE [TL*TM, D] x [D, A] MXU matmul."""
    TL, TM, D = h32.shape
    A = w1_ref.shape[1]
    t = jnp.tanh(jnp.dot(
        h32.reshape(TL * TM, D), w1_ref[...],
        preferred_element_type=jnp.float32,
    )).reshape(TL, TM, A)
    s = jnp.dot(
        t.reshape(TL * TM, A), w2_ref[...],
        preferred_element_type=jnp.float32,
    ).reshape(TL, TM, 1)
    return jnp.where(mask_col > 0, s, _NEG), t


def _make_fwd_kernel(with_stats: bool):
    """ONE online-softmax forward body; ``with_stats`` (a Python-level
    closure flag) decides whether the softmax stats outputs exist and are
    written at the last chunk — the no-grad primal and the vjp-forward
    must share their numerics by construction, not by parallel edits."""

    def kernel(H_ref, mask_ref, w1_ref, w2_ref, out_ref, *rest):
        if with_stats:
            mx_ref, dn_ref, acc_scr, m_scr, d_scr = rest
        else:
            acc_scr, m_scr, d_scr = rest
        t = pl.program_id(1)
        L = pl.num_programs(1)

        @pl.when(t == 0)
        def _():
            acc_scr[...] = jnp.zeros_like(acc_scr)
            m_scr[...] = jnp.full_like(m_scr, _NEG)
            d_scr[...] = jnp.zeros_like(d_scr)

        h32 = H_ref[...].astype(jnp.float32)            # [TL, TM, D]
        mask_col = mask_ref[...]                        # [TL, TM, 1]
        s, _ = _score(h32, mask_col, w1_ref, w2_ref)    # [TL, TM, 1]
        m_new = jnp.maximum(m_scr[...], s.max(axis=0))
        corr = jnp.exp(m_scr[...] - m_new)
        e = jnp.exp(s - m_new[None]) * (mask_col > 0)   # [TL, TM, 1]
        acc_scr[...] = acc_scr[...] * corr + jnp.sum(e * h32, axis=0)
        d_scr[...] = d_scr[...] * corr + jnp.sum(e, axis=0)
        m_scr[...] = m_new

        @pl.when(t == L - 1)
        def _():
            out_ref[...] = (acc_scr[...] / (d_scr[...] + 1e-13)).astype(
                out_ref.dtype
            )
            if with_stats:
                mx_ref[0] = m_scr[...][:, 0]
                dn_ref[0] = d_scr[...][:, 0]

    return kernel


_fwd_kernel = _make_fwd_kernel(with_stats=True)
_fwd_kernel_infer = _make_fwd_kernel(with_stats=False)


def _bwd_kernel(H_ref, mask_ref, w1_ref, w2_ref, out_ref, mx_ref, dn_ref,
                dout_ref, dH_ref, dw1_ref, dw2_ref,
                c_scr, dw1_scr, dw2_scr):
    t = pl.program_id(1)

    h32 = H_ref[...].astype(jnp.float32)                # [TL, TM, D]
    mask_col = mask_ref[...]                            # [TL, TM, 1]
    do = dout_ref[...].astype(jnp.float32)              # [TM, D]

    @pl.when(t == 0)
    def _():
        c_scr[...] = jnp.sum(
            do * out_ref[...].astype(jnp.float32), axis=1, keepdims=True
        )
        dw1_scr[...] = jnp.zeros_like(dw1_scr)
        dw2_scr[...] = jnp.zeros_like(dw2_scr)

    s, tl = _score(h32, mask_col, w1_ref, w2_ref)       # [TL, TM, *]
    TL, TM, D = h32.shape
    A = tl.shape[-1]
    mx = mx_ref[0][:, None]                             # [TM, 1]
    dn = dn_ref[0][:, None]
    a = jnp.exp(s - mx[None]) * (mask_col > 0) / (dn[None] + 1e-13)
    # Softmax-through-weighted-sum backward: ds_t = a_t (dout·h_t − dout·out)
    ds = a * (jnp.sum(do[None] * h32, axis=-1, keepdims=True) - c_scr[...][None])
    dproj = (ds * (1.0 - tl * tl)) * w2_ref[...][:, 0][None, None, :]
    dh = a * do[None] + jax.lax.dot_general(
        dproj.reshape(TL * TM, A), w1_ref[...],
        (((1,), (1,)), ((), ())),                       # dproj @ w1^T
        preferred_element_type=jnp.float32,
    ).reshape(TL, TM, D)
    dH_ref[...] = dh.astype(dH_ref.dtype)
    dw1_scr[...] += jax.lax.dot_general(
        h32.reshape(TL * TM, D), dproj.reshape(TL * TM, A),
        (((0,), (0,)), ((), ())),                       # h^T @ dproj
        preferred_element_type=jnp.float32,
    )
    dw2_scr[...] += jnp.sum(tl * ds, axis=(0, 1))[None]          # [1, A]

    # Only the LAST chunk's copy is observable (the output block index is
    # t-invariant) — gate it like the forward's final writes instead of
    # copying the partials out every chunk (review finding, round 5).
    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        dw1_ref[0] = dw1_scr[...]
        dw2_ref[0] = dw2_scr[...]


# --- calls -----------------------------------------------------------------


def _pad_rows(H_t, mask):
    """Pad rows to the _TM tile AND time to the _TL chunk; padded entries
    carry mask 0, so they contribute exact zeros everywhere."""
    L, M, D = H_t.shape
    pad_m = (-M) % _TM
    pad_l = (-L) % _TL
    if pad_m or pad_l:
        H_t = jnp.pad(H_t, ((0, pad_l), (0, pad_m), (0, 0)))
        mask = jnp.pad(mask, ((0, pad_l), (0, pad_m)))  # mask_t [L, M]
    return H_t, mask[..., None], M + pad_m        # mask -> [Lp, Mp, 1]


def _common_specs(D, A):
    # mask rides as [Lp, Mp, 1] (not [Lp, Mp]): the TPU lowering constrains
    # the LAST TWO block dims to 8/128-divisible-or-full, which a (TL, TM)
    # block of a 2-D [Lp, Mp] array violates at L=40 (chip-caught round 5);
    # the trailing singleton makes the constrained dims (TM, 1) = ok.
    return [
        pl.BlockSpec((_TL, _TM, D), lambda i, t: (t, i, 0)),   # H
        pl.BlockSpec((_TL, _TM, 1), lambda i, t: (t, i, 0)),   # mask_t
        pl.BlockSpec((D, A), lambda i, t: (0, 0)),             # w1 (full)
        pl.BlockSpec((A, 1), lambda i, t: (0, 0)),             # w2 (full)
    ]


def _fwd_call(H_t, mask_t, w1, w2, interpret, with_stats):
    Lp, Mp, D = H_t.shape
    A = w1.shape[1]
    tiles = Mp // _TM
    grid = (tiles, Lp // _TL)
    scratch = [
        pltpu.VMEM((_TM, D), jnp.float32),
        pltpu.VMEM((_TM, 1), jnp.float32),
        pltpu.VMEM((_TM, 1), jnp.float32),
    ]
    out_spec = pl.BlockSpec((_TM, D), lambda i, t: (i, 0))
    if not with_stats:
        return pl.pallas_call(
            _fwd_kernel_infer,
            grid=grid,
            in_specs=_common_specs(D, A),
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((Mp, D), H_t.dtype),
            scratch_shapes=scratch,
            interpret=interpret,
        )(H_t, mask_t, w1, w2)
    stat = pl.BlockSpec((1, _TM), lambda i, t: (0, i))
    return pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=_common_specs(D, A),
        out_specs=[out_spec, stat, stat],
        out_shape=[
            jax.ShapeDtypeStruct((Mp, D), H_t.dtype),
            jax.ShapeDtypeStruct((1, Mp), jnp.float32),
            jax.ShapeDtypeStruct((1, Mp), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(H_t, mask_t, w1, w2)


def _bwd_call(H_t, mask_t, w1, w2, out, mx, dn, dout, interpret):
    Lp, Mp, D = H_t.shape
    A = w1.shape[1]
    tiles = Mp // _TM
    grid = (tiles, Lp // _TL)
    row = pl.BlockSpec((_TM, D), lambda i, t: (i, 0))
    stat = pl.BlockSpec((1, _TM), lambda i, t: (0, i))
    dH, dw1_p, dw2_p = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=_common_specs(D, A) + [row, stat, stat, row],
        out_specs=[
            pl.BlockSpec((_TL, _TM, D), lambda i, t: (t, i, 0)),
            pl.BlockSpec((1, D, A), lambda i, t: (i, 0, 0)),
            pl.BlockSpec((1, 1, A), lambda i, t: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Lp, Mp, D), H_t.dtype),
            jax.ShapeDtypeStruct((tiles, D, A), jnp.float32),
            jax.ShapeDtypeStruct((tiles, 1, A), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TM, 1), jnp.float32),
            pltpu.VMEM((D, A), jnp.float32),
            pltpu.VMEM((1, A), jnp.float32),
        ],
        interpret=interpret,
    )(H_t, mask_t, w1, w2, out, mx, dn, dout)
    return dH, dw1_p.sum(axis=0), dw2_p.sum(axis=0).reshape(A, 1)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _attn_core(H_t, mask_t, w1, w2, interpret=False):
    """mask_t: [L, M] float32 (0/1). The wrapper below prepares it."""
    L, M, D = H_t.shape
    H_p, mask_p, Mp = _pad_rows(H_t, mask_t)
    out = _fwd_call(H_p, mask_p, w1, w2, interpret, with_stats=False)
    return out[:M]


def _attn_core_fwd(H_t, mask_t, w1, w2, interpret):
    L, M, D = H_t.shape
    H_p, mask_p, Mp = _pad_rows(H_t, mask_t)
    out, mx, dn = _fwd_call(H_p, mask_p, w1, w2, interpret, with_stats=True)
    return out[:M], (H_p, mask_p, w1, w2, out, mx, dn, L, M, mask_t.shape)


def _attn_core_bwd(interpret, res, dout):
    H_p, mask_p, w1, w2, out, mx, dn, L, M, mshape = res
    Lp, Mp, D = H_p.shape
    if Mp != M:
        dout = jnp.pad(dout, ((0, Mp - M), (0, 0)))
    dH, dw1, dw2 = _bwd_call(
        H_p, mask_p, w1, w2, out, mx, dn, dout.astype(H_p.dtype), interpret
    )
    # The mask is a 0/1 gate: zero cotangent (f32 zeros, DCE'd by XLA).
    return dH[:L, :M], jnp.zeros(mshape, jnp.float32), dw1, dw2


_attn_core.defvjp(_attn_core_fwd, _attn_core_bwd)


def _attn_pallas(H_t, mask, w1, w2, interpret=False):
    mask_t = jax.lax.stop_gradient(
        jnp.swapaxes(mask.astype(jnp.float32), 0, 1)
    )
    return _attn_core(H_t, mask_t, w1, w2, interpret)


# --- recompute-in-backward hybrid (--remat_attn) ---------------------------
#
# Forward: the two-pass XLA form, numerically the KERNEL's math (f32
# projection/softmax regardless of H's dtype — jnp.dot with
# preferred_element_type reads bf16 operands and accumulates f32, no
# upcast copy of H materializes). It additionally emits the (max,
# normalizer) stats the kernel backward reconstructs a_t from, so the
# residual tuple is EXACTLY what _attn_core_fwd saves — the backward
# rule IS _attn_core_bwd, one source of truth for the kernel bwd path.


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _attn_remat_core(H_t, mask_t, w1, w2, interpret=False):
    # Primal (no-grad) path: plain two-pass form, nothing extra computed.
    return _attn_reference(H_t, jnp.swapaxes(mask_t, 0, 1), w1, w2)


def _attn_remat_fwd(H_t, mask_t, w1, w2, interpret):
    L, M, D = H_t.shape
    H_p, mask_p, Mp = _pad_rows(H_t, mask_t)
    Lp = H_p.shape[0]
    # Same pass structure as _attn_reference, on the padded arrays, with
    # the softmax stats kept. Padded/fully-masked rows: s = _NEG
    # everywhere -> normalizer 0 -> out exactly 0 (kernel convention).
    t = jnp.tanh(jnp.dot(
        H_p.reshape(Lp * Mp, D), w1, preferred_element_type=jnp.float32
    ))
    s = jnp.dot(t, w2, preferred_element_type=jnp.float32).reshape(Lp, Mp, 1)
    s = jnp.where(mask_p > 0, s, _NEG)
    mx = jnp.max(s, axis=0)                          # [Mp, 1]
    e = jnp.exp(s - mx[None]) * (mask_p > 0)
    dn = jnp.sum(e, axis=0)                          # [Mp, 1]
    a = (e / (dn[None] + 1e-13))[..., 0]             # [Lp, Mp] f32
    out = jnp.einsum(
        "lm,lmd->md", a, H_p, preferred_element_type=jnp.float32
    ).astype(H_t.dtype)                              # [Mp, D] (padded)
    res = (
        H_p, mask_p, w1, w2, out,
        mx[:, 0][None], dn[:, 0][None], L, M, mask_t.shape,
    )
    return out[:M], res


# Backward: the one-pass Pallas kernel, verbatim — H read once, the tanh
# projection and a_t rebuilt in VMEM from the saved stats, dH written once.
_attn_remat_core.defvjp(_attn_remat_fwd, _attn_core_bwd)


def _attn_xla_remat(H_t, mask, w1, w2, interpret=False):
    mask_t = jax.lax.stop_gradient(
        jnp.swapaxes(mask.astype(jnp.float32), 0, 1)
    )
    return _attn_remat_core(H_t, mask_t, w1, w2, interpret)
