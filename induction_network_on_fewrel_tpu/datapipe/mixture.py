"""Declarative episode-mixture schedules, resolved from the cursor.

FewRel 2.0 training mixes corpora: wiki episodes interleaved with pubmed
for domain adaptation (Gao et al., EMNLP 2019), NOTA-bearing episodes at a
curriculum rate (Geng et al., EMNLP-IJCNLP 2019 defines the episode
structure). The flat sampler can't express any of that; this module does,
with two hard constraints honored:

* **Determinism from the cursor** — which source furnishes batch ``i`` is
  a pure function of ``(seed, i)`` (splitmix64-derived uniform against the
  schedule's weights at ``i``). No RNG state of its own beyond the child
  samplers', so the mixture resumes exactly from a ``PipelineCursor``.
* **Static shapes** — every source must produce identically-shaped batches
  (same ``batch_size`` and ``total_q``): batches cross ONE jit boundary,
  and a per-batch shape change would recompile the step. That means
  curricula act on **source weights over time**, not on episode geometry;
  an ``na_rate`` curriculum is expressed by scheduling weight between
  same-shape sources (e.g. NOTA negatives drawn from different corpora),
  not by varying ``na_rate`` itself (which changes TQ, hence the compiled
  shape).

Spec grammar (``--mixture``, ``MixtureSchedule.parse``)::

    SPEC   := entry (';' entry)*
    entry  := source ':' point (',' point)*
    point  := WEIGHT ('@' BATCH_INDEX)?

``"train:1.0;pubmed.json:0.0@0,1.0@4000"`` starts all-wiki and ramps
pubmed linearly to parity by batch 4000 (weights are renormalized per
index, interpolated linearly between breakpoints, held flat outside).
Sources: ``train`` is the run's primary dataset; anything else is a
FewRel-schema JSON path (resolved by the CLI).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Sequence

from induction_network_on_fewrel_tpu.datapipe.cursor import (
    capture_sampler_state,
    restore_sampler_state,
)
from induction_network_on_fewrel_tpu.parallel.hostfeed import _splitmix64


@dataclasses.dataclass(frozen=True)
class MixtureSchedule:
    """Per-source piecewise-linear weight curves over the batch index."""

    # ((source_name, ((index, weight), ...)), ...) — tuples, so the
    # schedule is hashable and trivially comparable for cursor validation.
    sources: tuple[tuple[str, tuple[tuple[int, float], ...]], ...]

    @classmethod
    def parse(cls, spec: str) -> "MixtureSchedule":
        sources = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, sep, points_s = entry.rpartition(":")
            if not sep or not name:
                raise ValueError(
                    f"mixture entry {entry!r} must be 'source:weight"
                    f"[@index][,weight@index...]'"
                )
            points = []
            for p in points_s.split(","):
                w_s, at, idx_s = p.strip().partition("@")
                w = float(w_s)
                if w < 0:
                    raise ValueError(f"mixture weight must be >= 0, got {w}")
                points.append((int(idx_s) if at else 0, w))
            points.sort()
            if len({i for i, _ in points}) != len(points):
                raise ValueError(
                    f"mixture source {name!r} repeats a breakpoint index"
                )
            sources.append((name.strip(), tuple(points)))
        if not sources:
            raise ValueError(f"empty mixture spec {spec!r}")
        seen = [n for n, _ in sources]
        if len(set(seen)) != len(seen):
            raise ValueError(f"mixture spec names a source twice: {seen}")
        return cls(sources=tuple(sources))

    @classmethod
    def ramp(cls, src: str = "src", tgt: str = "tgt",
             start_weight: float = 0.2, parity_at: int = 1,
             ) -> "MixtureSchedule":
        """The domain-adaptation ramp spelling used throughout the stack
        (tools/scenarios.py's DA arm, the ISSUE 14 adaptation
        fine-tune): the source corpus at weight 1.0 while the target
        ramps linearly from ``start_weight`` to parity by batch
        ``parity_at`` — weights move, episode geometry doesn't."""
        if parity_at < 1:
            raise ValueError(f"parity_at must be >= 1, got {parity_at}")
        return cls.parse(
            f"{src}:1.0;{tgt}:{start_weight:g}@0,1.0@{parity_at}"
        )

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(n for n, _ in self.sources)

    def weights_at(self, index: int) -> list[float]:
        """Unnormalized per-source weights at batch ``index`` (linear
        interpolation between breakpoints, clamped outside)."""
        out = []
        for _, points in self.sources:
            if index <= points[0][0]:
                out.append(points[0][1])
                continue
            if index >= points[-1][0]:
                out.append(points[-1][1])
                continue
            for (i0, w0), (i1, w1) in zip(points, points[1:]):
                if i0 <= index <= i1:
                    t = (index - i0) / max(i1 - i0, 1)
                    out.append(w0 + t * (w1 - w0))
                    break
        return out

    def pick(self, seed: int, index: int) -> int:
        """Source index for batch ``index`` — pure in (seed, index)."""
        weights = self.weights_at(index)
        total = sum(weights)
        if total <= 0:
            raise ValueError(
                f"mixture weights all zero at batch {index}: "
                f"{dict(zip(self.names, weights))}"
            )
        # Two dependent splitmix64 rounds (hostfeed.process_seed's
        # decorrelation argument): (seed, index) pairs cannot cancel
        # additively the way a linear combination could.
        u = _splitmix64(_splitmix64(seed) ^ index) / float(1 << 64)
        acc = 0.0
        for j, w in enumerate(weights):
            acc += w / total
            if u < acc:
                return j
        return len(weights) - 1

    def to_spec(self) -> str:
        """Canonical spec string (round-trips through parse)."""
        return ";".join(
            name + ":" + ",".join(f"{w:g}@{i}" for i, w in points)
            for name, points in self.sources
        )


class MixtureSampler:
    """Interleave same-shape child samplers under a MixtureSchedule.

    Exposes the standard sampler surface (``sample_batch`` /
    ``batch_size`` / ``total_q`` / ``close`` / iteration) so it drops into
    the trainer or a ``PipelineFeed`` unchanged. Deliberately NO
    ``sample_fused``: a fused stack would interleave sources inside one
    call; the feed's stacking fallback handles fusion, preserving the
    per-index source choice.
    """

    def __init__(
        self,
        children: "Sequence[tuple[str, object]]",
        schedule: MixtureSchedule,
        seed: int = 0,
    ):
        names = [n for n, _ in children]
        if list(schedule.names) != names:
            raise ValueError(
                f"mixture children {names} do not match schedule sources "
                f"{list(schedule.names)} (order matters: the pick is by "
                f"position)"
            )
        self._children = list(children)
        self.schedule = schedule
        self.seed = int(seed)
        self._next = 0
        # per-source served counts — telemetry, and the cheapest mixture
        # sanity check a test can assert on.
        self.counts = {n: 0 for n in names}
        first = self._children[0][1]
        self.batch_size = first.batch_size
        self.total_q = first.total_q
        for name, ch in self._children[1:]:
            if (ch.batch_size, ch.total_q) != (self.batch_size, self.total_q):
                raise ValueError(
                    f"mixture source {name!r} shape (batch_size="
                    f"{ch.batch_size}, total_q={ch.total_q}) differs from "
                    f"{self._children[0][0]!r} ({self.batch_size}, "
                    f"{self.total_q}); all sources must produce "
                    f"identically-shaped batches (static jit shapes)"
                )

    @property
    def return_indices(self) -> bool:
        return getattr(self._children[0][1], "return_indices", True)

    def sample_batch(self):
        j = self.schedule.pick(self.seed, self._next)
        name, child = self._children[j]
        self._next += 1
        self.counts[name] += 1
        return child.sample_batch()

    def __iter__(self) -> Iterator:
        while True:
            yield self.sample_batch()

    # --- cursor protocol --------------------------------------------------

    def feed_state(self) -> dict:
        return {
            "kind": "mixture",
            "next": self._next,
            "counts": dict(self.counts),
            "children": {
                name: capture_sampler_state(ch)
                for name, ch in self._children
            },
        }

    def restore_feed_state(self, state: dict) -> None:
        children = state.get("children", {})
        missing = [n for n, _ in self._children if n not in children]
        if missing:
            raise ValueError(
                f"cursor mixture state lacks sources {missing}; the resumed "
                f"run must use the same --mixture spec"
            )
        for name, ch in self._children:
            st = children[name]
            # Protocol-less children restore by replaying their own served
            # count (exact for deterministic samplers, just not O(1)).
            skip = (
                int(state.get("counts", {}).get(name, 0))
                if st.get("kind") == "replay" else 0
            )
            restore_sampler_state(ch, st, skip=skip)
        self._next = int(state["next"])
        self.counts = {
            n: int(state.get("counts", {}).get(n, 0))
            for n, _ in self._children
        }

    def close(self) -> None:
        for _, ch in self._children:
            if hasattr(ch, "close"):
                ch.close()
