"""datapipe/ — pipelined, checkpointable episode input pipeline (ISSUE 4).

The training input side as a production subsystem instead of an inline
``sample_batch()`` call on the critical path:

* ``producer`` — ``PipelineFeed``: a background producer thread drives any
  existing sampler into a bounded queue (optionally device-putting batches
  ahead of dispatch), so host sampling/assembly overlaps device compute
  instead of serializing with ``train/dispatch``. ``prefetch_depth=0``
  degrades to the exact synchronous path (bitwise-equal stream).
* ``cursor`` — ``PipelineCursor``: an explicit, serializable pipeline
  position (stream state, consumed batch index, per-host layout
  fingerprint) saved in every checkpoint and restored on resume; the
  resumed episode stream is byte-identical to the uninterrupted one at any
  prefetch depth.
* ``mixture`` — declarative episode-mixture schedules (domain-adaptation
  interleaves, weight curricula) resolved deterministically from the
  stream seed and batch index.
* ``faults`` — feed-path fault injection (slow producer, producer stall,
  poisoned batch) wired into the obs watchdog so a sick feed trips a
  health event instead of silently wedging the run.
"""

from induction_network_on_fewrel_tpu.datapipe.cursor import (
    PipelineCursor,
    capture_sampler_state,
    restore_sampler_state,
)
from induction_network_on_fewrel_tpu.datapipe.faults import FeedFaults
from induction_network_on_fewrel_tpu.datapipe.mixture import (
    MixtureSampler,
    MixtureSchedule,
)
from induction_network_on_fewrel_tpu.datapipe.producer import PipelineFeed

__all__ = [
    "FeedFaults",
    "MixtureSampler",
    "MixtureSchedule",
    "PipelineCursor",
    "PipelineFeed",
    "capture_sampler_state",
    "restore_sampler_state",
]
