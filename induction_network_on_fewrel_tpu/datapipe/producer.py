"""PipelineFeed: background-produced, bounded, checkpointable episode feed.

Why: the trainer's ``train/sample`` span (host-side episode sampling +
global-array assembly) runs serialized with ``train/dispatch`` — every
step pays the host work on the critical path. This feed moves production
onto a background thread driving the EXISTING samplers into a bounded
queue, so batch ``t+1`` is sampled (and optionally already device-put)
while the device runs batch ``t``. The consumer's wait on the queue is the
*feed stall* — measured, logged (``kind="data"``), and benchmarked
(``bench.py`` input-pipeline leg; target < 2% of p50 step time).

Stream contract — the load-bearing invariant every feature here preserves:

    The sequence of batches handed to the trainer is IDENTICAL to the
    synchronous path's, at every prefetch depth.

Production is strictly sequential from one base sampler (no work
stealing, no reordering); depth only changes how far ahead that sequence
is materialized. ``prefetch_depth=0`` short-circuits to direct synchronous
delegation — bitwise the pre-datapipe behavior.

Units: the feed produces in blocks of ``unit`` batches (``steps_per_call``
for index samplers whose ``sample_fused`` fills a stacked [S,B,...] block
in one native call; 1 otherwise). Units are a production/transport
granularity only — consumption may interleave single draws and fused
draws; the feed slices/stacks across unit boundaries as needed, and the
cursor tracks position in BATCHES.

Checkpointing: the producer captures the base sampler's stream state
(datapipe/cursor.py) immediately before drawing each unit; ``cursor_state``
pairs the captured state of the unit containing the consumed position with
the consumed batch index. Prefetched-but-unconsumed batches are thereby
re-produced on resume, never skipped — resume is byte-identical.

Faults (datapipe/faults.py): ``slow`` delays production, ``stall`` wedges
the producer (the consumer's stall ticks then trip the obs watchdog's
``feed_stall`` detector), ``poison`` corrupts a unit after state capture —
the validator refuses to hand it to the train step and the poisoned tick
trips ``feed_poisoned``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterator

import numpy as np

from induction_network_on_fewrel_tpu.datapipe.cursor import (
    PipelineCursor,
    capture_sampler_state,
    current_layout,
    restore_sampler_state,
)
from induction_network_on_fewrel_tpu.datapipe.faults import (
    FeedFaults,
    poison_tree,
)
from induction_network_on_fewrel_tpu.obs.spans import span


class FeedError(RuntimeError):
    """The feed cannot serve batches (producer died or batch poisoned)."""


class _Item:
    __slots__ = ("start", "payload", "poisoned")

    def __init__(self, start: int, payload: Any, poisoned: str | None):
        self.start = start          # batch index of payload[0]
        self.payload = payload      # fused (sup, qry, lab) or a single batch
        self.poisoned = poisoned    # validator verdict (None = clean)


class PipelineFeed:
    """Wraps any sampler (``sample_batch``/optional ``sample_fused``) with
    a producer thread + bounded queue + serializable cursor. Drop-in: the
    trainer-facing surface (``sample_batch``, ``sample_fused`` when fused,
    ``batch_size``, ``total_q``, ``return_indices``, iteration, ``close``)
    is the base sampler's."""

    def __init__(
        self,
        base,
        prefetch_depth: int = 2,
        unit: int = 1,
        device_put: bool = False,
        faults: FeedFaults | None = None,
        logger=None,
        local_batch: int | None = None,
        stream_tag: str = "",
        stall_tick_s: float = 2.0,
        validate: bool | None = None,
    ):
        if prefetch_depth < 0:
            raise ValueError(f"prefetch_depth must be >= 0, got {prefetch_depth}")
        if unit < 1:
            raise ValueError(f"unit must be >= 1, got {unit}")
        if unit > 1 and not hasattr(base, "sample_fused"):
            raise ValueError(
                f"unit={unit} needs a sampler with sample_fused; "
                f"{type(base).__name__} has none"
            )
        self.base = base
        self.depth = prefetch_depth
        self.unit = unit
        self.batch_size = base.batch_size
        self._device_put = device_put and prefetch_depth > 0
        self.faults = faults or FeedFaults()
        self.logger = logger          # attachable later (trainer wires it)
        self.stream_tag = stream_tag
        self._stall_tick_s = stall_tick_s
        # Validation (shape/dtype template + finite/int-range checks) runs
        # on the PRODUCER thread — off the critical path. Default: on
        # whenever poisoning is drillable or a logger will carry events
        # (the logger attaches after construction, so the default is
        # resolved per check in _should_validate, not frozen here).
        self._validate_opt = validate
        if local_batch is None:
            # Per-host wrappers (parallel/hostfeed.PerHostSampler) report
            # the GLOBAL batch; the layout fingerprint wants both sides.
            local_batch = getattr(
                getattr(base, "local", None), "batch_size", None
            )
        self._layout = current_layout(base.batch_size, local_batch)

        # --- stream position (all guarded by _lock) ---
        self._lock = threading.Lock()
        self._consumed = 0            # batches handed to the trainer
        self._produced = 0            # batches drawn from the base sampler
        self._next_produce = 0        # producer's next unit start
        # {unit_start: sampler state captured BEFORE drawing that unit}.
        # Seeded with the position-0 state so cursor_state never has to
        # touch the base sampler concurrently with the producer.
        self._states: dict[int, dict] = {0: capture_sampler_state(base)}
        self._template = None         # (shape, dtype) tree of unit 0

        # --- telemetry accumulators ---
        self._stall_s = 0.0           # consumer time blocked on the queue
        self._produce_s = 0.0         # producer time drawing units
        self._poisoned = 0
        self._win_t0 = time.monotonic()
        self._win = {"stall_s": 0.0, "produce_s": 0.0, "consumed": 0,
                     "produced": 0}

        # --- producer machinery ---
        self._q: queue.Queue = queue.Queue(maxsize=max(prefetch_depth, 1))
        self._cur: _Item | None = None  # partially-consumed unit
        self._cur_off = 0
        self._stop = threading.Event()
        self._gen = 0                 # bumped by restore_cursor/close
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        if unit > 1:
            # Exposed as an INSTANCE attribute so hasattr-based dispatch in
            # the trainer (_can_sample_fused) sees it only in fused mode.
            self.sample_fused = self._sample_fused

    # --- properties the trainer reads off samplers ------------------------

    @property
    def total_q(self):
        return self.base.total_q

    @property
    def return_indices(self):
        return getattr(self.base, "return_indices", True)

    # --- producer side ----------------------------------------------------

    def _ensure_producer(self) -> None:
        if self.depth == 0 or (self._thread is not None and self._thread.is_alive()):
            return
        if self._error is not None:
            raise FeedError("feed producer died") from self._error
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._produce_loop, args=(self._gen,),
            name="datapipe-producer", daemon=True,
        )
        self._thread.start()

    def _should_validate(self) -> bool:
        if self._validate_opt is not None:
            return self._validate_opt
        return self.faults.active or self.logger is not None

    def _draw_unit(self):
        if self.unit > 1:
            return self.base.sample_fused(self.unit)
        return self.base.sample_batch()

    def _produce_loop(self, gen: int) -> None:
        try:
            while not self._stop.is_set() and gen == self._gen:
                start = self._next_produce
                if self.faults.stalls_unit(start):
                    # Wedged-worker drill: produce nothing, stay alive. The
                    # consumer's stall ticks surface it to the watchdog.
                    self._stop.wait(0.05)
                    continue
                if self.faults.slow_s > 0:
                    self._stop.wait(self.faults.slow_s)
                    if self._stop.is_set() or gen != self._gen:
                        return
                state = capture_sampler_state(self.base)
                t0 = time.monotonic()
                with span("datapipe/produce", unit=self.unit):
                    payload = self._draw_unit()
                dt = time.monotonic() - t0
                poisoned = None
                if self.faults.poisons_unit(start, self.unit):
                    payload = poison_tree(payload)
                if self._should_validate():
                    poisoned = self._check_payload(payload)
                if self._device_put:
                    import jax

                    payload = jax.device_put(payload)
                item = _Item(start, payload, poisoned)
                with self._lock:
                    self._states[start] = state
                    self._produce_s += dt
                    self._win["produce_s"] += dt
                while not self._stop.is_set() and gen == self._gen:
                    try:
                        self._q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                else:
                    return
                with self._lock:
                    self._next_produce = start + self.unit
                    self._produced = self._next_produce
                    self._win["produced"] += self.unit
        except BaseException as e:  # noqa: BLE001 — surfaced on next pop
            self._error = e

    def _check_payload(self, payload) -> str | None:
        """Shape/dtype vs the first unit's template, floats finite, int
        leaves non-negative (episode indices/labels/token ids are all
        >= 0 in this repo). Returns a verdict string, None when clean."""
        import jax

        leaves = jax.tree_util.tree_leaves(payload)
        sig = [(np.shape(x), np.asarray(x).dtype) for x in leaves]
        if self._template is None:
            self._template = sig
        elif sig != self._template:
            return f"batch signature changed: {sig} != {self._template}"
        for x in leaves:
            a = np.asarray(x)
            if np.issubdtype(a.dtype, np.floating):
                if not np.all(np.isfinite(a)):
                    return "non-finite values in a float leaf"
            elif np.issubdtype(a.dtype, np.integer):
                if a.size and int(a.min()) < 0:
                    return "negative values in an integer leaf"
        return None

    # --- consumer side ----------------------------------------------------

    def _producer_alive(self) -> bool:
        """Depth 0 has no producer thread BY DESIGN — it must read as
        alive or the watchdog mis-diagnoses feed_dead on every
        synchronous-mode record."""
        return self.depth == 0 or (
            self._thread is not None and self._thread.is_alive()
        )

    def _account_inline(self, dt: float, n: int) -> None:
        """Depth-0 bookkeeping for one synchronous draw of ``n`` batches
        taking ``dt`` seconds: at depth 0 the consumer's wait on the feed
        IS the inline production, so the time accounts as BOTH stall and
        produce — feed_stall_frac then means "fraction of wall the
        trainer waited on the feed" at every depth (the serial-vs-
        pipelined comparison bench.py makes)."""
        with self._lock:
            self._consumed += n
            self._produced = self._consumed
            self._win["consumed"] += n
            self._win["produced"] += n
            self._stall_s += dt
            self._produce_s += dt
            self._win["stall_s"] += dt
            self._win["produce_s"] += dt

    def _tick(self, stalled_s: float) -> None:
        """Stall telemetry while blocked: a kind="data" record the obs
        watchdog can turn into a feed_stall event (obs/health.py). Uses the
        consumed batch count as the step."""
        if self.logger is None:
            return
        with self._lock:
            self.logger.log(
                self._consumed, "data",
                produced=float(self._produced),
                consumed=float(self._consumed),
                queue_depth=float(self._q.qsize()),
                stalled_s=round(stalled_s, 3),
                producer_alive=float(self._producer_alive()),
                poisoned=float(self._poisoned),
            )

    def _pop_item(self) -> _Item:
        self._ensure_producer()
        t0 = time.monotonic()
        next_tick = t0 + self._stall_tick_s
        while True:
            if self._error is not None:
                raise FeedError("feed producer died") from self._error
            try:
                item = self._q.get(timeout=0.2)
                break
            except queue.Empty:
                if self._thread is None or not self._thread.is_alive():
                    if self._error is not None:
                        raise FeedError(
                            "feed producer died"
                        ) from self._error
                    raise FeedError("feed producer exited without error")
                now = time.monotonic()
                if now >= next_tick:
                    self._tick(now - t0)
                    next_tick = now + self._stall_tick_s
        waited = time.monotonic() - t0
        with self._lock:
            self._stall_s += waited
            self._win["stall_s"] += waited
            # Prune captured states behind this unit: position can never
            # rewind past the unit currently being consumed.
            for s in [s for s in self._states if s < item.start]:
                del self._states[s]
        if item.poisoned is not None:
            with self._lock:
                self._poisoned += 1
            self._tick(0.0)  # poisoned counter reaches the watchdog
            raise FeedError(
                f"poisoned batch refused at index {item.start}: "
                f"{item.poisoned}"
            )
        return item

    def _slice_batch(self, payload, off: int):
        """One batch out of a fused [S,B,...] unit payload."""
        sup, qry, lab = payload
        from induction_network_on_fewrel_tpu.train.feature_cache import (
            IndexEpisodeBatch,  # deferred: jax-heavy module
        )

        return IndexEpisodeBatch(sup[off], qry[off], lab[off])

    def _next_single(self):
        if self.depth == 0:
            # Synchronous mode still honors the drillable faults so
            # --feed_fault works at any depth (poison respects indices).
            start = self._consumed
            if self.faults.stalls_unit(start):
                # Wedged-feed drill without a producer thread: block here
                # emitting stall ticks, exactly what a hung sampler does —
                # the watchdog trips feed_stall instead of the drill
                # silently sampling past the fault.
                t0 = time.monotonic()
                while True:
                    time.sleep(self._stall_tick_s)
                    self._tick(time.monotonic() - t0)
            t0 = time.monotonic()
            if self.faults.slow_s > 0:
                time.sleep(self.faults.slow_s)
            batch = self.base.sample_batch()
            dt = time.monotonic() - t0
            if self.faults.poisons_unit(start, 1):
                batch = poison_tree(batch)
            if self._should_validate():
                verdict = self._check_payload(batch)
                if verdict is not None:
                    with self._lock:
                        self._poisoned += 1
                    self._tick(0.0)
                    raise FeedError(
                        f"poisoned batch refused at index {start}: {verdict}"
                    )
            self._account_inline(dt, 1)
            return batch
        if self._cur is None or self._cur_off >= self.unit:
            self._cur = self._pop_item()
            self._cur_off = 0
        item, off = self._cur, self._cur_off
        out = (
            self._slice_batch(item.payload, off)
            if self.unit > 1 else item.payload
        )
        self._cur_off += 1
        if self._cur_off >= self.unit:
            self._cur = None
        with self._lock:
            self._consumed += 1
            self._win["consumed"] += 1
        return out

    def sample_batch(self):
        return self._next_single()

    def _sample_fused(self, s: int):
        """Fused twin (installed only when unit > 1): serves whole produced
        units on the fast path; assembles across unit boundaries when the
        consumption pattern left a partial unit behind."""
        if self.depth == 0:
            if self.faults.active:
                # Faults need per-batch indices; take the generic path.
                batches = [self._next_single() for _ in range(s)]
                return self._stack_batches(batches)
            t0 = time.monotonic()
            out = self.base.sample_fused(s)
            self._account_inline(time.monotonic() - t0, s)
            return out
        if s == self.unit and self._cur is None:
            item = self._pop_item()
            with self._lock:
                self._consumed += s
                self._win["consumed"] += s
            return item.payload
        batches = [self._next_single() for _ in range(s)]
        return self._stack_batches(batches)

    @staticmethod
    def _stack_batches(batches):
        """Re-stack single batches into the fused [S,B,...] layout. Slices
        of device-put payloads stack ON DEVICE (jnp) — np.stack would pull
        every leaf back to host and re-upload, inverting the producer-side
        device-put win for any consumption pattern that leaves a partial
        unit behind (e.g. a library-built trainer whose init_state draws
        one batch before the fused loop)."""
        import jax

        def stack(xs):
            if isinstance(xs[0], jax.Array):
                import jax.numpy as jnp

                return jnp.stack(xs)
            return np.stack([np.asarray(x) for x in xs])

        return tuple(stack([b[f] for b in batches]) for f in range(3))

    def __iter__(self) -> Iterator:
        while True:
            yield self.sample_batch()

    # --- cursor -----------------------------------------------------------

    def cursor_state(self) -> PipelineCursor:
        """The restorable position at the CONSUMED boundary. Prefetched
        batches sitting in the queue are intentionally not covered — they
        re-produce on resume."""
        with self._lock:
            c = self._consumed
            if self.depth == 0:
                state, captured_at = capture_sampler_state(self.base), c
            else:
                eligible = [s for s in self._states if s <= c]
                if not eligible:
                    raise RuntimeError(
                        f"no captured sampler state at or before batch {c} "
                        f"(internal bookkeeping bug)"
                    )
                captured_at = max(eligible)
                state = self._states[captured_at]
            if state.get("kind") == "replay":
                # Protocol-less sampler: restore means "fresh sampler +
                # replay", so the capture point is the stream origin.
                captured_at = 0
            return PipelineCursor(
                consumed=c,
                captured_at=captured_at,
                sampler_state=state,
                layout=dict(self._layout),
                stream_tag=self.stream_tag,
            )

    def restore_cursor(self, cursor: PipelineCursor) -> None:
        """Reposition the stream to ``cursor`` — the resumed sequence of
        batches is byte-identical to what the uninterrupted run would have
        consumed next. Validates the layout fingerprint and stream tag
        first (a mismatch would silently splice two different streams)."""
        cursor.check_layout(self._layout)
        if cursor.stream_tag != self.stream_tag:
            raise ValueError(
                f"pipeline cursor stream tag {cursor.stream_tag!r} does not "
                f"match this feed's {self.stream_tag!r} (different --mixture "
                f"/ sampler wiring); resume with the original configuration"
            )
        self._halt_producer()
        restore_sampler_state(
            self.base, cursor.sampler_state,
            skip=cursor.consumed - cursor.captured_at,
        )
        with self._lock:
            self._consumed = cursor.consumed
            self._produced = cursor.consumed
            self._next_produce = cursor.consumed
            self._states = {
                cursor.consumed: capture_sampler_state(self.base)
            }
            self._cur, self._cur_off = None, 0
        # Producer restarts lazily on the next pop (same generation path
        # as first use).

    def _halt_producer(self) -> None:
        self._gen += 1
        self._stop.set()
        # Unblock a producer waiting on a full queue.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # Drain anything the exiting producer managed to enqueue after the
        # drain above (put/get race is benign but must not survive).
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        # Cleared AFTER the join (a dying thread writes _error on its way
        # out): a halt starts a fresh producer generation, and a stale
        # error from the dead one must not poison it — restore_cursor's
        # contract is a FULL reposition, so reposition-and-retry after a
        # transient producer failure is a legitimate caller move.
        self._error = None

    # --- telemetry --------------------------------------------------------

    def stats(self) -> dict:
        """Cumulative counters (bench.py reads these)."""
        with self._lock:
            return {
                "produced": self._produced,
                "consumed": self._consumed,
                "queue_depth": self._q.qsize(),
                "stall_s": round(self._stall_s, 6),
                "produce_s": round(self._produce_s, 6),
                "poisoned": self._poisoned,
            }

    def drain_stats(self) -> dict:
        """Per-window feed telemetry for one kind="data" record: counters
        since the last drain plus instantaneous queue state. All floats
        (MetricsLogger coerces anyway)."""
        now = time.monotonic()
        with self._lock:
            win, self._win = self._win, {
                "stall_s": 0.0, "produce_s": 0.0, "consumed": 0,
                "produced": 0,
            }
            window_s = now - self._win_t0
            self._win_t0 = now
            qd = self._q.qsize()
            return {
                "produced": float(self._produced),
                "consumed": float(self._consumed),
                "queue_depth": float(qd),
                "episodes_buffered": float(
                    qd * self.unit * self.batch_size
                ),
                "stall_s": round(win["stall_s"], 6),
                "produce_s": round(win["produce_s"], 6),
                "window_s": round(window_s, 6),
                "window_consumed": float(win["consumed"]),
                "producer_alive": float(self._producer_alive()),
                "poisoned": float(self._poisoned),
            }

    def close(self) -> None:
        self._halt_producer()
        if hasattr(self.base, "close"):
            self.base.close()
