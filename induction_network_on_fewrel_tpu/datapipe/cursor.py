"""Serializable pipeline cursor: WHERE the episode stream is, exactly.

A checkpoint that omits the input-pipeline position silently changes the
training data on resume: the restored model continues from step S, but the
sampler restarts from batch 0 (or wherever a fresh seed lands), so the
resumed run replays a different episode stream than the uninterrupted one.
The cursor closes that hole. It captures, per checkpoint:

* the **sampler stream state** at a captured batch index — exact RNG
  state for the Python samplers (``numpy.random.Generator`` bit-generator
  state, a JSON-able dict), the next-batch sequence number for the native
  C++ samplers (pure functions of ``(seed, batch_index)``), recursively
  for mixtures and per-host wrappers;
* the **consumed batch index** — how many batches the trainer actually
  took (the producer may have prefetched further; prefetched-but-unconsumed
  batches are re-produced on resume, never skipped);
* a **layout fingerprint** — process count/index and global/local batch
  size. Per-host streams are seeded per process, so restoring a cursor
  under a different layout would silently splice two different global
  streams; the fingerprint makes that a loud error instead.

Restoring is ``restore_sampler_state`` (exact state) plus a bounded replay
of ``consumed - captured_at`` discarded batches (mid-unit resume: the
capture granularity is one producer unit, at most ``steps_per_call``
batches, so the replay is cheap and exact).

The capture/restore protocol is duck-typed: samplers may implement
``feed_state() -> dict`` and ``restore_feed_state(state)`` (the repo's
samplers all do — sampling/episodes.py, train/feature_cache.py,
native/sampler.py, parallel/hostfeed.py, datapipe/mixture.py). Samplers
without the protocol fall back to ``{"kind": "replay"}``: restore then
means "fresh sampler + discard ``consumed`` batches", which is still exact
for any deterministic sampler, just not O(1).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

CURSOR_VERSION = 1


def capture_sampler_state(sampler) -> dict:
    """The sampler's stream state, restorable via restore_sampler_state.

    ``{"kind": "replay"}`` when the sampler has no feed_state protocol —
    restore must then replay from a FRESH sampler."""
    fn = getattr(sampler, "feed_state", None)
    if fn is None:
        return {"kind": "replay"}
    return fn()


def restore_sampler_state(sampler, state: dict, skip: int = 0) -> None:
    """Set ``sampler`` to ``state``'s position, then discard ``skip``
    batches (mid-unit resume). For ``kind="replay"`` the sampler must be
    freshly constructed with the original seed; ``skip`` then counts from
    batch 0."""
    if state.get("kind") != "replay":
        fn = getattr(sampler, "restore_feed_state", None)
        if fn is None:
            raise ValueError(
                f"cursor carries state kind {state.get('kind')!r} but "
                f"{type(sampler).__name__} has no restore_feed_state"
            )
        fn(state)
    for _ in range(skip):
        sampler.sample_batch()


def current_layout(global_batch: int, local_batch: int | None = None) -> dict:
    """The layout fingerprint of THIS process (see module docstring)."""
    try:
        import jax

        pc, pi = jax.process_count(), jax.process_index()
    except Exception:  # noqa: BLE001 — cursor math must not need a backend
        pc, pi = 1, 0
    return {
        "process_count": int(pc),
        "process_index": int(pi),
        "global_batch": int(global_batch),
        "local_batch": int(local_batch if local_batch is not None
                           else global_batch),
    }


@dataclasses.dataclass
class PipelineCursor:
    """One restorable input-pipeline position (all fields JSON-able)."""

    consumed: int               # batches the trainer consumed so far
    captured_at: int            # batch index ``sampler_state`` corresponds to
    sampler_state: dict         # from capture_sampler_state
    layout: dict                # from current_layout
    stream_tag: str = ""        # mixture spec / seed tag, validated on restore
    version: int = CURSOR_VERSION

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "PipelineCursor":
        v = int(d.get("version", 0))
        if v != CURSOR_VERSION:
            raise ValueError(
                f"pipeline cursor version {v} unsupported "
                f"(this build reads v{CURSOR_VERSION})"
            )
        return cls(
            consumed=int(d["consumed"]),
            captured_at=int(d["captured_at"]),
            sampler_state=dict(d["sampler_state"]),
            layout=dict(d["layout"]),
            stream_tag=str(d.get("stream_tag", "")),
            version=v,
        )

    @classmethod
    def from_json(cls, s: str) -> "PipelineCursor":
        return cls.from_dict(json.loads(s))

    def check_layout(self, layout: dict) -> None:
        """Raise when this cursor was written under a different process
        layout — resuming would splice two different global streams."""
        mismatched = {
            k: (self.layout.get(k), layout.get(k))
            for k in ("process_count", "process_index",
                      "global_batch", "local_batch")
            if self.layout.get(k) != layout.get(k)
        }
        if mismatched:
            raise ValueError(
                f"pipeline cursor layout mismatch {mismatched}: the episode "
                "stream is seeded per process layout, so resuming under a "
                "different one would not reproduce the uninterrupted "
                "stream. Resume with the original layout, or start a fresh "
                "run directory."
            )


def _json_scalarize(obj: Any) -> Any:
    """numpy scalars/arrays inside an RNG state dict -> plain Python so the
    cursor serializes with the stdlib json encoder."""
    import numpy as np

    if isinstance(obj, dict):
        return {k: _json_scalarize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_scalarize(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


def rng_feed_state(rng) -> dict:
    """feed_state payload for a ``numpy.random.Generator``-backed sampler:
    the bit-generator's full state (exact O(1) resume)."""
    return {
        "kind": "rng",
        "bit_generator": type(rng.bit_generator).__name__,
        "state": _json_scalarize(rng.bit_generator.state),
    }


def restore_rng_feed_state(rng, state: dict) -> None:
    got = state.get("bit_generator")
    want = type(rng.bit_generator).__name__
    if got != want:
        raise ValueError(
            f"cursor RNG state is for bit generator {got!r}, sampler uses "
            f"{want!r} — numpy version / sampler construction mismatch"
        )
    rng.bit_generator.state = state["state"]
