"""Feed-path fault injection: make input-pipeline failure modes drillable.

The serving stack got this discipline in PR 2 (``--nan_inject_step``,
``--fault_step``); the feed path gets the same here. A fault spec is a
comma-separated list of directives applied inside the producer:

* ``slow:SECONDS``   — sleep SECONDS before producing every unit (a slow
  host sampler / starved CPU; the stall telemetry and bench leg quantify
  how much of it prefetch hides).
* ``stall:INDEX``    — the producer stops producing once the next batch
  index reaches INDEX (a wedged worker). The consumer's stall ticks keep
  flowing, so the watchdog trips ``feed_stall`` instead of the run hanging
  silently.
* ``poison:INDEX``   — corrupt the unit containing batch INDEX (float
  leaves NaN-poisoned, int leaves negated) AFTER cursor capture, the way a
  bad DMA or a buggy transform would. The feed's validator refuses to hand
  the batch to the train step and emits a critical ``feed_poisoned``
  health event.

Parsing lives here so ``--feed_fault`` on the CLI, the tests, and any
drill script agree on one grammar (``FeedFaults.parse``).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class FeedFaults:
    """Immutable fault plan; ``FeedFaults()`` (all off) is the default."""

    slow_s: float = 0.0         # per-unit producer delay
    stall_at: int | None = None  # stop producing at this batch index
    poison_at: int | None = None  # corrupt the unit containing this index

    @classmethod
    def parse(cls, spec: str | None) -> "FeedFaults":
        """``"slow:0.05,poison:30"`` -> FeedFaults(slow_s=0.05, poison_at=30).

        Empty/None -> all off. Unknown directives raise (a typoed drill
        that silently does nothing is worse than no drill)."""
        if not spec:
            return cls()
        slow_s, stall_at, poison_at = 0.0, None, None
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, arg = part.partition(":")
            if name == "slow":
                slow_s = float(arg)
                if slow_s < 0:
                    raise ValueError(f"slow delay must be >= 0, got {slow_s}")
            elif name == "stall":
                stall_at = int(arg)
            elif name == "poison":
                poison_at = int(arg)
            else:
                raise ValueError(
                    f"unknown feed fault {name!r} (known: slow:SECONDS, "
                    f"stall:INDEX, poison:INDEX)"
                )
        return cls(slow_s=slow_s, stall_at=stall_at, poison_at=poison_at)

    @property
    def active(self) -> bool:
        return (
            self.slow_s > 0
            or self.stall_at is not None
            or self.poison_at is not None
        )

    def stalls_unit(self, unit_start: int) -> bool:
        return self.stall_at is not None and unit_start >= self.stall_at

    def poisons_unit(self, unit_start: int, unit: int) -> bool:
        return (
            self.poison_at is not None
            and unit_start <= self.poison_at < unit_start + unit
        )


# --- query-side episode perturbation (ISSUE 10, scenarios harness) --------
#
# The fault plan above corrupts the FEED (a systems failure: bad DMA,
# wedged producer). The perturbations below corrupt the *queries inside a
# well-formed episode* — the model-quality failure modes a serving fleet
# actually meets: noisy tokenization, truncated inputs, out-of-domain
# garbage. Supports are left untouched on purpose: in the serving split
# the class vectors are distilled once from clean supports and only the
# query stream degrades. Same grammar discipline as FeedFaults.parse —
# one spec string shared by tools/scenarios.py, the tests, and any drill.

QUERY_PERTURBATIONS = ("token_noise", "mask_drop", "blank")


def parse_perturbation(spec: str) -> tuple[str, float]:
    """``"token_noise:0.3"`` -> ("token_noise", 0.3). Unknown modes or
    rates outside [0, 1] raise (a typoed leg that silently evaluates
    clean episodes would report a fake robustness number)."""
    name, _, arg = spec.strip().partition(":")
    if name not in QUERY_PERTURBATIONS:
        raise ValueError(
            f"unknown query perturbation {name!r} "
            f"(known: {', '.join(QUERY_PERTURBATIONS)})"
        )
    rate = float(arg) if arg else 1.0
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"perturbation rate must be in [0, 1], got {rate}")
    return name, rate


def perturb_query_batch(batch, mode: str, rate: float, rng):
    """Perturb the QUERY side of one EpisodeBatch (numpy, shape- and
    dtype-preserving; supports and labels untouched).

    * ``token_noise`` — each unmasked query token is replaced, with
      probability ``rate``, by a token drawn from the batch's own
      unmasked-token marginal (stays in-vocab by construction).
    * ``mask_drop``  — the trailing ``rate`` fraction of each query's
      mask zeroes out (input truncation).
    * ``blank``      — a ``rate`` fraction of query ROWS have every
      unmasked token replaced by the batch's single most frequent token
      (constant out-of-domain garbage — the strongest leg).
    """
    import numpy as np

    word = np.array(batch.query_word)          # writable copies
    mask = np.array(batch.query_mask)
    on = mask > 0
    if mode == "token_noise":
        pool = word[on]
        flip = on & (rng.random(word.shape) < rate)
        word[flip] = rng.choice(pool, size=int(flip.sum()))
    elif mode == "mask_drop":
        lengths = on.sum(axis=-1, keepdims=True)           # [..., 1]
        # Floor at one kept token: a fully-masked query drives the
        # encoder's masked_max to -inf (NaN logits) — that would measure
        # a numerics artifact, not robustness to truncation.
        keep = np.maximum(np.ceil(lengths * (1.0 - rate)), 1.0)
        pos = np.cumsum(on, axis=-1)                       # 1-based in-mask
        mask = np.where(on & (pos > keep), 0.0, mask).astype(
            batch.query_mask.dtype
        )
    elif mode == "blank":
        pool = word[on]
        vals, counts = np.unique(pool, return_counts=True)
        fill = vals[np.argmax(counts)]
        rows = rng.random(word.shape[:-1]) < rate          # [B, TQ]
        word = np.where((rows[..., None] & on), fill, word)
    else:
        raise ValueError(f"unknown query perturbation {mode!r}")
    return batch._replace(
        query_word=word.astype(batch.query_word.dtype), query_mask=mask
    )


class PerturbedSampler:
    """Wrap any episode sampler so every batch's queries pass through one
    perturbation leg — drops into ``FewShotTrainer.evaluate(sampler=...)``
    unchanged (exposes ``batch_size``/``total_q``/``sample_batch``).
    Deterministic given (sampler seed, ``seed``)."""

    def __init__(self, sampler, spec: str, seed: int = 0):
        import numpy as np

        self.mode, self.rate = parse_perturbation(spec)
        self.spec = spec
        self._sampler = sampler
        self._rng = np.random.default_rng(seed)
        self.batch_size = sampler.batch_size
        self.total_q = sampler.total_q

    def sample_batch(self):
        return perturb_query_batch(
            self._sampler.sample_batch(), self.mode, self.rate, self._rng
        )

    def __iter__(self):
        while True:
            yield self.sample_batch()

    def close(self) -> None:
        if hasattr(self._sampler, "close"):
            self._sampler.close()


def poison_tree(tree):
    """NaN-poison float leaves, negate int leaves (shape-preserving, so the
    corruption models bad VALUES, not a feed bug the shape check would
    catch for free). numpy-only — runs on host batches."""
    import numpy as np

    def bad(x):
        a = np.array(x)  # writable copy
        if np.issubdtype(a.dtype, np.floating):
            a.fill(np.nan)
        elif np.issubdtype(a.dtype, np.integer):
            np.negative(a, out=a)
            a -= 1  # 0 rows must corrupt too
        return a

    import jax

    return jax.tree.map(bad, tree)
