"""Feed-path fault injection: make input-pipeline failure modes drillable.

The serving stack got this discipline in PR 2 (``--nan_inject_step``,
``--fault_step``); the feed path gets the same here. A fault spec is a
comma-separated list of directives applied inside the producer:

* ``slow:SECONDS``   — sleep SECONDS before producing every unit (a slow
  host sampler / starved CPU; the stall telemetry and bench leg quantify
  how much of it prefetch hides).
* ``stall:INDEX``    — the producer stops producing once the next batch
  index reaches INDEX (a wedged worker). The consumer's stall ticks keep
  flowing, so the watchdog trips ``feed_stall`` instead of the run hanging
  silently.
* ``poison:INDEX``   — corrupt the unit containing batch INDEX (float
  leaves NaN-poisoned, int leaves negated) AFTER cursor capture, the way a
  bad DMA or a buggy transform would. The feed's validator refuses to hand
  the batch to the train step and emits a critical ``feed_poisoned``
  health event.

Parsing lives here so ``--feed_fault`` on the CLI, the tests, and any
drill script agree on one grammar (``FeedFaults.parse``).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class FeedFaults:
    """Immutable fault plan; ``FeedFaults()`` (all off) is the default."""

    slow_s: float = 0.0         # per-unit producer delay
    stall_at: int | None = None  # stop producing at this batch index
    poison_at: int | None = None  # corrupt the unit containing this index

    @classmethod
    def parse(cls, spec: str | None) -> "FeedFaults":
        """``"slow:0.05,poison:30"`` -> FeedFaults(slow_s=0.05, poison_at=30).

        Empty/None -> all off. Unknown directives raise (a typoed drill
        that silently does nothing is worse than no drill)."""
        if not spec:
            return cls()
        slow_s, stall_at, poison_at = 0.0, None, None
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, arg = part.partition(":")
            if name == "slow":
                slow_s = float(arg)
                if slow_s < 0:
                    raise ValueError(f"slow delay must be >= 0, got {slow_s}")
            elif name == "stall":
                stall_at = int(arg)
            elif name == "poison":
                poison_at = int(arg)
            else:
                raise ValueError(
                    f"unknown feed fault {name!r} (known: slow:SECONDS, "
                    f"stall:INDEX, poison:INDEX)"
                )
        return cls(slow_s=slow_s, stall_at=stall_at, poison_at=poison_at)

    @property
    def active(self) -> bool:
        return (
            self.slow_s > 0
            or self.stall_at is not None
            or self.poison_at is not None
        )

    def stalls_unit(self, unit_start: int) -> bool:
        return self.stall_at is not None and unit_start >= self.stall_at

    def poisons_unit(self, unit_start: int, unit: int) -> bool:
        return (
            self.poison_at is not None
            and unit_start <= self.poison_at < unit_start + unit
        )


def poison_tree(tree):
    """NaN-poison float leaves, negate int leaves (shape-preserving, so the
    corruption models bad VALUES, not a feed bug the shape check would
    catch for free). numpy-only — runs on host batches."""
    import numpy as np

    def bad(x):
        a = np.array(x)  # writable copy
        if np.issubdtype(a.dtype, np.floating):
            a.fill(np.nan)
        elif np.issubdtype(a.dtype, np.integer):
            np.negative(a, out=a)
            a -= 1  # 0 rows must corrupt too
        return a

    import jax

    return jax.tree.map(bad, tree)
