"""Online step-time decomposition: where every wall second of a training
window went, with named causes when a window goes out of band.

ISSUE 11 tentpole, layer 1. The serving data plane has had this since
ISSUE 9 (queue/pack/execute/respond segments that tile each request's
measured latency EXACTLY); this module applies the same discipline to
training: per metric window, wall-clock time decomposes into host-observed
segments that tile the window by construction —

* ``data_wait``      — ``train/sample`` spans (feed/sampler time the loop
                       thread spent blocked on input)
* ``host_dispatch``  — ``train/dispatch`` spans (tracing + jit dispatch;
                       on synchronous backends this also carries device
                       compute)
* ``device_sync``    — ``train/metrics_fetch`` spans (the hard-sync value
                       fetch where async device execution surfaces on the
                       host — on tunneled TPU backends this IS device
                       time, bench.py's hard-sync finding)
* ``checkpoint`` / ``eval`` / ``probe`` — their spans at val boundaries
* ``other``          — the residual (loop bookkeeping, logging); defined
                       as window − sum(tracked), so the tiles sum to the
                       measured window EXACTLY, every window — the
                       acceptance invariant (tests/test_perf.py).

Overlapping context (recorded, never tiled — they happen INSIDE the
segments above): ``compile_ms``/``compiles`` from the CompileWatcher
(obs/compile.py) and ``gc_ms`` from a ``gc.callbacks`` pause meter.

Out-of-band classification: a rolling-median baseline of per-window step
time (same warmup discipline as the throughput watchdog); a window slower
than ``oob_factor`` × baseline is classified into ONE named cause, in
priority order —

* ``recompile_burst``    — compiles fired inside the window
* ``feed_stall``         — data_wait dominates the window
* ``checkpoint_spike``   — checkpoint segment dominates
* ``gc_pause``           — collector pauses dominate
* ``neighbor_contention``— same segment mix, everything uniformly slower:
                           the host/device itself degraded (straggler,
                           noisy neighbor, thermal). The residual cause —
                           asserted only when nothing above explains the
                           excess.

Each cause is a once-latched CRITICAL ``perf_regression`` health event
(one incident per episode, the obs/health discipline) with auto-captured
diagnostics (flight dump + span snapshot via DiagnosticsCapture); a
window back in band re-arms. ``kind="perf"`` records land in
metrics.jsonl for every window; tools/obs_report.py renders the perf
section and ``--check`` validates the stream.

Cost discipline: the observer adds ZERO per-step work (the spans already
exist); one ``observe_window`` per metric window scans the span ring once
(bounded at the tracker capacity). Gated < 2% of p50 step in
tests/test_perf.py, PR 8's methodology.
"""

from __future__ import annotations

import gc
import math
import threading
import time
from collections import deque
from typing import Callable

# Span name -> tiled segment. Unmapped top-level spans (rare) fall into
# ``other`` implicitly — the residual definition keeps the tiling exact no
# matter what runs on the loop thread.
SEGMENT_OF = {
    "train/sample": "data_wait",
    "train/dispatch": "host_dispatch",
    "train/metrics_fetch": "device_sync",
    "train/checkpoint": "checkpoint",
    "train/eval": "eval",
    "train/grad_probe": "probe",
}
TILE_SEGMENTS = (
    "data_wait", "host_dispatch", "device_sync", "checkpoint", "eval",
    "probe", "other",
)
CAUSES = (
    "recompile_burst", "feed_stall", "checkpoint_spike", "gc_pause",
    "neighbor_contention",
)


class GcPauseMeter:
    """Accumulated collector pause seconds via ``gc.callbacks`` — the only
    honest way to see GC stalls from inside the process. Global (the
    collector is); ``total_s`` is read-diffed per window."""

    def __init__(self):
        self.total_s = 0.0
        self.collections = 0
        self._t0: float | None = None
        self._installed = False

    def _cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._t0 = time.monotonic()
        elif phase == "stop" and self._t0 is not None:
            self.total_s += time.monotonic() - self._t0
            self.collections += 1
            self._t0 = None

    def install(self) -> "GcPauseMeter":
        if not self._installed:
            gc.callbacks.append(self._cb)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            try:
                gc.callbacks.remove(self._cb)
            except ValueError:
                pass
            self._installed = False


class PerfObserver:
    """Per-window step-time decomposition over the host span ring.

    ``tracker`` defaults to the process-global SpanTracker; ``logger``
    receives one ``kind="perf"`` record per window; ``compile_watcher``
    (obs/compile.CompileWatcher) supplies the in-window compile context;
    ``capture`` (obs/health.DiagnosticsCapture) auto-captures on the
    first window of each out-of-band episode; ``on_event`` additionally
    receives the HealthEvent (the cli wires the watchdog's emitter so
    perf events ride the same health stream + flight recorder).
    ``floor_ms`` is the shared roofline projection for one step
    (utils/roofline.projected_floor_ms at the deployment's calibration)
    — recorded next to the measured decomposition so "how far off the
    analytic floor is this config running" is a stream field, not a
    ledger session.
    """

    def __init__(
        self,
        logger=None,
        tracker=None,
        compile_watcher=None,
        capture=None,
        on_event: Callable | None = None,
        oob_factor: float = 1.5,
        baseline_window: int = 8,
        baseline_warmup: int = 2,
        floor_ms: float | None = None,
        feed_stall_frac: float = 0.25,
        checkpoint_frac: float = 0.25,
        gc_frac: float = 0.25,
    ):
        if tracker is None:
            from induction_network_on_fewrel_tpu.obs.spans import get_tracker

            tracker = get_tracker()
        self._tracker = tracker
        self.logger = logger
        self._compile = compile_watcher
        self.capture = capture
        self.on_event = on_event
        self.oob_factor = oob_factor
        self.baseline_warmup = baseline_warmup
        self.floor_ms = floor_ms
        self._feed_stall_frac = feed_stall_frac
        self._checkpoint_frac = checkpoint_frac
        self._gc_frac = gc_frac
        self.gc_meter = GcPauseMeter().install()
        self._step_ms = deque(maxlen=baseline_window)
        # The FIRST window contains the step compile (seconds of one-time
        # cost) and must not seed the baseline — an inflated baseline
        # blinds the out-of-band detector for the rest of the run (the
        # watchdog's throughput_warmup rationale, applied here).
        self._skip_baseline = 1
        self._mark: float | None = None      # tracker-timeline window start
        self._last_step: int | None = None
        self._last_compiles = 0
        self._last_compile_s = 0.0
        self._last_gc_s = 0.0
        self._last_gc_n = 0
        self._last_evicted = 0
        self._thread: str | None = None
        self._latched: str | None = None     # active out-of-band cause
        self.windows = 0
        self.events: list = []
        self.captured: dict[str, dict] = {}

    # --- lifecycle --------------------------------------------------------

    def begin(self, step: int) -> None:
        """Open the first window at loop entry (the trainer calls this
        once; ``observe_window`` then closes/reopens per metric window).
        Binds the observer to the CALLING thread — only that thread's
        spans tile its windows (the producer/serving threads have their
        own timelines)."""
        self._mark = time.monotonic() - self._tracker._t0
        self._last_step = int(step)
        self._thread = threading.current_thread().name
        if self._compile is not None:
            self._last_compiles = self._compile.compiles
            self._last_compile_s = self._compile.compile_s_total
        self._last_gc_s = self.gc_meter.total_s
        self._last_gc_n = self.gc_meter.collections
        self._last_evicted = self._tracker.evicted

    def close(self) -> None:
        self.gc_meter.uninstall()

    # --- the per-window observation --------------------------------------

    def _segment_sums(self, w0: float, w1: float) -> dict[str, float]:
        """Clipped per-segment span seconds inside [w0, w1] on the bound
        thread, top-level spans only (depth 0 — children re-state their
        parent's time). One pass over the ring under its lock, Span
        objects read in place (no dict conversion — this is the whole
        per-window cost)."""
        sums = {s: 0.0 for s in TILE_SEGMENTS}
        tracker = self._tracker
        with tracker._lock:
            ring = list(tracker._ring)
        for s in ring:
            if s.depth != 0 or s.thread != self._thread:
                continue
            seg = SEGMENT_OF.get(s.name)
            if seg is None:
                continue
            lo = max(s.start_s, w0)
            hi = min(s.start_s + s.dur_s, w1)
            if hi > lo:
                sums[seg] += hi - lo
        return sums

    def observe_window(self, step: int) -> dict | None:
        """Close the current window at ``step``; emit the kind="perf"
        record; classify if out of band. Returns the record dict (None
        before ``begin``)."""
        if self._mark is None or self._last_step is None:
            return None
        now = time.monotonic() - self._tracker._t0
        w0, w1 = self._mark, now
        steps = int(step) - self._last_step
        self._mark, self._last_step = now, int(step)
        window_s = w1 - w0
        if steps <= 0 or window_s <= 0:
            return None
        sums = self._segment_sums(w0, w1)
        tracked = sum(sums.values())
        # The tiling invariant: other := window − tracked. Tracked spans
        # are disjoint (same thread, depth 0, clipped), so tracked <=
        # window up to clock granularity; clamp shields the subtraction
        # from sub-microsecond rounding.
        sums["other"] = max(0.0, window_s - tracked)
        step_ms = window_s * 1e3 / steps
        # Overlapping context: compiles + GC pauses inside the window.
        win_compiles, compile_ms = 0, 0.0
        if self._compile is not None:
            win_compiles = self._compile.compiles - self._last_compiles
            compile_ms = (
                self._compile.compile_s_total - self._last_compile_s
            ) * 1e3
            self._last_compiles = self._compile.compiles
            self._last_compile_s = self._compile.compile_s_total
        gc_ms = (self.gc_meter.total_s - self._last_gc_s) * 1e3
        gc_n = self.gc_meter.collections - self._last_gc_n
        self._last_gc_s = self.gc_meter.total_s
        self._last_gc_n = self.gc_meter.collections
        evicted = self._tracker.evicted - self._last_evicted
        self._last_evicted = self._tracker.evicted

        baseline = None
        if len(self._step_ms) >= self.baseline_warmup:
            ordered = sorted(self._step_ms)
            baseline = ordered[len(ordered) // 2]
        rec = {
            "window_s": round(window_s, 6),
            "steps": float(steps),
            "step_ms": round(step_ms, 4),
            **{
                f"{seg}_ms": round(sums[seg] * 1e3, 3)
                for seg in TILE_SEGMENTS
            },
            "segments_sum_ms": round(
                sum(sums.values()) * 1e3, 3
            ),
            "compiles": float(win_compiles),
            "compile_ms": round(compile_ms, 3),
            "gc_ms": round(gc_ms, 3),
            "gc_collections": float(gc_n),
        }
        if evicted:
            # Ring overflow DURING THIS WINDOW may undercount its tracked
            # spans (the loss lands in ``other``); flagged per window as
            # a delta — the cumulative counter would permanently flag
            # every window after the ring's first wrap.
            rec["ring_evicted"] = float(evicted)
        if baseline is not None:
            rec["baseline_step_ms"] = round(baseline, 4)
        if self.floor_ms is not None:
            rec["floor_ms"] = round(self.floor_ms, 4)
            # Compute-facing time per step vs the analytic floor: how far
            # off the roofline this window ran (CPU-honest: large on CPU,
            # the chip sessions read ~1-2x).
            dev_ms = (
                (sums["host_dispatch"] + sums["device_sync"]) * 1e3 / steps
            )
            if self.floor_ms > 0:
                rec["device_over_floor"] = round(dev_ms / self.floor_ms, 3)
        oob = (
            baseline is not None
            and math.isfinite(step_ms)
            and step_ms > self.oob_factor * baseline
        )
        rec["oob"] = float(oob)
        cause = None
        if oob:
            excess_ms = (step_ms - baseline) * steps
            cause = self._classify(
                sums, window_s, win_compiles, gc_ms, compile_ms, excess_ms
            )
            rec["cause"] = cause
        else:
            # In-band (or warmup) window: re-arm (the episode ended) and
            # feed the baseline — an out-of-band window must not drag the
            # baseline up with it (the watchdog's discipline). The
            # compile-bearing first window is skipped entirely.
            self._latched = None
            if self._skip_baseline > 0:
                self._skip_baseline -= 1
            else:
                self._step_ms.append(step_ms)
        self.windows += 1
        # Record BEFORE classifying/capturing: a critical's flight dump
        # must contain the perf window that tripped it (the recorder-
        # before-watchdog ordering discipline, obs/recorder.py).
        if self.logger is not None:
            self.logger.log(int(step), kind="perf", **rec)
        if cause is not None:
            self._maybe_event(int(step), cause, rec, baseline)
        return rec

    def _classify(
        self, sums: dict, window_s: float, win_compiles: int,
        gc_ms: float, compile_ms: float, excess_ms: float,
    ) -> str:
        # Compiles take the blame only when they EXPLAIN a material share
        # of the window's excess over baseline — the obs/compile.py
        # gate_min_s discipline, restated for classification: a ~10 ms
        # utility-pjit shape variant at an eval boundary must not mask a
        # feed stall that actually cost the window (a real step-function
        # recompile is seconds and passes trivially).
        if win_compiles > 0 and compile_ms >= 0.25 * excess_ms:
            return "recompile_burst"
        if sums["data_wait"] / window_s > self._feed_stall_frac:
            return "feed_stall"
        if sums["checkpoint"] / window_s > self._checkpoint_frac:
            return "checkpoint_spike"
        if gc_ms / 1e3 / window_s > self._gc_frac:
            return "gc_pause"
        return "neighbor_contention"

    def _maybe_event(
        self, step: int, cause: str, rec: dict, baseline: float
    ) -> None:
        """Once-latched CRITICAL per out-of-band EPISODE: consecutive
        out-of-band windows are one incident (even if the classifier
        refines the cause mid-episode); an in-band window re-arms."""
        if self._latched is not None:
            return
        self._latched = cause
        from induction_network_on_fewrel_tpu.obs.health import (
            CRITICAL,
            HealthEvent,
        )

        ev = HealthEvent(
            event="perf_regression", severity=CRITICAL, step=step,
            message=(
                f"step time {rec['step_ms']:.2f} ms out of band "
                f"(baseline {baseline:.2f} ms, factor "
                f"{rec['step_ms'] / baseline:.2f}x) — cause: {cause}"
            ),
            data={
                "cause": cause,
                "step_ms": rec["step_ms"],
                "baseline_step_ms": round(baseline, 4),
                "data_wait_ms": rec["data_wait_ms"],
                "compile_ms": rec["compile_ms"],
                "checkpoint_ms": rec["checkpoint_ms"],
                "gc_ms": rec["gc_ms"],
            },
        )
        self.events.append(ev)
        if self.on_event is not None:
            self.on_event(ev)
        if self.capture is not None:
            self.captured[f"perf:{cause}:{step}"] = self.capture.capture(
                reason=f"perf: {ev.message}"
            )
