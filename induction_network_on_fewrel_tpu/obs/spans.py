"""Host-side spans: the timing half of the telemetry spine (ISSUE 2).

A span is one timed region of host code (sampling, dispatch, eval, a
serving batch). Spans nest per-thread, carry attributes, and land in a
fixed-capacity ring buffer — long soaks never grow host memory, and the
flight recorder (obs/recorder.py) can always dump the most recent window.

Two deliberate bridges to the device side:

* ``jax.named_scope`` — entering a span also enters a named scope of the
  same name, so any ops *traced* inside it attribute to the same stage
  name in an XPlane profile. Host spans and device trace rows then share
  one vocabulary ("train/step", "serve/execute") instead of two.
* Overhead discipline — enter/exit is two ``time.monotonic()`` calls, a
  deque append, and a thread-local push/pop. Measured by
  ``tools/obs_report.py --overhead`` against the run's own p50 step time
  (acceptance: < 2% of step time on the headline config).

Request-scoped tracing (ISSUE 9) rides on the same ring:

* Every span carries a ``span_id`` (allocated at ENTRY, so children can
  point at their parent) and, when a trace context is active on the
  thread, a ``trace_id`` — the request/step identity that ties spans
  together ACROSS threads (a serving request is admitted on a client
  thread and executed on the batcher worker).
* ``TraceContext`` is the tiny handle that crosses threads: stash it on
  the unit of work at admission, then ``tracker.trace(ctx)`` in the
  worker and every span opened there joins the same trace.
* Fan-in is first-class: one batch-execute span can ``links`` many
  request trace ids (N admissions -> one launch), which is how the
  continuous batcher's packing stays attributable per request.
* ``TraceSampler`` is the head-sampling decision: deterministic 1-in-N.
  Rate 0 short-circuits to a no-op that allocates NOTHING — the hot
  path's tracing tax is gated < 2% of p50 exec with sampling on
  (tests/test_tracing.py) and exactly zero with it off.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import itertools
import os
import threading
import time
from typing import Any, Callable, Iterator


class TraceContext:
    """The cross-thread trace handle: the trace id plus the span id of
    the originating span (0 = none yet; the FIRST span opened under a
    fresh context fills it in). Callers propagate it, never mutate it."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: int = 0):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # debugging aid only
        return f"TraceContext({self.trace_id!r}, span_id={self.span_id})"


_TRACE_IDS = itertools.count(1)
_TRACE_PREFIX = f"{os.getpid() & 0xFFFF:04x}"


def new_trace_id() -> str:
    """Process-unique trace id: pid prefix + monotonic counter. Cheap (one
    string format), collision-free within a process, and distinguishable
    across the processes of one run directory."""
    return f"{_TRACE_PREFIX}-{next(_TRACE_IDS):08x}"


class TraceSampler:
    """Deterministic head sampler: trace every ``round(1/rate)``-th call.

    ``rate <= 0`` pins ``stride = 0`` and ``maybe_trace`` returns None
    after one attribute test — no counter advance, no allocation — so an
    untraced deployment pays nothing on the hot path. ``rate >= 1``
    traces every request. Deterministic (not random) on purpose: load
    tests and the loadgen get reproducible exemplar counts.
    """

    __slots__ = ("rate", "stride", "_count")

    def __init__(self, rate: float):
        self.rate = max(0.0, float(rate))
        self.stride = 0 if self.rate <= 0 else max(1, round(1.0 / self.rate))
        # itertools.count.__next__ is atomic under the GIL — submitters on
        # many threads share this sampler without a lock.
        self._count = itertools.count() if self.stride else None

    def maybe_trace(self) -> TraceContext | None:
        if not self.stride:
            return None
        if next(self._count) % self.stride:
            return None
        return TraceContext(new_trace_id())


@dataclasses.dataclass
class Span:
    """One completed span. ``start_s`` is on the tracker's monotonic
    timeline (comparable across spans of one process, not wall time)."""

    name: str
    start_s: float
    dur_s: float
    depth: int                 # 0 = top-level in its thread
    parent: str | None         # enclosing span's name, if any
    thread: str
    span_id: int
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)
    trace_id: str | None = None   # request/step trace this span belongs to
    parent_id: int | None = None  # enclosing span's id (same thread), or
    #                               the originating span across threads
    links: tuple[str, ...] = ()   # fan-in: trace ids merged into this span

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "dur_s": round(self.dur_s, 6),
            "depth": self.depth,
            "parent": self.parent,
            "thread": self.thread,
            "span_id": self.span_id,
        }
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.parent_id is not None:
            d["parent_id"] = self.parent_id
        if self.links:
            d["links"] = list(self.links)
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class SpanTracker:
    """Thread-safe ring buffer of completed spans + per-thread nesting.

    The ring holds the most recent ``capacity`` spans; older ones are
    evicted silently (``evicted`` counts them so a report can say "window
    of the last N", not "everything").
    """

    def __init__(self, capacity: int = 4096, xplane_bridge: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        # Process identity (ISSUE 17): when set, snapshot() stamps
        # proc_role/proc_replica/proc_pid on every span dict — the
        # same fields MetricsLogger.set_identity stamps on records, so
        # a span dumped by the flight recorder names the process it
        # came from. Applied at READ time: the hot enter/exit path
        # stays two clock calls and an append.
        self.identity: dict[str, object] = {}
        # RLock: the flight recorder's SIGTERM dump snapshots this tracker
        # from a signal handler that may interrupt the same thread inside
        # _append — a plain lock would deadlock the dump.
        self._lock = threading.RLock()
        self._ring: list[Span] = []
        self._next_slot = 0            # round-robin slot once full
        self.evicted = 0
        # Span ids start at 1: TraceContext.span_id == 0 means "no
        # originating span yet", so id 0 would be indistinguishable.
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._t0 = time.monotonic()
        self._xplane = xplane_bridge

    def set_identity(self, role: str, replica: str | None = None) -> None:
        """Stamp this process's identity onto future snapshot() output.
        Mirrors MetricsLogger.set_identity so spans and metrics records
        from one process carry the same proc_* fields."""
        ident: dict[str, object] = {"proc_role": str(role), "proc_pid": os.getpid()}
        if replica is not None:
            ident["proc_replica"] = str(replica)
        self.identity = ident

    # --- recording -------------------------------------------------------

    def _stack(self) -> list[tuple[str, int]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, span: Span) -> None:
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(span)
            else:
                self._ring[self._next_slot] = span
                self._next_slot = (self._next_slot + 1) % self.capacity
                self.evicted += 1

    # --- trace context (request/step-scoped ids) --------------------------

    def current_trace(self) -> TraceContext | None:
        """The thread's active trace context, if any."""
        return getattr(self._tls, "ctx", None)

    def set_trace(self, ctx: TraceContext | None) -> TraceContext | None:
        """Replace the thread's trace context; returns the previous one.
        The train loop's per-step pattern: a fresh context each iteration
        (no context-manager nesting across a loop body), cleared once
        after the loop."""
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = ctx
        return prev

    def new_context(self) -> TraceContext:
        return TraceContext(new_trace_id())

    @contextlib.contextmanager
    def trace(self, ctx: TraceContext | None = None) -> Iterator[TraceContext]:
        """Activate a trace context for the block: spans opened inside (on
        THIS thread) carry its trace id. Pass the context a request
        carried across threads to adopt it (cross-thread propagation);
        omit it to start a fresh trace."""
        ctx = ctx if ctx is not None else self.new_context()
        prev = self.set_trace(ctx)
        try:
            yield ctx
        finally:
            self.set_trace(prev)

    @contextlib.contextmanager
    def span(
        self, name: str, links: tuple[str, ...] = (), xplane: bool = True,
        **attrs: Any,
    ) -> Iterator[dict]:
        """Time a region. Yields the attrs dict so the body can attach
        results (e.g. ``s["rows"] = len(batch)``) before the span closes.
        ``links`` records fan-in: the trace ids of work merged into this
        span (N admissions -> one batch execute). ``xplane=False`` skips
        the jax.named_scope bridge for spans wrapping PURE host code
        (e.g. serving-side tokenization): the scope would name nothing in
        a device profile, and entering it perturbs jax's jit dispatch
        fast path for the NEXT program launch — measured ~80 µs on the
        in-process engine, the dominant term of the tracing tax before
        this knob existed (tests/test_tracing.py's 2% gate)."""
        stack = self._stack()
        # Span id at ENTRY (not exit): children close before their parent,
        # so a parent id is only known if allocated when the parent opens.
        span_id = next(self._ids)
        ctx = getattr(self._tls, "ctx", None)
        if stack:
            parent, parent_id = stack[-1]
        else:
            parent = None
            # Cross-thread stitch: a top-level span in a worker thread
            # points at the originating span of its adopted trace.
            parent_id = ctx.span_id if ctx is not None and ctx.span_id else None
        if ctx is not None and not ctx.span_id:
            # First span of a fresh trace: it IS the originating span —
            # spans opened under this context on OTHER threads will
            # parent to it.
            ctx.span_id = span_id
        stack.append((name, span_id))
        scope = _named_scope(name) if (self._xplane and xplane) else None
        if scope is not None:
            scope.__enter__()
        t0 = time.monotonic()
        try:
            yield attrs
        finally:
            dur = time.monotonic() - t0
            if scope is not None:
                scope.__exit__(None, None, None)
            stack.pop()
            self._append(Span(
                name=name,
                start_s=t0 - self._t0,
                dur_s=dur,
                depth=len(stack),
                parent=parent,
                thread=threading.current_thread().name,
                span_id=span_id,
                attrs=attrs,
                trace_id=ctx.trace_id if ctx is not None else None,
                parent_id=parent_id,
                links=tuple(links),
            ))

    def wrap(self, name: str | None = None) -> Callable:
        """Decorator form: ``@tracker.wrap("train/probe")``."""

        def deco(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def inner(*args, **kw):
                with self.span(span_name):
                    return fn(*args, **kw)

            return inner

        return deco

    # --- reading ---------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Completed spans, oldest first, as plain dicts."""
        with self._lock:
            ordered = self._ring[self._next_slot:] + self._ring[:self._next_slot]
        out = [s.to_dict() for s in ordered]
        if self.identity:
            for d in out:
                d.update(self.identity)
        return out

    def durations(self, name: str) -> list[float]:
        with self._lock:
            ordered = self._ring[self._next_slot:] + self._ring[:self._next_slot]
        return [s.dur_s for s in ordered if s.name == name]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._next_slot = 0
            self.evicted = 0


def _named_scope(name: str):
    """jax.named_scope bridge; None when jax is unavailable (the obs layer
    itself is pure host code and must not require a device runtime)."""
    try:
        import jax

        return jax.named_scope(name)
    except Exception:
        return None


# --- process-global tracker ---------------------------------------------
# One default tracker so integration points (trainer, hostfeed, serving)
# share a timeline without threading a handle through every constructor.
# Tests install their own via set_tracker().

_GLOBAL = SpanTracker()


def get_tracker() -> SpanTracker:
    return _GLOBAL


def set_tracker(tracker: SpanTracker) -> SpanTracker:
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, tracker
    return prev


def span(name: str, **attrs: Any):
    """Module-level convenience: a span on the current global tracker."""
    return _GLOBAL.span(name, **attrs)
