"""Host-side spans: the timing half of the telemetry spine (ISSUE 2).

A span is one timed region of host code (sampling, dispatch, eval, a
serving batch). Spans nest per-thread, carry attributes, and land in a
fixed-capacity ring buffer — long soaks never grow host memory, and the
flight recorder (obs/recorder.py) can always dump the most recent window.

Two deliberate bridges to the device side:

* ``jax.named_scope`` — entering a span also enters a named scope of the
  same name, so any ops *traced* inside it attribute to the same stage
  name in an XPlane profile. Host spans and device trace rows then share
  one vocabulary ("train/step", "serve/execute") instead of two.
* Overhead discipline — enter/exit is two ``time.monotonic()`` calls, a
  deque append, and a thread-local push/pop. Measured by
  ``tools/obs_report.py --overhead`` against the run's own p50 step time
  (acceptance: < 2% of step time on the headline config).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import itertools
import threading
import time
from typing import Any, Callable, Iterator


@dataclasses.dataclass
class Span:
    """One completed span. ``start_s`` is on the tracker's monotonic
    timeline (comparable across spans of one process, not wall time)."""

    name: str
    start_s: float
    dur_s: float
    depth: int                 # 0 = top-level in its thread
    parent: str | None         # enclosing span's name, if any
    thread: str
    span_id: int
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "dur_s": round(self.dur_s, 6),
            "depth": self.depth,
            "parent": self.parent,
            "thread": self.thread,
            "span_id": self.span_id,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class SpanTracker:
    """Thread-safe ring buffer of completed spans + per-thread nesting.

    The ring holds the most recent ``capacity`` spans; older ones are
    evicted silently (``evicted`` counts them so a report can say "window
    of the last N", not "everything").
    """

    def __init__(self, capacity: int = 4096, xplane_bridge: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        # RLock: the flight recorder's SIGTERM dump snapshots this tracker
        # from a signal handler that may interrupt the same thread inside
        # _append — a plain lock would deadlock the dump.
        self._lock = threading.RLock()
        self._ring: list[Span] = []
        self._next_slot = 0            # round-robin slot once full
        self.evicted = 0
        self._ids = itertools.count()
        self._tls = threading.local()
        self._t0 = time.monotonic()
        self._xplane = xplane_bridge

    # --- recording -------------------------------------------------------

    def _stack(self) -> list[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, span: Span) -> None:
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(span)
            else:
                self._ring[self._next_slot] = span
                self._next_slot = (self._next_slot + 1) % self.capacity
                self.evicted += 1

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[dict]:
        """Time a region. Yields the attrs dict so the body can attach
        results (e.g. ``s["rows"] = len(batch)``) before the span closes."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(name)
        scope = _named_scope(name) if self._xplane else None
        if scope is not None:
            scope.__enter__()
        t0 = time.monotonic()
        try:
            yield attrs
        finally:
            dur = time.monotonic() - t0
            if scope is not None:
                scope.__exit__(None, None, None)
            stack.pop()
            self._append(Span(
                name=name,
                start_s=t0 - self._t0,
                dur_s=dur,
                depth=len(stack),
                parent=parent,
                thread=threading.current_thread().name,
                span_id=next(self._ids),
                attrs=attrs,
            ))

    def wrap(self, name: str | None = None) -> Callable:
        """Decorator form: ``@tracker.wrap("train/probe")``."""

        def deco(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def inner(*args, **kw):
                with self.span(span_name):
                    return fn(*args, **kw)

            return inner

        return deco

    # --- reading ---------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Completed spans, oldest first, as plain dicts."""
        with self._lock:
            ordered = self._ring[self._next_slot:] + self._ring[:self._next_slot]
        return [s.to_dict() for s in ordered]

    def durations(self, name: str) -> list[float]:
        with self._lock:
            ordered = self._ring[self._next_slot:] + self._ring[:self._next_slot]
        return [s.dur_s for s in ordered if s.name == name]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._next_slot = 0
            self.evicted = 0


def _named_scope(name: str):
    """jax.named_scope bridge; None when jax is unavailable (the obs layer
    itself is pure host code and must not require a device runtime)."""
    try:
        import jax

        return jax.named_scope(name)
    except Exception:
        return None


# --- process-global tracker ---------------------------------------------
# One default tracker so integration points (trainer, hostfeed, serving)
# share a timeline without threading a handle through every constructor.
# Tests install their own via set_tracker().

_GLOBAL = SpanTracker()


def get_tracker() -> SpanTracker:
    return _GLOBAL


def set_tracker(tracker: SpanTracker) -> SpanTracker:
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, tracker
    return prev


def span(name: str, **attrs: Any):
    """Module-level convenience: a span on the current global tracker."""
    return _GLOBAL.span(name, **attrs)
