"""Shared counter/gauge registry + Prometheus text exposition.

Before this layer, every execution path kept its own counters
(``serving/stats.py`` fields, trainer locals); the registry gives them one
namespace so a scrape — or the run report — sees train and serving through
the same model:

* ``counter(name)`` — monotonically increasing totals.
* ``gauge(name)`` — last-written values.
* ``gauge_fn(name, fn)`` — computed at render time (e.g. queue depth read
  from the live batcher instead of mirrored on every mutation).
* ``labeled_gauge(name)`` — a gauge FAMILY keyed by label set
  (``fleet_replica_qps{replica="r01"}``), the fleet rollup's per-replica
  exposition shape (ISSUE 17): one scrape shows every replica without
  minting one metric name per replica id.
* ``histogram(name)`` — bucketed distributions (serving latency), rendered
  as the standard ``_bucket``/``_sum``/``_count`` family. Each bucket
  remembers the most recent **exemplar trace_id** observed into it
  (ISSUE 9), emitted in OpenMetrics exemplar syntax — a scrape of the
  p99 bucket hands the operator a concrete traced request to pull the
  waterfall for, closing the metric -> trace loop.

``to_prometheus()`` renders the standard text exposition format
(``# TYPE``/``# HELP`` + one sample per line) so the output can be served
from any HTTP handler or dropped into a textfile collector; nothing here
imports an HTTP server or a client library. Exemplars use the
OpenMetrics spelling (`` # {trace_id="..."} value`` after a bucket
sample) — scrapers speaking only the legacy format should be pointed at
an OpenMetrics-capable endpoint when histograms are bound, or the
exemplars stripped (they appear ONLY on histogram ``_bucket`` lines).
"""

from __future__ import annotations

import re
import threading
from typing import Callable

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with per-bucket exemplars.

    ``observe(v, exemplar=trace_id)`` increments the first bucket whose
    upper bound holds ``v`` (cumulative rendering happens at exposition
    time) and stamps that bucket's exemplar. Buckets are upper bounds in
    the metric's own unit; +Inf is implicit.
    """

    DEFAULT_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                  1000.0, 2500.0)

    __slots__ = ("bounds", "_counts", "_sum", "_total", "_exemplars", "_lock")

    def __init__(self, bounds: tuple[float, ...] = DEFAULT_MS):
        self.bounds = tuple(sorted(bounds))
        self._counts = [0] * (len(self.bounds) + 1)   # last = +Inf
        self._exemplars: list[tuple[str, float] | None] = (
            [None] * (len(self.bounds) + 1)
        )
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: str | None = None) -> None:
        i = 0
        for i, b in enumerate(self.bounds):  # noqa: B007 — i used after
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._total += 1
            if exemplar is not None:
                self._exemplars[i] = (exemplar, float(v))

    @property
    def value(self) -> float:
        """Registry-snapshot scalar: the observation count (histograms
        render fully only in the Prometheus exposition)."""
        with self._lock:
            return float(self._total)

    def state(self) -> tuple[list[int], float, int, list]:
        with self._lock:
            return (
                list(self._counts), self._sum, self._total,
                list(self._exemplars),
            )


def _escape_label(v: str) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote, and newline are the three characters with meaning
    inside a quoted label value."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


class GaugeFamily:
    """Labeled gauge family (ISSUE 17): one child value per unique
    label set, rendered as ``name{k="v",...} value`` — the shape the
    fleet rollup needs (``fleet_replica_qps{replica="r01"}``), where a
    plain Gauge would force one metric NAME per replica and break every
    dashboard aggregation. ``set`` is last-write-wins per label set
    (gauge semantics); ``remove`` retires a series (a drained replica
    must stop being scraped, not freeze at its last value)."""

    __slots__ = ("_children", "_lock")

    def __init__(self):
        self._children: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(labels: dict) -> tuple[tuple[str, str], ...]:
        if not labels:
            raise ValueError("a labeled gauge needs at least one label")
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        for k, _ in key:
            _check_name(k)
        return key

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def remove(self, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children.pop(key, None)

    def state(self) -> dict[tuple[tuple[str, str], ...], float]:
        with self._lock:
            return dict(self._children)

    @property
    def value(self) -> float:
        """Registry-snapshot scalar: the live series count (the full
        family renders only in the Prometheus exposition)."""
        with self._lock:
            return float(len(self._children))


class CounterRegistry:
    """Named counters/gauges with idempotent registration: asking for the
    same name twice returns the same instrument, so independent modules
    (stats emitters, the trainer, tools) can share one registry without
    coordinating construction order."""

    def __init__(self, prefix: str = "induction"):
        self.prefix = _check_name(prefix)
        self._lock = threading.Lock()
        self._instruments: dict[str, Counter | Gauge] = {}
        self._fns: dict[str, Callable[[], float]] = {}
        self._help: dict[str, str] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, help, Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, help, Gauge)

    def _get(self, name: str, help: str, cls):
        _check_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                if name in self._fns:
                    raise ValueError(f"{name!r} already registered as gauge_fn")
                inst = self._instruments[name] = cls()
                self._help[name] = help
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"{name!r} already registered as {type(inst).__name__}"
                )
            return inst

    def histogram(
        self, name: str, bounds: tuple[float, ...] = Histogram.DEFAULT_MS,
        help: str = "",
    ) -> Histogram:
        """Bucketed distribution; idempotent like counter/gauge (the
        FIRST registration's bounds win — re-asking returns the existing
        instrument unchanged)."""
        _check_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                if name in self._fns:
                    raise ValueError(f"{name!r} already registered as gauge_fn")
                inst = self._instruments[name] = Histogram(bounds)
                self._help[name] = help
            elif not isinstance(inst, Histogram):
                raise ValueError(
                    f"{name!r} already registered as {type(inst).__name__}"
                )
            return inst

    def labeled_gauge(self, name: str, help: str = "") -> GaugeFamily:
        """Labeled gauge family; idempotent like counter/gauge —
        re-asking returns the existing family, so the router's
        re-binds across restarts share one series table."""
        return self._get(name, help, GaugeFamily)

    def gauge_fn(self, name: str, fn: Callable[[], float], help: str = "") -> None:
        """Register a pull-style gauge evaluated at render time.
        Re-registration replaces the callback (latest wins) — a fresh
        ServingStats binding over a closed one must not raise."""
        _check_name(name)
        with self._lock:
            if name in self._instruments:
                raise ValueError(f"{name!r} already registered as instrument")
            self._fns[name] = fn
            self._help[name] = help

    def unregister(
        self, name: str, fn: Callable[[], float] | None = None,
        inst=None,
    ) -> None:
        """Drop an instrument or gauge_fn. Idempotent. Lets a closing
        component (e.g. ServingStats.unbind_registry) release the
        callbacks that would otherwise pin it in the global registry and
        keep rendering stale values after its engine is gone. With ``fn``
        (or ``inst`` for push instruments like histograms), removal is
        identity-checked: a closing engine must not delete the live
        instrument a successor engine re-registered under the same name."""
        with self._lock:
            if fn is not None:
                if self._fns.get(name) is fn:
                    self._fns.pop(name)
                    self._help.pop(name, None)
                return
            if inst is not None:
                if self._instruments.get(name) is inst:
                    self._instruments.pop(name)
                    self._help.pop(name, None)
                return
            self._instruments.pop(name, None)
            self._fns.pop(name, None)
            self._help.pop(name, None)

    # --- reading ---------------------------------------------------------

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            insts = dict(self._instruments)
            fns = dict(self._fns)
        out = {name: inst.value for name, inst in insts.items()}
        for name, fn in fns.items():
            try:
                out[name] = float(fn())
            except Exception:
                out[name] = float("nan")  # a dead callback must not kill a scrape
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (one metric family per instrument)."""
        with self._lock:
            insts = dict(self._instruments)
            fns = dict(self._fns)
            helps = dict(self._help)
        lines = []
        values = self.snapshot()
        for name in sorted(values):
            full = f"{self.prefix}_{name}"
            inst = insts.get(name)
            if isinstance(inst, Histogram):
                if helps.get(name):
                    lines.append(f"# HELP {full} {helps[name]}")
                lines.append(f"# TYPE {full} histogram")
                counts, total_sum, total, exemplars = inst.state()
                cum = 0
                for i, bound in enumerate((*inst.bounds, float("inf"))):
                    cum += counts[i]
                    le = "+Inf" if bound == float("inf") else f"{bound:g}"
                    line = f'{full}_bucket{{le="{le}"}} {cum}'
                    ex = exemplars[i]
                    if ex is not None:
                        # OpenMetrics exemplar: the last traced request
                        # that landed in this bucket — scrape-to-waterfall.
                        line += f' # {{trace_id="{ex[0]}"}} {ex[1]:g}'
                    lines.append(line)
                lines.append(f"{full}_sum {total_sum:g}")
                lines.append(f"{full}_count {total}")
                continue
            if isinstance(inst, GaugeFamily):
                if helps.get(name):
                    lines.append(f"# HELP {full} {helps[name]}")
                lines.append(f"# TYPE {full} gauge")
                for key, v in sorted(inst.state().items()):
                    lbl = ",".join(
                        f'{k}="{_escape_label(val)}"' for k, val in key
                    )
                    lines.append(f"{full}{{{lbl}}} {v:g}")
                continue
            mtype = "counter" if isinstance(inst, Counter) else "gauge"
            if name in fns:
                mtype = "gauge"
            if helps.get(name):
                lines.append(f"# HELP {full} {helps[name]}")
            lines.append(f"# TYPE {full} {mtype}")
            lines.append(f"{full} {values[name]:g}")
        return "\n".join(lines) + "\n"


# Process-global registry: integration points (ServingStats, the trainer)
# default to it, mirroring the global span tracker in obs/spans.py.
_GLOBAL = CounterRegistry()


def get_registry() -> CounterRegistry:
    return _GLOBAL


def set_registry(reg: CounterRegistry) -> CounterRegistry:
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, reg
    return prev
