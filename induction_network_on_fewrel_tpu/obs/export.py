"""Shared counter/gauge registry + Prometheus text exposition.

Before this layer, every execution path kept its own counters
(``serving/stats.py`` fields, trainer locals); the registry gives them one
namespace so a scrape — or the run report — sees train and serving through
the same model:

* ``counter(name)`` — monotonically increasing totals.
* ``gauge(name)`` — last-written values.
* ``gauge_fn(name, fn)`` — computed at render time (e.g. queue depth read
  from the live batcher instead of mirrored on every mutation).

``to_prometheus()`` renders the standard text exposition format
(``# TYPE``/``# HELP`` + one sample per line) so the output can be served
from any HTTP handler or dropped into a textfile collector; nothing here
imports an HTTP server or a client library.
"""

from __future__ import annotations

import re
import threading
from typing import Callable

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class CounterRegistry:
    """Named counters/gauges with idempotent registration: asking for the
    same name twice returns the same instrument, so independent modules
    (stats emitters, the trainer, tools) can share one registry without
    coordinating construction order."""

    def __init__(self, prefix: str = "induction"):
        self.prefix = _check_name(prefix)
        self._lock = threading.Lock()
        self._instruments: dict[str, Counter | Gauge] = {}
        self._fns: dict[str, Callable[[], float]] = {}
        self._help: dict[str, str] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, help, Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, help, Gauge)

    def _get(self, name: str, help: str, cls):
        _check_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                if name in self._fns:
                    raise ValueError(f"{name!r} already registered as gauge_fn")
                inst = self._instruments[name] = cls()
                self._help[name] = help
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"{name!r} already registered as {type(inst).__name__}"
                )
            return inst

    def gauge_fn(self, name: str, fn: Callable[[], float], help: str = "") -> None:
        """Register a pull-style gauge evaluated at render time.
        Re-registration replaces the callback (latest wins) — a fresh
        ServingStats binding over a closed one must not raise."""
        _check_name(name)
        with self._lock:
            if name in self._instruments:
                raise ValueError(f"{name!r} already registered as instrument")
            self._fns[name] = fn
            self._help[name] = help

    def unregister(self, name: str, fn: Callable[[], float] | None = None) -> None:
        """Drop an instrument or gauge_fn. Idempotent. Lets a closing
        component (e.g. ServingStats.unbind_registry) release the
        callbacks that would otherwise pin it in the global registry and
        keep rendering stale values after its engine is gone. With ``fn``,
        the gauge_fn is removed only if it is STILL the registered one —
        a closing engine must not delete the live gauges a successor
        engine re-registered under the same names (latest-wins)."""
        with self._lock:
            if fn is not None:
                if self._fns.get(name) is fn:
                    self._fns.pop(name)
                    self._help.pop(name, None)
                return
            self._instruments.pop(name, None)
            self._fns.pop(name, None)
            self._help.pop(name, None)

    # --- reading ---------------------------------------------------------

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            insts = dict(self._instruments)
            fns = dict(self._fns)
        out = {name: inst.value for name, inst in insts.items()}
        for name, fn in fns.items():
            try:
                out[name] = float(fn())
            except Exception:
                out[name] = float("nan")  # a dead callback must not kill a scrape
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (one metric family per instrument)."""
        with self._lock:
            insts = dict(self._instruments)
            fns = dict(self._fns)
            helps = dict(self._help)
        lines = []
        values = self.snapshot()
        for name in sorted(values):
            full = f"{self.prefix}_{name}"
            mtype = (
                "counter"
                if isinstance(insts.get(name), Counter) else "gauge"
            )
            if name in fns:
                mtype = "gauge"
            if helps.get(name):
                lines.append(f"# HELP {full} {helps[name]}")
            lines.append(f"# TYPE {full} {mtype}")
            lines.append(f"{full} {values[name]:g}")
        return "\n".join(lines) + "\n"


# Process-global registry: integration points (ServingStats, the trainer)
# default to it, mirroring the global span tracker in obs/spans.py.
_GLOBAL = CounterRegistry()


def get_registry() -> CounterRegistry:
    return _GLOBAL


def set_registry(reg: CounterRegistry) -> CounterRegistry:
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, reg
    return prev
