"""Online prediction-drift detection over serving verdicts (ISSUE 10).

The serving stack already tells an operator when it is slow (SLO burn
rates) or wedged (queue-stall watchdog); this module tells them when it
is *wrong* — or about to be. FewRel 2.0 (Gao et al. 2019, PAPERS.md)
shows exactly where the Geng et al. 2019 induction model degrades
silently: traffic drifting out of the training domain (wiki -> pubmed)
and open-world none-of-the-above queries. Neither failure mode raises an
exception or moves a latency percentile; both move the *prediction
distribution* first. So that is what this detector watches, per tenant:

* **NOTA rate** — fraction of verdicts resolved ``no_relation``. The
  single most sensitive out-of-domain signal: queries that match none of
  the tenant's resident class vectors land here (or stop landing here,
  when a miscalibrated threshold starts swallowing everything).
* **Top-1 margin** — best class score minus runner-up. Shrinking margins
  mean the class vectors no longer separate the traffic.
* **Score entropy** — softmax entropy of the class scores. Rising
  entropy is the same collapse seen from the other side (and catches a
  *uniformly confident-wrong* model that keeps its margins).

Mechanics (deliberately parallel to ``obs/health.SLOEngine``):

* ``observe(tenant, nota=..., margin=..., entropy=...)`` per verdict —
  the engine calls it on the emit path, one deque append steady-state.
* A **calibration baseline** per tenant: mean/std of each feature over
  the first ``baseline_n`` verdicts after (re-)arming, or injected
  explicitly via ``set_baseline`` from a publish-time calibration
  artifact (the ``tools/scenarios.py`` NOTA sweep records exactly these
  stats at the chosen operating point).
* A rolling **detection window** (count-based, bounded deque) compared
  against the baseline: per feature, the band is
  ``max(band_sigma * base_std / sqrt(window), floor)`` — the standard
  error of the window mean under the baseline distribution, floored so
  a zero-variance baseline (NOTA rate 0.0 is common) still gets a
  meaningful band. Window mean outside the band -> WARNING; outside
  ``crit_factor`` bands -> CRITICAL.
* **Once-latched** per (tenant, feature, severity): a sustained shift is
  one incident, not one event per evaluation; returning inside the band
  re-arms the latch. A CRITICAL auto-captures diagnostics through the
  shared ``DiagnosticsCapture`` (flight dump + host-span snapshot),
  exactly once per latch — the evidence for "the model went wrong at
  14:03" is on disk before anyone asks.
* **Baseline re-arm on publish**: a hot-swap (``snapshot_swap``)
  legitimately moves the prediction distribution — new weights, new
  class vectors. The serving engine calls ``rearm()`` after every
  publish, which drops baselines + windows + latches and re-captures
  from the first post-publish traffic, so a publish never reads as
  drift and drift is never masked by a stale pre-publish baseline.
* The clock is injectable (``now=``) like every detector in obs/: the
  evaluation throttle (``eval_interval_s``) compresses in tests and
  drills to whatever wall-time they actually have.

Drill: ``tools/loadgen.py --drift_drill`` (RUNBOOK §15) calibrates an
open-set NOTA floor from live verdicts, baselines in-domain traffic,
then injects an out-of-vocabulary traffic shift that must trip a
once-latched CRITICAL with captures on disk — and proves a publish
re-arms cleanly.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable

from induction_network_on_fewrel_tpu.obs.health import (
    CRITICAL,
    WARNING,
    HealthEvent,
)

FEATURES = ("nota_rate", "margin", "entropy")


def quality_features(scores):
    """(top-1 margin, softmax entropy) of class-score rows — THE quality
    formulas of the stack, shared by the serving verdict path
    (engine._verdict, per row) and the scenarios harness
    (tools/scenarios.py, vectorized), so the offline calibration baseline
    and the online drift features can never disagree.

    ``scores``: numpy [..., n] class scores (the NOTA logit excluded —
    it is a learned threshold, not a class; folding it in would alias
    threshold recalibration with distribution shift). Returns
    (margin[...], entropy[...]) float64 arrays; margin is 0 for n < 2.
    """
    import numpy as np

    s = np.asarray(scores, dtype=np.float64)
    n = s.shape[-1]
    if n >= 2:
        top2 = np.partition(s, -2, axis=-1)[..., -2:]
        margin = top2[..., 1] - top2[..., 0]
    else:
        margin = np.zeros(s.shape[:-1])
    z = s - s.max(axis=-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=-1, keepdims=True)
    entropy = -(p * np.log(np.maximum(p, 1e-12))).sum(axis=-1)
    return margin, entropy


def _mean_std(xs) -> tuple[float, float]:
    n = len(xs)
    if n == 0:
        return 0.0, 0.0
    m = sum(xs) / n
    var = sum((x - m) ** 2 for x in xs) / max(n - 1, 1)
    return m, math.sqrt(max(var, 0.0))


class DriftDetector:
    """Per-tenant prediction-drift detector over serving verdicts."""

    def __init__(
        self,
        window: int = 128,
        baseline_n: int = 64,
        min_count: int | None = None,
        band_sigma: float = 4.0,
        crit_factor: float = 2.0,
        eval_interval_s: float = 1.0,
        nota_rate_floor: float = 0.05,
        rel_floor: float = 0.1,
        parity_floor: float = 0.99,
        parity_margin_band: float = 0.25,
        parity_window: int = 8,
        logger=None,
        recorder=None,
        capture=None,
        on_event: Callable[[HealthEvent], None] | None = None,
    ):
        """``window``: detection-window verdict count (bounded memory per
        tenant). ``baseline_n``: verdicts that form the calibration
        baseline after (re-)arming. ``min_count``: don't judge a window
        thinner than this — None (default) resolves to ``min(32,
        window)`` so a small window is judged when full; an explicit
        value larger than the window is refused (the deque is capped at
        ``window``, so such a detector could NEVER judge — a silent
        no-op an operator would mistake for armed coverage).
        ``band_sigma``: band width in standard errors of the window
        mean; ``crit_factor``: CRITICAL at this many bands.
        ``nota_rate_floor``: absolute band floor for the NOTA rate (a
        clean baseline has rate 0.0 with std 0.0); margin/entropy floor
        at ``rel_floor`` of their baseline scale instead (score units are
        model-dependent).

        Quantization parity bands (ISSUE 18): ``parity_floor`` is the
        absolute verdict-agreement floor the parity police holds
        quantized tenants to (WARNING below it, CRITICAL past
        ``crit_factor`` of the shortfall band ``1 - parity_floor``);
        ``parity_margin_band`` bounds the mean |margin drift| vs f32 the
        same way; ``parity_window`` is how many probes the rolling
        parity means average over. Unlike the drift features these need
        NO calibration baseline — f32 agreement is an absolute bar, not
        a distribution."""
        if not 0.0 < parity_floor <= 1.0:
            raise ValueError(
                f"parity_floor must be in (0, 1], got {parity_floor}"
            )
        if parity_window < 1:
            raise ValueError(
                f"parity_window must be >= 1, got {parity_window}"
            )
        if min_count is None:
            min_count = min(32, window)
        if baseline_n < 2 or window < 2 or min_count < 2:
            raise ValueError("window/baseline_n/min_count must be >= 2")
        if min_count > window:
            raise ValueError(
                f"min_count ({min_count}) exceeds window ({window}): the "
                f"detection window is capped at `window` entries, so this "
                f"detector would never judge anything"
            )
        self.window = window
        self.baseline_n = baseline_n
        self.min_count = min_count
        self.band_sigma = band_sigma
        self.crit_factor = crit_factor
        self.eval_interval_s = eval_interval_s
        self.nota_rate_floor = nota_rate_floor
        self.rel_floor = rel_floor
        self.parity_floor = parity_floor
        self.parity_margin_band = parity_margin_band
        self.parity_window = parity_window
        self.logger = logger
        self.recorder = recorder
        self.capture = capture
        self.on_event = on_event
        self._lock = threading.RLock()
        # tenant -> {feature: (mean, std)} once calibrated.
        self._baseline: dict[str, dict[str, tuple[float, float]]] = {}
        # tenant -> accumulating calibration buffer (pre-baseline).
        self._base_buf: dict[str, list[tuple[float, float, float]]] = {}
        # tenant -> rolling detection window of (nota, margin, entropy).
        self._win: dict[str, deque] = {}
        self._seen: dict[str, int] = {}       # verdicts observed per tenant
        self._last_eval: dict[str, float] = {}
        # tenant -> rolling window of parity-probe outcomes
        # (agreement, margin_drift, rows) — ISSUE 18 parity police.
        self._parity_win: dict[str, deque] = {}
        self.rearms = 0
        self.events: deque[HealthEvent] = deque(maxlen=512)
        self.tripped = False
        self._latched: set[str] = set()
        self.captured: dict[str, dict] = {}   # latch key -> capture result

    # --- calibration ------------------------------------------------------

    def armed(self, tenant: str) -> bool:
        """True once the tenant has a calibration baseline."""
        with self._lock:
            return tenant in self._baseline

    def set_baseline(
        self, tenant: str, baseline: dict[str, tuple[float, float]]
    ) -> None:
        """Inject an explicit calibration baseline — ``{feature: (mean,
        std)}`` for the features in ``FEATURES`` — e.g. the operating-
        point stats a ``tools/scenarios.py`` NOTA calibration recorded at
        publish time. Replaces any traffic-derived baseline and clears
        the tenant's window/latches (the comparison basis changed)."""
        missing = [f for f in FEATURES if f not in baseline]
        if missing:
            raise ValueError(f"baseline lacks features {missing}")
        with self._lock:
            self._baseline[tenant] = {
                f: (float(baseline[f][0]), float(baseline[f][1]))
                for f in FEATURES
            }
            self._base_buf.pop(tenant, None)
            self._win[tenant] = deque(maxlen=self.window)
            self._unlatch(tenant)

    def baseline_for(self, tenant: str) -> dict | None:
        with self._lock:
            base = self._baseline.get(tenant)
            return {f: tuple(v) for f, v in base.items()} if base else None

    def rearm(self, tenant: str | None = None, reason: str = "") -> None:
        """Drop baseline + window + latches (one tenant, or all) and
        re-capture from subsequent traffic. The serving engine calls this
        after every hot-swap publish: a publish legitimately moves the
        prediction distribution, so the old baseline is void — and the
        re-capture means post-publish drift is judged against the NEW
        normal, not masked by it."""
        with self._lock:
            tenants = [tenant] if tenant is not None else list(
                set(self._baseline) | set(self._base_buf) | set(self._win)
                | set(self._parity_win)
            )
            # Quiet no-op when the target never accumulated state: the
            # engine re-arms on every control-plane change (register /
            # threshold / publish), and setup-time registrations before
            # any traffic must not spam drift_rearm events.
            had_any = any(
                t in self._baseline or t in self._base_buf or t in self._win
                or t in self._parity_win
                for t in tenants
            )
            for t in tenants:
                self._baseline.pop(t, None)
                self._base_buf.pop(t, None)
                self._win.pop(t, None)
                self._last_eval.pop(t, None)
                # Parity windows drop with the rest: a publish or a
                # residency roll changes the quantization error, so old
                # probe outcomes no longer describe the live matrix
                # (and _unlatch clears the quant_* latches by prefix).
                self._parity_win.pop(t, None)
                self._unlatch(t)
            if had_any:
                self.rearms += 1
        if had_any:
            self._send(HealthEvent(
                event="drift_rearm", severity=WARNING, step=self.rearms,
                message=(
                    f"drift baseline re-armed for "
                    f"{tenant if tenant is not None else 'all tenants'}"
                    + (f": {reason}" if reason else "")
                ),
                data={"tenants": float(len(tenants))},
            ), latch=None)

    def _unlatch(self, tenant: str) -> None:
        for key in [k for k in self._latched
                    if k.startswith(f"drift:{tenant}:")]:
            self._latched.discard(key)

    # --- observation ------------------------------------------------------

    def observe(
        self,
        tenant: str,
        nota: bool,
        margin: float,
        entropy: float,
        now: float | None = None,
    ) -> list[HealthEvent]:
        """One verdict's quality features. Steady-state cost: a deque
        append + (at most once per ``eval_interval_s``) a window-mean
        judgment. Returns newly emitted events (tests/drills)."""
        now = time.monotonic() if now is None else now
        sample = (1.0 if nota else 0.0, float(margin), float(entropy))
        pending: list[tuple[HealthEvent, str]] = []
        with self._lock:
            self._seen[tenant] = self._seen.get(tenant, 0) + 1
            if tenant not in self._baseline:
                buf = self._base_buf.setdefault(tenant, [])
                buf.append(sample)
                if len(buf) >= self.baseline_n:
                    self._baseline[tenant] = {
                        f: _mean_std([s[i] for s in buf])
                        for i, f in enumerate(FEATURES)
                    }
                    del self._base_buf[tenant]
                    self._win[tenant] = deque(maxlen=self.window)
                return []
            win = self._win[tenant]
            win.append(sample)
            if len(win) < self.min_count:
                return []
            if now - self._last_eval.get(tenant, -math.inf) \
                    < self.eval_interval_s:
                return []
            self._last_eval[tenant] = now
            pending = self._judge_locked(tenant)
        for ev, latch in pending:
            self._send(ev, latch)
        return [ev for ev, _ in pending]

    def observe_parity(
        self,
        tenant: str,
        agreement: float,
        margin_drift: float,
        rows: int = 1,
        now: float | None = None,
    ) -> list[HealthEvent]:
        """One quantization parity-probe outcome (ISSUE 18): the engine's
        sampled f32 shadow-score hands over the probe's verdict-agreement
        fraction and mean |margin drift|. Judged against the ABSOLUTE
        parity bands (no calibration baseline — f32 IS the reference) on
        every probe, and routed through the exact same latch/auto-
        capture/on_event path as feature drift, so a quantization
        regression trips the same alarms the adaptation loop (PR 13)
        listens to. Returns newly emitted events (tests/drills)."""
        pending: list[tuple[HealthEvent, str]] = []
        with self._lock:
            win = self._parity_win.setdefault(
                tenant, deque(maxlen=self.parity_window)
            )
            win.append((float(agreement), float(margin_drift), int(rows)))
            total = sum(r for _, _, r in win)
            agree = sum(a * r for a, _, r in win) / max(total, 1)
            drift = sum(d * r for _, d, r in win) / max(total, 1)
            checks = (
                # (feature, shift, band): agreement judged as shortfall
                # below 1.0 against the floor's allowance; margin drift
                # as an absolute excursion from 0.
                ("quant_agreement", 1.0 - agree, 1.0 - self.parity_floor),
                ("quant_margin_drift", drift, self.parity_margin_band),
            )
            for f, shift, band in checks:
                warn_latch = f"drift:{tenant}:{f}:warning"
                crit_latch = f"drift:{tenant}:{f}:critical"
                if shift <= band:
                    self._latched.discard(warn_latch)
                    self._latched.discard(crit_latch)
                    continue
                severity = (
                    CRITICAL if shift > self.crit_factor * band else WARNING
                )
                latch = crit_latch if severity == CRITICAL else warn_latch
                if latch in self._latched:
                    continue
                self._latched.add(latch)
                if severity == CRITICAL:
                    self._latched.add(warn_latch)
                cur = agree if f == "quant_agreement" else drift
                pending.append((HealthEvent(
                    event="prediction_drift", severity=severity,
                    step=self._seen.get(tenant, 0),
                    message=(
                        f"tenant {tenant!r} {f} {cur:.4g} breached the "
                        f"quantization parity band {band:.4g} "
                        f"({total} probed rows)"
                    ),
                    data={
                        "tenant": tenant, "feature": f,
                        "baseline": 1.0 if f == "quant_agreement" else 0.0,
                        "current": round(cur, 6),
                        "band": round(band, 6), "window": total,
                    },
                ), latch))
        for ev, latch in pending:
            self._send(ev, latch)
        return [ev for ev, _ in pending]

    def parity_state(self, tenant: str) -> dict | None:
        """{agreement, margin_drift, probes, rows} rolling parity view for
        a tenant with probe history; None otherwise."""
        with self._lock:
            win = self._parity_win.get(tenant)
            if not win:
                return None
            total = sum(r for _, _, r in win)
            return {
                "agreement": round(
                    sum(a * r for a, _, r in win) / max(total, 1), 6
                ),
                "margin_drift": round(
                    sum(d * r for _, d, r in win) / max(total, 1), 6
                ),
                "probes": len(win),
                "rows": total,
            }

    # --- judgment ---------------------------------------------------------

    def _band(self, feature: str, base_std: float, base_mean: float,
              n: int) -> float:
        se = base_std / math.sqrt(max(n, 1))
        if feature == "nota_rate":
            floor = self.nota_rate_floor
        else:
            floor = self.rel_floor * max(abs(base_mean), base_std, 1e-6)
        return max(self.band_sigma * se, floor)

    def drift_state(self, tenant: str) -> dict | None:
        """{feature: {base, cur, band, shift}} + window/latch info for a
        calibrated tenant; None otherwise. The ``kind="quality"`` drift
        record and tools/obs_report.py's quality section read this."""
        with self._lock:
            base = self._baseline.get(tenant)
            if base is None:
                return None
            win = self._win.get(tenant) or ()
            n = len(win)
            out: dict = {"window": n, "latched": sum(
                1 for k in self._latched if k.startswith(f"drift:{tenant}:")
            )}
            for i, f in enumerate(FEATURES):
                bm, bs = base[f]
                cur = (sum(s[i] for s in win) / n) if n else bm
                # Same band the judgment uses (the actual window size) —
                # the emitted record must never show a narrower band
                # than the one that decides alerts.
                band = self._band(f, bs, bm, max(n, 1))
                out[f] = {
                    "base": round(bm, 6), "cur": round(cur, 6),
                    "band": round(band, 6),
                    "shift": round(abs(cur - bm), 6),
                }
            return out

    def _judge_locked(self, tenant: str) -> list[tuple[HealthEvent, str]]:
        """Latch transitions + event construction ONLY (lock held); the
        caller emits after release — same discipline as SLOEngine: the
        capture's file writes must not stall the verdict path."""
        base = self._baseline[tenant]
        win = self._win[tenant]
        n = len(win)
        pending: list[tuple[HealthEvent, str]] = []
        for i, f in enumerate(FEATURES):
            bm, bs = base[f]
            cur = sum(s[i] for s in win) / n
            band = self._band(f, bs, bm, n)
            shift = abs(cur - bm)
            warn_latch = f"drift:{tenant}:{f}:warning"
            crit_latch = f"drift:{tenant}:{f}:critical"
            if shift <= band:
                self._latched.discard(warn_latch)   # back in band re-arms
                self._latched.discard(crit_latch)
                continue
            severity = (
                CRITICAL if shift > self.crit_factor * band else WARNING
            )
            latch = crit_latch if severity == CRITICAL else warn_latch
            # Latches re-arm ONLY fully inside the band (the branch
            # above) — a dip from critical to merely-warning territory
            # keeps the critical latch held, or shift noise around the
            # critical boundary would fire one capture per crossing
            # (same discipline as SLOEngine._judge).
            if latch in self._latched:
                continue
            self._latched.add(latch)
            if severity == CRITICAL:
                self._latched.add(warn_latch)  # critical covers warning
            pending.append((HealthEvent(
                event="prediction_drift", severity=severity,
                step=self._seen.get(tenant, 0),
                message=(
                    f"tenant {tenant!r} {f} drifted {shift:.4g} from "
                    f"baseline {bm:.4g} (band {band:.4g}, window {n})"
                ),
                data={
                    "tenant": tenant, "feature": f,
                    "baseline": round(bm, 6), "current": round(cur, 6),
                    "band": round(band, 6), "window": n,
                },
            ), latch))
        return pending

    # --- emission ---------------------------------------------------------

    def _send(self, ev: HealthEvent, latch: str | None) -> None:
        self.events.append(ev)
        if ev.severity == CRITICAL:
            self.tripped = True
        if self.recorder is not None:
            self.recorder.record_event(ev.to_dict())
        if self.logger is not None:
            self.logger.log(
                ev.step, kind="health", event=ev.event,
                severity=ev.severity, message=ev.message, **ev.data,
            )
        if ev.severity == CRITICAL and latch is not None:
            # Auto-capture once per latch: flight dump + host-span
            # snapshot (+ profiler where the image allows) on disk at
            # trip time — the same evidence discipline as SLO burns.
            if self.capture is not None:
                self.captured[latch] = self.capture.capture(
                    reason=f"drift: {ev.message}"
                )
            elif self.recorder is not None:
                self.recorder.dump(reason=f"drift: {ev.message}")
        if self.on_event is not None:
            self.on_event(ev)

    def emit(self, logger, step: int) -> None:
        """One ``kind="quality"`` drift-state record per calibrated
        tenant: baseline vs current vs band per feature, flattened to
        scalars (schema contract). The serving engine calls this with
        its periodic stats emit."""
        with self._lock:
            tenants = sorted(self._baseline)
            parity_tenants = sorted(self._parity_win)
        for tenant in tenants:
            st = self.drift_state(tenant)
            if st is None:
                continue
            fields: dict = {
                "tenant": tenant, "probe": "drift",
                "window": float(st["window"]),
                "latched": float(st["latched"]),
            }
            for f in FEATURES:
                fields[f"{f}_base"] = st[f]["base"]
                fields[f"{f}_cur"] = st[f]["cur"]
                fields[f"{f}_band"] = st[f]["band"]
            logger.log(step, kind="quality", **fields)
        for tenant in parity_tenants:
            st = self.parity_state(tenant)
            if st is None:
                continue
            logger.log(
                step, kind="quality", tenant=tenant, probe="quant_parity",
                agreement=st["agreement"], margin_drift=st["margin_drift"],
                probes=float(st["probes"]), rows=float(st["rows"]),
                agreement_floor=self.parity_floor,
                margin_band=self.parity_margin_band,
            )
