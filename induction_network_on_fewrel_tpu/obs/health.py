"""Run-health watchdog: turns the metrics stream into structured events.

The failure modes this catches are the ones VERDICT.md flags as silent
today:

* **Non-finite loss/grads** — NaN/Inf in any numeric scalar of a train
  record (the bf16-backward risk, the MSE-sigmoid dead zone).
* **Throughput regression** — episodes/sec falling below a fraction of the
  rolling-median baseline (feed stall, thermal/preemption slowdowns).
* **Routing-entropy collapse** — the induction routing (or any model that
  logs a ``routing_entropy`` / ``*_entropy`` scalar) pinning near zero:
  every query routed identically, i.e. the class vectors collapsed.
* **Serving queue stall** — queue depth > 0 while the served counter stops
  advancing for longer than ``queue_stall_s`` (a wedged batcher worker).
* **Serving shed-load** — the per-tenant shed counter advancing between
  serve windows: some tenant is over its admission share and actively
  shedding traffic (ISSUE 7 fleet serving). Critical + once-latched, so a
  sustained overload is one incident; re-arms after a shed-free window.
  Hot-swap publishes (``event="snapshot_swap"`` serve records) surface as
  WARNING events — an operator reading the health stream sees every
  weight swap next to whatever it perturbed.
* **Feed stall / poison** — the training input pipeline (datapipe/) starving
  its consumer: stall ticks (``kind="data"``) whose produced counter stops
  advancing for longer than ``queue_stall_s`` while the trainer waits, a
  dead producer thread, or a poisoned batch — the feed-side generalization
  of the serving queue-stall detector.

This module also hosts the per-tenant **SLO burn-rate engine** (ISSUE 9):
``SLOEngine`` turns per-request serving outcomes into multi-window
error-budget burn rates (fast 5m-equivalent / slow 1h-equivalent,
injectable clock like the watchdog above) and — on a fast-window CRITICAL
— auto-captures diagnostics through ``DiagnosticsCapture`` (flight-
recorder dump + a ``jax.profiler`` trace when the runtime cooperates,
host-span snapshot as the CPU-honest guaranteed artifact), so the
evidence for a tail regression is on disk before anyone asks.

Wiring: the watchdog is installed as a ``MetricsLogger`` hook, so every
record every execution path emits (train/val/serve) flows through
``observe_record`` with no extra calls at the emit sites. Events are
appended to the flight recorder, logged as ``kind="health"`` records in
metrics.jsonl, and — for critical events — trip the watchdog, which dumps
the flight recorder (obs/recorder.py) so the last-N window of context
survives the incident.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Any, Callable


CRITICAL = "critical"
WARNING = "warning"


@dataclasses.dataclass
class HealthEvent:
    event: str                 # "non_finite" | "throughput_regression" | ...
    severity: str              # "critical" | "warning"
    step: int
    message: str
    data: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "event": self.event,
            "severity": self.severity,
            "step": self.step,
            "message": self.message,
            **{k: v for k, v in self.data.items()},
        }


class HealthWatchdog:
    def __init__(
        self,
        logger=None,
        recorder=None,
        throughput_drop: float = 0.5,
        throughput_window: int = 8,
        throughput_warmup: int = 3,
        entropy_floor: float = 0.05,
        queue_stall_s: float = 5.0,
        on_event: Callable[[HealthEvent], None] | None = None,
        capture: "DiagnosticsCapture | None" = None,
    ):
        """``throughput_drop``: trip when eps/s < drop * rolling median.
        ``throughput_warmup``: train records to observe before the baseline
        arms (the first windows include compile time and are not a
        baseline). ``logger``/``recorder`` are attached lazily so the
        watchdog can be constructed before either exists. ``capture``: a
        DiagnosticsCapture; when set, criticals capture through it (which
        includes the flight dump) instead of a bare recorder dump — the
        ISSUE 12 fault criticals (ckpt_corrupt / breaker_open /
        publish_rollback) get the same evidence discipline as SLO burns
        and drift."""
        self.logger = logger
        self.recorder = recorder
        self.capture = capture
        self.throughput_drop = throughput_drop
        self.throughput_warmup = throughput_warmup
        self.entropy_floor = entropy_floor
        self.queue_stall_s = queue_stall_s
        self.on_event = on_event
        # Bounded (the module contract says everything here is): a
        # condition that persists for a whole soak must not grow host
        # memory one event per window.
        self.events: deque[HealthEvent] = deque(maxlen=512)
        self.tripped = False
        self._lock = threading.RLock()
        self._eps = deque(maxlen=throughput_window)
        self._in_emit = False
        # Once-semantics latches: a PERSISTENT condition (loss stuck at
        # NaN, entropy pinned at zero) emits one event when it begins and
        # re-arms only after a clean observation — not one critical event
        # (and one flight-recorder dump) per record for the rest of the
        # run. Keys: "non_finite:<kind>", "routing_collapse:<metric>",
        # "throughput".
        self._latched: set[str] = set()
        # Serving-stall state: (served counter, first time it was seen
        # unchanged with a non-empty queue).
        self._last_served: int | None = None
        self._stall_since: float | None = None
        self._stall_reported = False
        # Shed-load state: last aggregate shed counter seen.
        self._last_shed: int | None = None
        # Feed-stall state (training input pipeline): produced counter and
        # first time it was seen unchanged while the consumer waited.
        self._last_fed: int | None = None
        self._feed_stall_since: float | None = None
        self._feed_stall_reported = False
        self._poisoned_seen = 0

    # --- event plumbing --------------------------------------------------

    def _emit(self, ev: HealthEvent) -> None:
        self.events.append(ev)
        if ev.severity == CRITICAL:
            self.tripped = True
        if self.recorder is not None:
            self.recorder.record_event(ev.to_dict())
        if self.logger is not None:
            # Guard against self-observation: this log() call re-enters
            # observe_record through the logger hook.
            self._in_emit = True
            try:
                self.logger.log(
                    ev.step, kind="health", event=ev.event,
                    severity=ev.severity, message=ev.message, **ev.data,
                )
            finally:
                self._in_emit = False
        if ev.severity == CRITICAL:
            # DiagnosticsCapture (when wired) already dumps the recorder
            # as its first artifact — capturing AND dumping would write
            # the flight window twice per incident.
            if self.capture is not None:
                self.capture.capture(
                    reason=f"watchdog: {ev.event} ({ev.message})"
                )
            elif self.recorder is not None:
                self.recorder.dump(
                    reason=f"watchdog: {ev.event} ({ev.message})"
                )
        if self.on_event is not None:
            self.on_event(ev)

    # --- observations ----------------------------------------------------

    def observe_record(self, rec: dict) -> None:
        """MetricsLogger hook: one call per emitted record, any kind."""
        with self._lock:
            if self._in_emit:
                return
            kind = rec.get("kind")
            if kind == "health":
                # Grad-probe records are measurements, not watchdog output:
                # a NaN grad norm must still trip the non-finite check.
                if rec.get("event") == "grad_probe":
                    self._check_finite(int(rec.get("step", 0)), rec)
                return
            step = int(rec.get("step", 0))
            if kind == "fault":
                # Fault-domain stream (ISSUE 12): containment actions
                # become once-latched criticals; injections are context.
                self._check_fault(step, rec)
                return
            if kind in ("train", "val", "eval", "test", "serve",
                        "quality", "scenario", "perf", "compile",
                        "adapt"):
                # quality/scenario carry model-score statistics — a NaN
                # margin/entropy/accuracy means NaN logits upstream, the
                # exact silent failure the non-finite check exists for.
                # perf/compile carry timing decompositions (ISSUE 11) — a
                # non-finite segment or elapsed means broken clocks or a
                # division by a zero window, equally silent upstream.
                # adapt carries the loop's recover/publish timings and
                # the verification band numbers (ISSUE 14) — same class.
                self._check_finite(step, rec)
            if kind in ("train", "val", "eval"):
                self._check_entropy(step, rec)
            if kind == "train" and "episodes_per_s" in rec:
                self._check_throughput(step, float(rec["episodes_per_s"]))
            if kind == "serve":
                if rec.get("event") == "snapshot_swap":
                    # A publish that COMMITTED re-arms the rollback
                    # latch: the next failed publish is a new incident,
                    # not a suppressed repeat of the last one.
                    self._latched.discard("publish_rollback")
                    # Visibility, not a failure: every hot-swap publish
                    # lands in the health stream next to whatever it
                    # perturbed.
                    # The logger normalizes scalars to float before hooks
                    # see them; these two are counts.
                    as_count = lambda v: (  # noqa: E731
                        int(v) if isinstance(v, (int, float)) else v
                    )
                    self._emit(HealthEvent(
                        event="snapshot_swap", severity=WARNING, step=step,
                        message=(
                            f"hot-swap published params_version "
                            f"{as_count(rec.get('params_version'))} to "
                            f"{as_count(rec.get('tenants'))} tenant(s)"
                        ),
                        data={
                            k: rec[k] for k in
                            ("params_version", "tenants", "slots")
                            if k in rec
                        },
                    ))
                elif "tenant" not in rec:
                    # Aggregate serve windows only: per-tenant records
                    # restate the same counters tenant-by-tenant.
                    self.observe_queue(
                        int(rec.get("queue_depth", 0)),
                        int(rec.get("served", 0)),
                    )
                    self._check_shed(step, rec)
            if kind == "scale":
                # A completed scale decision re-arms the stuck latch:
                # the next stall is a new incident.
                if rec.get("event") in ("scale_out", "drain_in"):
                    self._latched.discard("scale_stuck")
                self._check_finite(step, rec)
            if kind == "data":
                self.observe_feed(
                    produced=int(rec.get("produced", 0)),
                    consumed=int(rec.get("consumed", 0)),
                    producer_alive=bool(rec.get("producer_alive", 1.0)),
                    poisoned=int(rec.get("poisoned", 0)),
                    step=step,
                    waiting="stalled_s" in rec,
                )

    def _check_finite(self, step: int, rec: dict) -> None:
        latch = f"non_finite:{rec.get('kind')}"
        bad = {
            k: str(v) for k, v in rec.items()
            if isinstance(v, float) and not math.isfinite(v)
        }
        if not bad:
            self._latched.discard(latch)  # clean record re-arms
            return
        if latch in self._latched:
            return
        self._latched.add(latch)
        self._emit(HealthEvent(
            event="non_finite", severity=CRITICAL, step=step,
            message=f"non-finite scalars: {sorted(bad)}",
            data={f"bad_{k}": v for k, v in bad.items()},
        ))

    def _check_entropy(self, step: int, rec: dict) -> None:
        for k, v in rec.items():
            if not k.endswith("entropy") or not isinstance(v, (int, float)):
                continue
            latch = f"routing_collapse:{k}"
            if math.isfinite(v) and v < self.entropy_floor:
                if latch in self._latched:
                    continue
                self._latched.add(latch)
                self._emit(HealthEvent(
                    event="routing_collapse", severity=CRITICAL, step=step,
                    message=f"{k}={v:.4g} below floor {self.entropy_floor}",
                    data={k: float(v)},
                ))
            else:
                self._latched.discard(latch)

    def _check_throughput(self, step: int, eps: float) -> None:
        if not math.isfinite(eps):
            return
        if len(self._eps) >= self.throughput_warmup:
            baseline = sorted(self._eps)[len(self._eps) // 2]  # rolling median
            if baseline > 0 and eps < self.throughput_drop * baseline:
                if "throughput" not in self._latched:
                    self._latched.add("throughput")
                    self._emit(HealthEvent(
                        event="throughput_regression", severity=WARNING,
                        step=step,
                        message=(
                            f"episodes_per_s {eps:.1f} < "
                            f"{self.throughput_drop:.0%} of baseline "
                            f"{baseline:.1f}"
                        ),
                        data={"episodes_per_s": eps, "baseline": baseline},
                    ))
                # A regressed window must not drag the baseline down with
                # it (a real slowdown stays an incident, not the new
                # normal) — and it must not re-arm the latch either.
                return
        self._latched.discard("throughput")  # healthy window re-arms
        self._eps.append(eps)

    def _check_shed(self, step: int, rec: dict) -> None:
        """Shed-load detection over aggregate serve windows: the shed
        counter advancing means some tenant is over its admission share
        and actively shedding. Once-latched (a sustained overload is one
        incident); a shed-free window re-arms."""
        shed = rec.get("shed")
        if not isinstance(shed, (int, float)):
            return
        shed = int(shed)
        prev, self._last_shed = self._last_shed, shed
        if prev is None:
            # First window: a nonzero total is still news.
            prev = 0
        if shed > prev:
            if "shed_load" in self._latched:
                return
            self._latched.add("shed_load")
            self._emit(HealthEvent(
                event="shed_load", severity=CRITICAL, step=step,
                message=(
                    f"shed-load active: {shed - prev} per-tenant share "
                    f"rejections since the last serve window "
                    f"(total {shed})"
                ),
                data={
                    "shed": shed,
                    "rejected": int(rec.get("rejected", 0)),
                    "queue_depth": int(rec.get("queue_depth", 0)),
                },
            ))
        else:
            self._latched.discard("shed_load")

    def _check_fault(self, step: int, rec: dict) -> None:
        """Fault-domain criticals (ISSUE 12), each once-latched with an
        explicit re-arm:

        * ``ckpt_corrupt``     — a checkpoint slot quarantined. Latched
          per SLOT (kind+step): one incident per corrupt slot, however
          many roots/retries report it; a different slot is a new
          incident by key.
        * ``breaker_open``     — a tenant's circuit breaker opened.
          Latched per tenant; the breaker's own ``to="closed"``
          transition re-arms.
        * ``publish_rollback`` — a publish transaction rolled back.
          Single latch; a later COMMITTED publish (snapshot_swap serve
          event) re-arms.
        * ``replica_dead``     — a fleet replica marked dead (ISSUE 13).
          Latched per replica; ``action="replica_recover"`` re-arms.

        Injected faults (action="inject") are context, not failures —
        the containment they provoke is what must (and does) trip.
        """
        action = rec.get("action")
        if action == "ckpt_quarantine":
            latch = (
                f"ckpt_corrupt:{rec.get('ckpt_kind')}:{rec.get('ckpt_step')}"
            )
            if latch in self._latched:
                return
            self._latched.add(latch)
            self._emit(HealthEvent(
                event="ckpt_corrupt", severity=CRITICAL, step=step,
                message=(
                    f"checkpoint slot {rec.get('ckpt_kind')}/"
                    f"{int(rec.get('ckpt_step', 0))} failed integrity "
                    f"verification and was quarantined "
                    f"({rec.get('reason')})"
                ),
                data={
                    k: rec[k] for k in ("ckpt_kind", "ckpt_step", "reason")
                    if k in rec
                },
            ))
        elif action == "breaker":
            tenant = rec.get("tenant")
            latch = f"breaker_open:{tenant}"
            if rec.get("to") == "open":
                if latch in self._latched:
                    return
                self._latched.add(latch)
                self._emit(HealthEvent(
                    event="breaker_open", severity=CRITICAL, step=step,
                    message=(
                        f"circuit breaker OPEN for tenant {tenant!r} "
                        f"after {int(rec.get('failures', 0))} consecutive "
                        f"execute failures — shedding before it burns "
                        f"device time"
                    ),
                    data={
                        k: rec[k] for k in ("tenant", "from", "failures")
                        if k in rec
                    },
                ))
            elif rec.get("to") == "closed":
                self._latched.discard(latch)
        elif action == "replica_dead":
            # Fleet tier (ISSUE 13): a replica marked dead (breaker open
            # on forwarded failures, or the fleet.replica_kill chaos
            # point). Latched per replica; action="replica_recover"
            # re-arms — a flapping replica is one incident per down
            # transition, not one per routed-around request.
            replica = rec.get("replica")
            latch = f"replica_dead:{replica}"
            if latch in self._latched:
                return
            self._latched.add(latch)
            self._emit(HealthEvent(
                event="replica_dead", severity=CRITICAL, step=step,
                message=(
                    f"fleet replica {replica!r} marked DEAD "
                    f"({rec.get('reason')}) — "
                    f"{int(rec.get('tenants', 0))} tenant(s) failing "
                    f"over to degraded NOTA until re-placement"
                ),
                data={
                    k: rec[k] for k in ("replica", "reason", "tenants")
                    if k in rec
                },
            ))
        elif action == "replica_recover":
            self._latched.discard(f"replica_dead:{rec.get('replica')}")
        elif action == "scale_stuck":
            # Elasticity tier (ISSUE 16): a scale decision (spawn/warm
            # on scale-out, wait-for-inflight on drain-in) could not
            # complete within the autoscaler's budget. Once-latched; a
            # later COMPLETED scale event (kind="scale",
            # event="scale_out"/"drain_in") re-arms it.
            if "scale_stuck" in self._latched:
                return
            self._latched.add("scale_stuck")
            self._emit(HealthEvent(
                event="scale_stuck", severity=CRITICAL, step=step,
                message=(
                    f"autoscaler {rec.get('direction')} decision stuck "
                    f"after {rec.get('waited_s')}s "
                    f"(budget {rec.get('budget_s')}s): "
                    f"{rec.get('reason')}"
                ),
                data={
                    k: rec[k] for k in
                    ("direction", "replica", "reason", "waited_s",
                     "budget_s")
                    if k in rec
                },
            ))
        elif action == "publish_rollback":
            if "publish_rollback" in self._latched:
                return
            self._latched.add("publish_rollback")
            self._emit(HealthEvent(
                event="publish_rollback", severity=CRITICAL, step=step,
                message=(
                    f"publish transaction rolled back — every tenant "
                    f"stays on its pre-publish snapshot "
                    f"({rec.get('reason')})"
                ),
                data={
                    k: rec[k] for k in ("reason", "params_version")
                    if k in rec
                },
            ))

    def observe_feed(
        self,
        produced: int,
        consumed: int,
        producer_alive: bool = True,
        poisoned: int = 0,
        step: int = 0,
        waiting: bool = False,
        now: float | None = None,
    ) -> None:
        """Training-feed stall detection — the datapipe generalization of
        observe_queue: same ``queue_stall_s`` budget, but the watched
        counter is the PRODUCER's (a starving consumer with a stuck
        producer is the wedge; an idle feed with a full queue is healthy).
        Fed from ``kind="data"`` records; callable directly with an
        injectable clock for tests."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if poisoned > self._poisoned_seen:
                self._poisoned_seen = poisoned
                self._emit(HealthEvent(
                    event="feed_poisoned", severity=CRITICAL, step=step,
                    message=(
                        f"input pipeline refused a poisoned batch "
                        f"(total {poisoned})"
                    ),
                    data={"poisoned": poisoned, "consumed": consumed},
                ))
            if not producer_alive:
                if "feed_dead" not in self._latched:
                    self._latched.add("feed_dead")
                    self._emit(HealthEvent(
                        event="feed_dead", severity=CRITICAL, step=step,
                        message=(
                            f"input-pipeline producer thread is dead at "
                            f"consumed={consumed}"
                        ),
                        data={"produced": produced, "consumed": consumed},
                    ))
                return
            self._latched.discard("feed_dead")
            advancing = self._last_fed is None or produced > self._last_fed
            if advancing or not waiting:
                self._feed_stall_since = None
                self._feed_stall_reported = False
            elif self._feed_stall_since is None:
                self._feed_stall_since = now
            elif (
                not self._feed_stall_reported
                and now - self._feed_stall_since >= self.queue_stall_s
            ):
                self._feed_stall_reported = True
                self._emit(HealthEvent(
                    event="feed_stall", severity=CRITICAL, step=step,
                    message=(
                        f"input pipeline stalled: produced counter stuck "
                        f"at {produced} for "
                        f"{now - self._feed_stall_since:.1f}s with the "
                        f"trainer waiting"
                    ),
                    data={"produced": produced, "consumed": consumed},
                ))
            self._last_fed = produced

    def observe_queue(
        self, queue_depth: int, served: int, now: float | None = None
    ) -> None:
        """Serving stall detection. Callable directly (the engine's emit
        path does) with an injectable clock for tests."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if queue_depth <= 0 or (
                self._last_served is not None and served > self._last_served
            ):
                self._stall_since = None
                self._stall_reported = False
            elif self._stall_since is None:
                self._stall_since = now
            elif (
                not self._stall_reported
                and now - self._stall_since >= self.queue_stall_s
            ):
                self._stall_reported = True
                self._emit(HealthEvent(
                    event="queue_stall", severity=CRITICAL, step=served,
                    message=(
                        f"queue depth {queue_depth} with served counter "
                        f"stuck at {served} for "
                        f"{now - self._stall_since:.1f}s"
                    ),
                    data={"queue_depth": queue_depth, "served": served},
                ))
            self._last_served = served


# --- per-tenant SLOs: multi-window burn rates (ISSUE 9) -------------------


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One tenant's service-level objective.

    ``availability`` is the target GOOD fraction (error budget =
    1 - availability). A request is BAD when it errors (shed, rejected,
    deadline-missed, execution failure) or — with ``latency_ms`` set —
    when it completes slower than the threshold. Folding latency into
    the same budget is the standard "latency SLI as availability"
    spelling: one burn rate, one alert policy, for both failure modes.
    """

    availability: float = 0.99
    latency_ms: float | None = None

    def __post_init__(self):
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"availability must be in (0, 1), got {self.availability}"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.availability


class DiagnosticsCapture:
    """Auto-capture on an SLO CRITICAL: put the evidence on disk.

    Three artifacts, in decreasing order of certainty:

    * ``flight_recorder.json`` — the recorder's last-N window (metrics,
      health events, spans), when a recorder is attached.
    * ``slo_spans_<n>.json`` — a host-span snapshot from the tracker:
      the GUARANTEED artifact, written synchronously on every capture
      (CPU-honest — no profiler runtime required).
    * ``slo_profile_<n>/`` — a ``jax.profiler`` trace bracketing
      ``profile_s`` seconds of whatever executes next, captured from a
      background thread so the caller (a serving worker or submit path)
      never blocks on it. Best-effort: an unavailable/occupied profiler
      (another trace already active, no jax) downgrades to the span
      snapshot alone, and the returned dict says so. ``profile=False``
      disables the attempt entirely — the CLIs default to that on this
      image, where a profiler session concurrent with the threaded
      serving worker corrupts the heap and segfaults at interpreter
      exit (RUNBOOK §14; chip sessions opt in via ``--slo_profile``).
    """

    def __init__(
        self,
        out_dir,
        recorder=None,
        tracker=None,
        profile_s: float = 0.5,
        profile: bool = True,
    ):
        from pathlib import Path

        self.out_dir = Path(out_dir)
        self.recorder = recorder
        self._tracker = tracker
        self.profile_s = profile_s
        self.profile = profile
        self.captures = 0
        self._lock = threading.Lock()
        self._profiling = False

    def _get_tracker(self):
        if self._tracker is not None:
            return self._tracker
        from induction_network_on_fewrel_tpu.obs.spans import get_tracker

        return get_tracker()

    def capture(self, reason: str) -> dict:
        """Run one capture; returns {flight_dump, span_snapshot, profile,
        profile_state} with paths (str) or None per artifact."""
        import json

        with self._lock:
            self.captures += 1
            n = self.captures
        self.out_dir.mkdir(parents=True, exist_ok=True)
        out: dict = {"reason": reason}
        if self.recorder is not None:
            out["flight_dump"] = str(self.recorder.dump(reason=reason))
        else:
            out["flight_dump"] = None
        snap_path = self.out_dir / f"slo_spans_{n}.json"
        snap_path.write_text(json.dumps({
            "reason": reason,
            "captured_unix_s": time.time(),
            "spans": self._get_tracker().snapshot(),
        }, default=str, indent=1))
        out["span_snapshot"] = str(snap_path)
        out["profile"], out["profile_state"] = self._start_profile(n)
        return out

    def _start_profile(self, n: int) -> tuple[str | None, str]:
        if not self.profile:
            return None, "disabled"
        with self._lock:
            if self._profiling:
                # One profile at a time: a second critical during the
                # capture window keeps its span snapshot + dump.
                return None, "already_capturing"
            self._profiling = True
        prof_dir = self.out_dir / f"slo_profile_{n}"

        def _run():
            try:
                import jax

                jax.profiler.start_trace(str(prof_dir))
                try:
                    time.sleep(self.profile_s)
                finally:
                    jax.profiler.stop_trace()
            except Exception:
                # Profiler unavailable/occupied: the span snapshot above
                # is the capture. Nothing to clean up — start_trace either
                # took the global session (stopped in the finally) or
                # refused before touching it.
                pass
            finally:
                with self._lock:
                    self._profiling = False

        # Non-daemon on purpose: a daemon profiler thread racing
        # interpreter teardown segfaulted inside the profiler's C++
        # session (observed in the loadgen ab drill). The thread is
        # bounded at ~profile_s, so a clean exit waits at most that.
        t = threading.Thread(target=_run, name=f"slo-profile-{n}")
        t.start()
        self._profile_thread = t
        return str(prof_dir), "started"

    def wait(self, timeout: float | None = None) -> None:
        """Join an in-flight profiler capture (tests / orderly shutdown)."""
        t = getattr(self, "_profile_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout)


class _BurnWindow:
    """Running-sum time window: a deque of ``[bucket, good, bad]`` cells
    (touched buckets only) with maintained totals. ``add`` and ``counts``
    expire cells older than ``span`` buckets from the left, so reads are
    O(1) amortized and storage never scales with the window's cell
    capacity — the round-10 SLO scale paydown."""

    __slots__ = ("span", "cells", "good", "bad")

    def __init__(self, span: int):
        self.span = max(int(span), 1)
        self.cells: deque[list[float]] = deque()
        self.good = 0.0
        self.bad = 0.0

    def add(self, bucket: int, bad: bool) -> None:
        if self.cells and bucket < self.cells[-1][0]:
            # Clock went backwards across threads: fold into the newest
            # cell rather than corrupting the ascending-order invariant.
            bucket = int(self.cells[-1][0])
        if not self.cells or self.cells[-1][0] != bucket:
            self.cells.append([bucket, 0.0, 0.0])
        self.cells[-1][2 if bad else 1] += 1.0
        if bad:
            self.bad += 1.0
        else:
            self.good += 1.0
        self._expire(bucket)

    def _expire(self, bucket: int) -> None:
        while self.cells and self.cells[0][0] <= bucket - self.span:
            _, g, b = self.cells.popleft()
            self.good -= g
            self.bad -= b

    def counts(self, bucket: int) -> tuple[float, float]:
        """READ-ONLY window counts at ``bucket``: expired cells are
        subtracted without mutating state. Destructive expiry happens
        only in ``add`` (whose bucket comes from the engine's own
        monotonic clock) — a read with a wrong caller-supplied ``now``
        (e.g. wall clock against a monotonic t0) must not permanently
        delete still-valid SLO data, matching the old ring design's
        read-only reads. The window is ``(bucket - span, bucket]`` on
        BOTH sides — cells newer than the queried bucket are excluded
        too (the ring skipped ``b > at`` the same way), so a read with a
        stale ``now`` sees that moment's window, not all later traffic.
        Cost: O(out-of-range cells), usually zero (record-time expiry
        keeps the deque tight), bounded by span."""
        good, bad = self.good, self.bad
        for cell in self.cells:
            if cell[0] <= bucket - self.span:
                good -= cell[1]
                bad -= cell[2]
            else:
                break
        for cell in reversed(self.cells):
            if cell[0] > bucket:
                good -= cell[1]
                bad -= cell[2]
            else:
                break
        return good, bad


class SLOEngine:
    """Per-tenant SLO evaluation as multi-window burn rates.

    The SRE-standard alert shape: burn rate = (bad fraction over a
    window) / error budget. A burn of 1.0 spends the budget exactly over
    the SLO period; the FAST window (5m-equivalent) at a high threshold
    catches "the budget is vaporizing right now" (CRITICAL), the SLOW
    window (1h-equivalent) at a lower threshold catches sustained
    erosion (WARNING). Defaults are the classic 14.4x/6x pair.

    Mechanics:

    * ``record(tenant, latency_ms=..., error=...)`` per request outcome —
      ``ServingStats`` calls this from its existing recording points, so
      the engine's hot path gains no new locks beyond this object's own.
    * Outcomes land in fixed-width time buckets per tenant (ring of
      ``slow_window_s / bucket_s`` [good, bad] pairs — bounded memory per
      tenant, thousand-tenant soaks stay flat).
    * ``evaluate()`` sweeps tenants and emits once-latched events: a
      burning tenant is ONE incident until its fast window drops back
      under threshold (re-arm), not one critical per evaluation.
    * A fast-window CRITICAL triggers ``DiagnosticsCapture`` (flight
      dump + profiler-or-span-snapshot) exactly once per latch. The
      dump + span snapshot are SYNCHRONOUS on the evaluating thread by
      design: the evidence must be durable before the process can die
      of whatever is burning the budget, and the cost (tens of ms,
      once per incident) lands on one request of an already-burning
      tenant. Only the profiler leg backgrounds (it brackets future
      work by nature).
    * The clock is injectable everywhere (``now=``), like the watchdog's
      stall detectors, so tests and drills compress the "5m" windows to
      whatever wall-time they actually have.

    Scale (round-10 follow-up, PAID here): outcomes land in per-tenant
    **running-sum windows** (``_BurnWindow`` — a deque of touched bucket
    cells plus maintained good/bad totals, expired from the left as the
    bucket index advances), so one evaluate() sweep is O(tenants) and
    memory per tenant is O(touched buckets), never O(window cells). The
    old ring design allocated ``ceil(slow_window/bucket)`` cells per
    tenant up front and summed ``O(window cells)`` per sweep — a
    month-long slow window at 1 s buckets would have been 2.6M cells
    per tenant. Pinned cell-count-independent in
    tests/test_tracing.py::test_slo_evaluate_cell_count_independent.
    """

    MIN_COUNT = 10   # don't alert a window on fewer requests than this

    def __init__(
        self,
        objective: SLOObjective | None = None,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        fast_burn: float = 14.4,
        slow_burn: float = 6.0,
        bucket_s: float | None = None,
        logger=None,
        recorder=None,
        capture: DiagnosticsCapture | None = None,
        on_event: Callable[[HealthEvent], None] | None = None,
    ):
        if slow_window_s < fast_window_s:
            raise ValueError(
                f"slow window ({slow_window_s}s) must be >= fast window "
                f"({fast_window_s}s)"
            )
        self.default_objective = objective or SLOObjective()
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.fast_burn = fast_burn
        self.slow_burn = slow_burn
        self.bucket_s = bucket_s or max(fast_window_s / 12.0, 1e-3)
        self._span_fast = int(math.ceil(fast_window_s / self.bucket_s))
        self._span_slow = int(math.ceil(slow_window_s / self.bucket_s))
        self.logger = logger
        self.recorder = recorder
        self.capture = capture
        self.on_event = on_event
        self._lock = threading.RLock()
        self._objectives: dict[str, SLOObjective] = {}
        # tenant -> {"fast"/"slow": _BurnWindow} running sums.
        self._windows: dict[str, dict[str, _BurnWindow]] = {}
        self.events: deque[HealthEvent] = deque(maxlen=512)
        self.tripped = False
        self._latched: set[str] = set()
        self.captured: dict[str, dict] = {}   # latch key -> capture result
        self._t0: float | None = None
        self._last_eval_bucket = -1

    # --- objectives -------------------------------------------------------

    def set_objective(self, tenant: str, objective: SLOObjective) -> None:
        with self._lock:
            self._objectives[tenant] = objective

    def objective_for(self, tenant: str) -> SLOObjective:
        return self._objectives.get(tenant, self.default_objective)

    # --- recording --------------------------------------------------------

    def _bucket_index(self, now: float) -> int:
        if self._t0 is None:
            self._t0 = now
        return int((now - self._t0) / self.bucket_s)

    def _tenant_windows(self, tenant: str) -> dict[str, _BurnWindow]:
        wins = self._windows.get(tenant)
        if wins is None:
            wins = self._windows[tenant] = {
                "fast": _BurnWindow(self._span_fast),
                "slow": _BurnWindow(self._span_slow),
            }
        return wins

    def record(
        self,
        tenant: str,
        latency_ms: float | None = None,
        error: bool = False,
        now: float | None = None,
    ) -> None:
        """One request outcome. ``error=True`` is always bad; otherwise
        the tenant's latency threshold (when set) decides."""
        now = time.monotonic() if now is None else now
        with self._lock:
            obj = self.objective_for(tenant)
            bad = error or (
                obj.latency_ms is not None
                and latency_ms is not None
                and latency_ms > obj.latency_ms
            )
            bucket = self._bucket_index(now)
            for win in self._tenant_windows(tenant).values():
                win.add(bucket, bad)

    # --- evaluation -------------------------------------------------------

    def _rates_locked(self, tenant: str, bucket: int) -> dict | None:
        """burn_rates' body, caller holds the lock — ONE source for the
        public per-tenant read and evaluate()'s all-tenant sweep, so the
        sweep acquires the lock once instead of re-entering the RLock per
        tenant (the last O(tenants) lock cost in the sweep after the
        round-10 running-sum windows; re-entrant acquires are cheap but
        not free, and a thousand-tenant sweep paid two per tenant)."""
        wins = self._windows.get(tenant)
        if wins is None:
            return None
        obj = self.objective_for(tenant)
        out = {"budget": obj.budget}
        for label in ("fast", "slow"):
            good, bad = wins[label].counts(bucket)
            total = good + bad
            frac = bad / total if total else 0.0
            out[f"total_{label}"] = int(total)
            out[f"bad_{label}"] = int(bad)
            out[f"burn_{label}"] = (
                round(frac / obj.budget, 3) if obj.budget > 0 else 0.0
            )
        return out

    def burn_rates(
        self, tenant: str, now: float | None = None
    ) -> dict | None:
        """{burn_fast, burn_slow, bad_fast, total_fast, ...} for a tenant
        with recorded traffic; None otherwise. O(1) amortized per window
        — the running sums are maintained at record time."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._rates_locked(tenant, self._bucket_index(now))

    def tenants(self) -> tuple[str, ...]:
        """Tenants with recorded traffic, sorted — the autoscaler sweeps
        these for its max-burn pressure signal."""
        with self._lock:
            return tuple(sorted(self._windows))

    def evaluate(self, now: float | None = None) -> list[HealthEvent]:
        """Sweep every tenant's windows; emit (and return) new events.
        Cheap enough to call per stats emit; the serving engine also
        throttles it to once per bucket on the submit path.

        Lock discipline: judgments (window sums + latch transitions)
        happen under the lock; the EMISSION side effects — logger line,
        recorder event, diagnostics capture's file writes — run after
        releasing it. A capture at trip time writing the flight dump
        under this lock would stall every ``record()`` on the serving
        data plane for the duration, injecting the observer into the
        very incident it is documenting. The latch set (mutated under
        the lock) guarantees each event is claimed by exactly one
        evaluating thread."""
        now = time.monotonic() if now is None else now
        pending: list[tuple[HealthEvent, str]] = []
        with self._lock:
            # One lock acquisition and one bucket-index computation for
            # the WHOLE sweep (_rates_locked) — not two re-entrant
            # acquires and a clock quantization per tenant.
            bucket = self._bucket_index(now)
            for tenant in list(self._windows):
                rates = self._rates_locked(tenant, bucket)
                if rates is None:
                    continue
                pending.extend(self._judge(tenant, "fast", rates, CRITICAL,
                                           self.fast_burn))
                pending.extend(self._judge(tenant, "slow", rates, WARNING,
                                           self.slow_burn))
        for ev, latch in pending:
            self._emit(ev, latch)
        return [ev for ev, _ in pending]

    def maybe_evaluate(self, now: float | None = None) -> None:
        """evaluate() at most once per bucket width — the submit-path
        spelling (cheap steady-state: one int compare)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            bucket = self._bucket_index(now)
            if bucket == self._last_eval_bucket:
                return
            self._last_eval_bucket = bucket
        self.evaluate(now=now)

    def _judge(
        self, tenant: str, label: str, rates: dict, severity: str,
        threshold: float,
    ) -> list[tuple[HealthEvent, str]]:
        """Latch transition + event construction ONLY (call with the lock
        held); the caller emits after releasing the lock."""
        latch = f"slo_burn:{tenant}:{label}"
        burn = rates[f"burn_{label}"]
        total = rates[f"total_{label}"]
        if burn >= threshold and total >= self.MIN_COUNT:
            if latch in self._latched:
                return []
            self._latched.add(latch)
            ev = HealthEvent(
                event=f"slo_{label}_burn", severity=severity, step=total,
                message=(
                    f"tenant {tenant!r} burning its error budget "
                    f"{burn:.1f}x over the {label} window "
                    f"({rates[f'bad_{label}']}/{total} bad, "
                    f"budget {rates['budget']:.4g})"
                ),
                data={
                    "tenant": tenant,
                    f"burn_{label}": burn,
                    "burn_fast": rates["burn_fast"],
                    "burn_slow": rates["burn_slow"],
                    "bad": rates[f"bad_{label}"],
                    "total": total,
                },
            )
            return [(ev, latch)]
        if burn < threshold:
            self._latched.discard(latch)   # healthy window re-arms
        return []

    def _emit(self, ev: HealthEvent, latch: str) -> None:
        self.events.append(ev)
        if ev.severity == CRITICAL:
            self.tripped = True
        if self.recorder is not None:
            self.recorder.record_event(ev.to_dict())
        if self.logger is not None:
            self.logger.log(
                ev.step, kind="health", event=ev.event,
                severity=ev.severity, message=ev.message, **ev.data,
            )
        if ev.severity == CRITICAL:
            # Auto-capture: the whole point — the flight dump + profiler
            # (or host-span) evidence is on disk at trip time, once per
            # latch. Falls back to a bare recorder dump with no capture
            # configured.
            if self.capture is not None:
                self.captured[latch] = self.capture.capture(
                    reason=f"slo: {ev.message}"
                )
            elif self.recorder is not None:
                self.recorder.dump(reason=f"slo: {ev.message}")
        if self.on_event is not None:
            self.on_event(ev)
