"""Run-health watchdog: turns the metrics stream into structured events.

The failure modes this catches are the ones VERDICT.md flags as silent
today:

* **Non-finite loss/grads** — NaN/Inf in any numeric scalar of a train
  record (the bf16-backward risk, the MSE-sigmoid dead zone).
* **Throughput regression** — episodes/sec falling below a fraction of the
  rolling-median baseline (feed stall, thermal/preemption slowdowns).
* **Routing-entropy collapse** — the induction routing (or any model that
  logs a ``routing_entropy`` / ``*_entropy`` scalar) pinning near zero:
  every query routed identically, i.e. the class vectors collapsed.
* **Serving queue stall** — queue depth > 0 while the served counter stops
  advancing for longer than ``queue_stall_s`` (a wedged batcher worker).
* **Serving shed-load** — the per-tenant shed counter advancing between
  serve windows: some tenant is over its admission share and actively
  shedding traffic (ISSUE 7 fleet serving). Critical + once-latched, so a
  sustained overload is one incident; re-arms after a shed-free window.
  Hot-swap publishes (``event="snapshot_swap"`` serve records) surface as
  WARNING events — an operator reading the health stream sees every
  weight swap next to whatever it perturbed.
* **Feed stall / poison** — the training input pipeline (datapipe/) starving
  its consumer: stall ticks (``kind="data"``) whose produced counter stops
  advancing for longer than ``queue_stall_s`` while the trainer waits, a
  dead producer thread, or a poisoned batch — the feed-side generalization
  of the serving queue-stall detector.

Wiring: the watchdog is installed as a ``MetricsLogger`` hook, so every
record every execution path emits (train/val/serve) flows through
``observe_record`` with no extra calls at the emit sites. Events are
appended to the flight recorder, logged as ``kind="health"`` records in
metrics.jsonl, and — for critical events — trip the watchdog, which dumps
the flight recorder (obs/recorder.py) so the last-N window of context
survives the incident.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Any, Callable


CRITICAL = "critical"
WARNING = "warning"


@dataclasses.dataclass
class HealthEvent:
    event: str                 # "non_finite" | "throughput_regression" | ...
    severity: str              # "critical" | "warning"
    step: int
    message: str
    data: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "event": self.event,
            "severity": self.severity,
            "step": self.step,
            "message": self.message,
            **{k: v for k, v in self.data.items()},
        }


class HealthWatchdog:
    def __init__(
        self,
        logger=None,
        recorder=None,
        throughput_drop: float = 0.5,
        throughput_window: int = 8,
        throughput_warmup: int = 3,
        entropy_floor: float = 0.05,
        queue_stall_s: float = 5.0,
        on_event: Callable[[HealthEvent], None] | None = None,
    ):
        """``throughput_drop``: trip when eps/s < drop * rolling median.
        ``throughput_warmup``: train records to observe before the baseline
        arms (the first windows include compile time and are not a
        baseline). ``logger``/``recorder`` are attached lazily so the
        watchdog can be constructed before either exists."""
        self.logger = logger
        self.recorder = recorder
        self.throughput_drop = throughput_drop
        self.throughput_warmup = throughput_warmup
        self.entropy_floor = entropy_floor
        self.queue_stall_s = queue_stall_s
        self.on_event = on_event
        # Bounded (the module contract says everything here is): a
        # condition that persists for a whole soak must not grow host
        # memory one event per window.
        self.events: deque[HealthEvent] = deque(maxlen=512)
        self.tripped = False
        self._lock = threading.RLock()
        self._eps = deque(maxlen=throughput_window)
        self._in_emit = False
        # Once-semantics latches: a PERSISTENT condition (loss stuck at
        # NaN, entropy pinned at zero) emits one event when it begins and
        # re-arms only after a clean observation — not one critical event
        # (and one flight-recorder dump) per record for the rest of the
        # run. Keys: "non_finite:<kind>", "routing_collapse:<metric>",
        # "throughput".
        self._latched: set[str] = set()
        # Serving-stall state: (served counter, first time it was seen
        # unchanged with a non-empty queue).
        self._last_served: int | None = None
        self._stall_since: float | None = None
        self._stall_reported = False
        # Shed-load state: last aggregate shed counter seen.
        self._last_shed: int | None = None
        # Feed-stall state (training input pipeline): produced counter and
        # first time it was seen unchanged while the consumer waited.
        self._last_fed: int | None = None
        self._feed_stall_since: float | None = None
        self._feed_stall_reported = False
        self._poisoned_seen = 0

    # --- event plumbing --------------------------------------------------

    def _emit(self, ev: HealthEvent) -> None:
        self.events.append(ev)
        if ev.severity == CRITICAL:
            self.tripped = True
        if self.recorder is not None:
            self.recorder.record_event(ev.to_dict())
        if self.logger is not None:
            # Guard against self-observation: this log() call re-enters
            # observe_record through the logger hook.
            self._in_emit = True
            try:
                self.logger.log(
                    ev.step, kind="health", event=ev.event,
                    severity=ev.severity, message=ev.message, **ev.data,
                )
            finally:
                self._in_emit = False
        if ev.severity == CRITICAL and self.recorder is not None:
            self.recorder.dump(reason=f"watchdog: {ev.event} ({ev.message})")
        if self.on_event is not None:
            self.on_event(ev)

    # --- observations ----------------------------------------------------

    def observe_record(self, rec: dict) -> None:
        """MetricsLogger hook: one call per emitted record, any kind."""
        with self._lock:
            if self._in_emit:
                return
            kind = rec.get("kind")
            if kind == "health":
                # Grad-probe records are measurements, not watchdog output:
                # a NaN grad norm must still trip the non-finite check.
                if rec.get("event") == "grad_probe":
                    self._check_finite(int(rec.get("step", 0)), rec)
                return
            step = int(rec.get("step", 0))
            if kind in ("train", "val", "eval", "test", "serve"):
                self._check_finite(step, rec)
            if kind in ("train", "val", "eval"):
                self._check_entropy(step, rec)
            if kind == "train" and "episodes_per_s" in rec:
                self._check_throughput(step, float(rec["episodes_per_s"]))
            if kind == "serve":
                if rec.get("event") == "snapshot_swap":
                    # Visibility, not a failure: every hot-swap publish
                    # lands in the health stream next to whatever it
                    # perturbed.
                    # The logger normalizes scalars to float before hooks
                    # see them; these two are counts.
                    as_count = lambda v: (  # noqa: E731
                        int(v) if isinstance(v, (int, float)) else v
                    )
                    self._emit(HealthEvent(
                        event="snapshot_swap", severity=WARNING, step=step,
                        message=(
                            f"hot-swap published params_version "
                            f"{as_count(rec.get('params_version'))} to "
                            f"{as_count(rec.get('tenants'))} tenant(s)"
                        ),
                        data={
                            k: rec[k] for k in
                            ("params_version", "tenants", "slots")
                            if k in rec
                        },
                    ))
                elif "tenant" not in rec:
                    # Aggregate serve windows only: per-tenant records
                    # restate the same counters tenant-by-tenant.
                    self.observe_queue(
                        int(rec.get("queue_depth", 0)),
                        int(rec.get("served", 0)),
                    )
                    self._check_shed(step, rec)
            if kind == "data":
                self.observe_feed(
                    produced=int(rec.get("produced", 0)),
                    consumed=int(rec.get("consumed", 0)),
                    producer_alive=bool(rec.get("producer_alive", 1.0)),
                    poisoned=int(rec.get("poisoned", 0)),
                    step=step,
                    waiting="stalled_s" in rec,
                )

    def _check_finite(self, step: int, rec: dict) -> None:
        latch = f"non_finite:{rec.get('kind')}"
        bad = {
            k: str(v) for k, v in rec.items()
            if isinstance(v, float) and not math.isfinite(v)
        }
        if not bad:
            self._latched.discard(latch)  # clean record re-arms
            return
        if latch in self._latched:
            return
        self._latched.add(latch)
        self._emit(HealthEvent(
            event="non_finite", severity=CRITICAL, step=step,
            message=f"non-finite scalars: {sorted(bad)}",
            data={f"bad_{k}": v for k, v in bad.items()},
        ))

    def _check_entropy(self, step: int, rec: dict) -> None:
        for k, v in rec.items():
            if not k.endswith("entropy") or not isinstance(v, (int, float)):
                continue
            latch = f"routing_collapse:{k}"
            if math.isfinite(v) and v < self.entropy_floor:
                if latch in self._latched:
                    continue
                self._latched.add(latch)
                self._emit(HealthEvent(
                    event="routing_collapse", severity=CRITICAL, step=step,
                    message=f"{k}={v:.4g} below floor {self.entropy_floor}",
                    data={k: float(v)},
                ))
            else:
                self._latched.discard(latch)

    def _check_throughput(self, step: int, eps: float) -> None:
        if not math.isfinite(eps):
            return
        if len(self._eps) >= self.throughput_warmup:
            baseline = sorted(self._eps)[len(self._eps) // 2]  # rolling median
            if baseline > 0 and eps < self.throughput_drop * baseline:
                if "throughput" not in self._latched:
                    self._latched.add("throughput")
                    self._emit(HealthEvent(
                        event="throughput_regression", severity=WARNING,
                        step=step,
                        message=(
                            f"episodes_per_s {eps:.1f} < "
                            f"{self.throughput_drop:.0%} of baseline "
                            f"{baseline:.1f}"
                        ),
                        data={"episodes_per_s": eps, "baseline": baseline},
                    ))
                # A regressed window must not drag the baseline down with
                # it (a real slowdown stays an incident, not the new
                # normal) — and it must not re-arm the latch either.
                return
        self._latched.discard("throughput")  # healthy window re-arms
        self._eps.append(eps)

    def _check_shed(self, step: int, rec: dict) -> None:
        """Shed-load detection over aggregate serve windows: the shed
        counter advancing means some tenant is over its admission share
        and actively shedding. Once-latched (a sustained overload is one
        incident); a shed-free window re-arms."""
        shed = rec.get("shed")
        if not isinstance(shed, (int, float)):
            return
        shed = int(shed)
        prev, self._last_shed = self._last_shed, shed
        if prev is None:
            # First window: a nonzero total is still news.
            prev = 0
        if shed > prev:
            if "shed_load" in self._latched:
                return
            self._latched.add("shed_load")
            self._emit(HealthEvent(
                event="shed_load", severity=CRITICAL, step=step,
                message=(
                    f"shed-load active: {shed - prev} per-tenant share "
                    f"rejections since the last serve window "
                    f"(total {shed})"
                ),
                data={
                    "shed": shed,
                    "rejected": int(rec.get("rejected", 0)),
                    "queue_depth": int(rec.get("queue_depth", 0)),
                },
            ))
        else:
            self._latched.discard("shed_load")

    def observe_feed(
        self,
        produced: int,
        consumed: int,
        producer_alive: bool = True,
        poisoned: int = 0,
        step: int = 0,
        waiting: bool = False,
        now: float | None = None,
    ) -> None:
        """Training-feed stall detection — the datapipe generalization of
        observe_queue: same ``queue_stall_s`` budget, but the watched
        counter is the PRODUCER's (a starving consumer with a stuck
        producer is the wedge; an idle feed with a full queue is healthy).
        Fed from ``kind="data"`` records; callable directly with an
        injectable clock for tests."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if poisoned > self._poisoned_seen:
                self._poisoned_seen = poisoned
                self._emit(HealthEvent(
                    event="feed_poisoned", severity=CRITICAL, step=step,
                    message=(
                        f"input pipeline refused a poisoned batch "
                        f"(total {poisoned})"
                    ),
                    data={"poisoned": poisoned, "consumed": consumed},
                ))
            if not producer_alive:
                if "feed_dead" not in self._latched:
                    self._latched.add("feed_dead")
                    self._emit(HealthEvent(
                        event="feed_dead", severity=CRITICAL, step=step,
                        message=(
                            f"input-pipeline producer thread is dead at "
                            f"consumed={consumed}"
                        ),
                        data={"produced": produced, "consumed": consumed},
                    ))
                return
            self._latched.discard("feed_dead")
            advancing = self._last_fed is None or produced > self._last_fed
            if advancing or not waiting:
                self._feed_stall_since = None
                self._feed_stall_reported = False
            elif self._feed_stall_since is None:
                self._feed_stall_since = now
            elif (
                not self._feed_stall_reported
                and now - self._feed_stall_since >= self.queue_stall_s
            ):
                self._feed_stall_reported = True
                self._emit(HealthEvent(
                    event="feed_stall", severity=CRITICAL, step=step,
                    message=(
                        f"input pipeline stalled: produced counter stuck "
                        f"at {produced} for "
                        f"{now - self._feed_stall_since:.1f}s with the "
                        f"trainer waiting"
                    ),
                    data={"produced": produced, "consumed": consumed},
                ))
            self._last_fed = produced

    def observe_queue(
        self, queue_depth: int, served: int, now: float | None = None
    ) -> None:
        """Serving stall detection. Callable directly (the engine's emit
        path does) with an injectable clock for tests."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if queue_depth <= 0 or (
                self._last_served is not None and served > self._last_served
            ):
                self._stall_since = None
                self._stall_reported = False
            elif self._stall_since is None:
                self._stall_since = now
            elif (
                not self._stall_reported
                and now - self._stall_since >= self.queue_stall_s
            ):
                self._stall_reported = True
                self._emit(HealthEvent(
                    event="queue_stall", severity=CRITICAL, step=served,
                    message=(
                        f"queue depth {queue_depth} with served counter "
                        f"stuck at {served} for "
                        f"{now - self._stall_since:.1f}s"
                    ),
                    data={"queue_depth": queue_depth, "served": served},
                ))
            self._last_served = served
