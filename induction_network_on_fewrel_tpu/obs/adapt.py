"""Drift-triggered adaptation controller (ISSUE 14 tentpole).

The pieces of the quality loop all exist — ``obs/drift.py`` detects
per-tenant prediction drift (PR 9), ``datapipe/mixture.py`` ramps the
mixture curricula SCENARIOS_r01 proved recover domain-adaptation parity
(Gao et al. 2019's wiki -> pubmed shift in miniature), and
``publish_checkpoint`` hot-swaps a training artifact into the live fleet
with zero recompiles (PR 7/13). What latched-and-waited-for-a-human
until now closes here: ``AdaptationController`` subscribes to
DriftDetector CRITICALs and drives remediation as a SUPERVISED,
BOUNDED, GATED background job that can never make the fleet worse than
doing nothing:

* **armed -> triggered** — a CRITICAL ``prediction_drift`` event for a
  tenant arms one adaptation loop (re-triggers while a loop is already
  running, cooling down, or exhausted are absorbed — no retrain storms).
  The trigger snapshots the tenant's HEALTHY calibration baseline (the
  pre-drift normal the verification phase must return to).
* **training** — ``train_fn`` runs the targeted mixture-ramp fine-tune
  from the live checkpoint (``train/finetune.mixture_finetune``:
  PipelineFeed + MixtureSchedule + the delta-ring saver) under a STEP
  budget and a WALL-CLOCK budget; a budget breach kills the fine-tune
  and cleans its checkpoints (the helper's contract), and counts as a
  failed attempt.
* **canary** — the candidate is held to the scenario-harness quality
  floors (``tools/scenarios.run_canary``, plan-in/verdict-out) as a
  hard pre-publish go/no-go gate: a candidate that fails ANY floor is
  discarded (``cleanup_fn``) and NEVER published.
* **publishing** — survivors publish through the existing all-or-nothing
  fan-out (``FleetControl.publish_checkpoint`` — any replica's refusal
  rolls the whole fleet back) or a single engine's
  ``publish_checkpoint``; both re-arm every drift baseline through the
  engines' own commit hooks.
* **verifying** — success is DECLARED, not assumed: inside
  ``verify_window_s`` the drift detector must re-arm (re-baseline from
  post-publish traffic) with the tenant's NOTA rate back inside the
  band of the healthy trigger-time baseline. A drift CRITICAL
  re-tripping inside the window — or the window expiring un-verified —
  ROLLS BACK to the prior artifact (republished through the same
  fan-out) and counts the attempt failed.
* **cooldown / failed / exhausted** — a verified loop resets the
  attempt counter and suppresses triggers for ``cooldown_s``; a failed
  attempt retries after exponential backoff
  (``backoff_s * 2**(attempt-1)``); ``retry_budget`` failed attempts is
  the flap damper: the tenant latches a PERMANENT ``adapt_exhausted``
  CRITICAL (with auto-captured diagnostics), is quarantined
  (``quarantine_fn`` -> degraded NOTA verdicts, zero device time), and
  never retrains again without operator intervention.

Every transition emits one ``kind="adapt"`` record (schema documented in
utils/metrics.KNOWN_KINDS; tools/obs_report.py renders the loop outcome
table with a time-to-recover headline). Every failure arm is drillable
through the chaos registry: ``adapt.train_raise`` / ``adapt.canary_fail``
/ ``adapt.publish_raise`` (obs/chaos.py, RUNBOOK §19), proven end to end
by ``tools/loadgen.py --adapt_drill`` stamping ADAPT_r*.json.

The clock is injectable (``now=`` on every entry point) like every
detector in obs/: drills compress backoff/cooldown/verify windows to the
wall time they actually have. ``run_once``/``tick`` are the synchronous
spine (what tests and drills call); ``start()`` runs them on a
background thread for the serving CLIs.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from collections import deque
from typing import Callable

from induction_network_on_fewrel_tpu.obs.chaos import (
    ChaosError,
    chaos_fire,
)
from induction_network_on_fewrel_tpu.obs.health import (
    CRITICAL,
    HealthEvent,
)

# Controller states (per tenant). One adaptation loop runs at a time
# fleet-wide (the job is a supervised background fine-tune — two
# concurrent fine-tunes would contend for the same device).
ARMED = "armed"
TRIGGERED = "triggered"
TRAINING = "training"
CANARY = "canary"
PUBLISHING = "publishing"
VERIFYING = "verifying"
COOLDOWN = "cooldown"
EXHAUSTED = "exhausted"

STATES = (ARMED, TRIGGERED, TRAINING, CANARY, PUBLISHING, VERIFYING,
          COOLDOWN, EXHAUSTED)


class _Loop:
    """Per-tenant adaptation-loop state (guarded by the controller lock)."""

    __slots__ = (
        "state", "attempts", "not_before", "triggered_at", "feature",
        "healthy", "verify_deadline", "retripped", "prior", "candidate",
        "published_version", "cooldown_until", "loops",
    )

    def __init__(self):
        self.state = ARMED
        self.attempts = 0          # consecutive failed attempts (damper)
        self.not_before = 0.0      # earliest next attempt (backoff)
        self.triggered_at = None   # trigger wall time (recover_s anchor)
        self.feature = ""          # drift feature that tripped
        self.healthy = None        # trigger-time baseline {f: (mean, std)}
        self.verify_deadline = 0.0
        self.retripped = False     # drift CRITICAL during verification
        self.prior = None          # pre-publish artifact (rollback target)
        self.candidate = None      # published candidate (cleanup on
                                   # rollback)
        self.published_version = None
        self.cooldown_until = 0.0
        self.loops = 0             # verified (successful) loops


def make_checkpoint_loop(base_ckpt: str, work_dir: str,
                         finetune_fn: Callable, publish_fn: Callable,
                         prefix: str = "candidate_"):
    """ONE home for the closure wiring both controller builders
    (serve.py's ``_build_adapt`` and the drill's
    ``_build_adapt_controller``) hang the controller on — hand-mirrored
    copies drifted once already (the fine-tune-from-live fix had to
    land twice). Returns ``(train_fn, publish, cleanup, current_fn)``:

    * a live-artifact holder — repeat loops fine-tune from the last
      PUBLISHED artifact, not the stale startup base, and rollback
      republishes whatever is live;
    * ``train_fn`` minting sequential candidate dirs under ``work_dir``
      and delegating to ``finetune_fn(src_ckpt, out_dir, seq, attempt,
      step_budget, wall_budget_s)``;
    * a ``publish`` wrapper advancing the holder on commit;
    * a ``cleanup`` that only ever deletes candidate dirs THIS loop
      minted (never the base checkpoint or an operator-provided dir).
    """
    live = {"artifact": base_ckpt}
    seq = {"n": 0}

    def train_fn(tenant, attempt, step_budget, wall_budget_s):
        seq["n"] += 1
        out = os.path.join(work_dir, f"{prefix}{seq['n']:03d}")
        return finetune_fn(live["artifact"], out, seq["n"], attempt,
                           step_budget, wall_budget_s)

    def publish(candidate):
        version = publish_fn(candidate)
        live["artifact"] = candidate
        return version

    def cleanup(candidate):
        if isinstance(candidate, str) and candidate.startswith(
                os.path.join(work_dir, prefix)):
            shutil.rmtree(candidate, ignore_errors=True)

    return train_fn, publish, cleanup, (lambda: live["artifact"])


class AdaptationController:
    """Supervised drift -> fine-tune -> canary -> publish -> verify loop.

    ``train_fn(tenant, attempt, step_budget, wall_budget_s)`` returns an
    opaque CANDIDATE (whatever ``publish_fn`` accepts — the stack's
    spelling is a checkpoint directory); it must enforce the budgets
    itself and clean up on failure (``train/finetune.mixture_finetune``'s
    contract). ``canary_fn(candidate)`` returns a verdict dict with
    ``passed`` (tools/scenarios.run_canary). ``publish_fn(candidate)``
    returns the committed params_version (a single engine's
    ``publish_checkpoint`` or the fleet fan-out — both raise on refusal,
    which counts the attempt failed with the fleet untouched).
    ``current_fn()`` returns the currently-live artifact, captured
    immediately before each publish as the rollback target.
    ``cleanup_fn(candidate)`` discards a candidate that failed the
    canary (or was rolled back). ``quarantine_fn(tenant, reason)`` runs
    at exhaustion. ``drift`` is the detector to subscribe to (``bind``)
    and to verify re-arm/in-band against; without one, verification
    degrades to publish-implies-success (unit-test harnesses)."""

    def __init__(
        self,
        train_fn: Callable,
        canary_fn: Callable | None,
        publish_fn: Callable,
        *,
        drift=None,
        current_fn: Callable | None = None,
        cleanup_fn: Callable | None = None,
        quarantine_fn: Callable | None = None,
        retry_budget: int = 3,
        backoff_s: float = 2.0,
        cooldown_s: float = 60.0,
        verify_window_s: float = 30.0,
        step_budget: int = 200,
        wall_budget_s: float = 300.0,
        logger=None,
        recorder=None,
        capture=None,
        on_event: Callable[[HealthEvent], None] | None = None,
        journal=None,
    ):
        if retry_budget < 1:
            raise ValueError(f"retry_budget must be >= 1, got {retry_budget}")
        if backoff_s <= 0 or verify_window_s <= 0 or wall_budget_s <= 0:
            raise ValueError(
                "backoff_s/verify_window_s/wall_budget_s must be > 0"
            )
        self.train_fn = train_fn
        self.canary_fn = canary_fn
        self.publish_fn = publish_fn
        self.drift = drift
        self.current_fn = current_fn
        self.cleanup_fn = cleanup_fn
        self.quarantine_fn = quarantine_fn
        self.retry_budget = retry_budget
        self.backoff_s = backoff_s
        self.cooldown_s = cooldown_s
        self.verify_window_s = verify_window_s
        self.step_budget = step_budget
        self.wall_budget_s = wall_budget_s
        self.logger = logger
        self.recorder = recorder
        self.capture = capture
        self.on_event = on_event
        # Optional fleet journal (ISSUE 15): the EXHAUSTED latch is
        # control-plane state that must survive a router restart — a
        # recovered fleet must not un-quarantine a flapping tenant and
        # re-enter the retrain storm the damper stopped.
        self.journal = journal
        self._lock = threading.RLock()
        self._loops: dict[str, _Loop] = {}
        self._busy = False           # one fine-tune at a time, fleet-wide
        self._seq = 0                # kind="adapt" record step counter
        self._prev_on_event = None   # chained drift subscriber (bind)
        self._bound_fanout = None    # the installed fanout (bind guard)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.events: deque[HealthEvent] = deque(maxlen=256)
        self.records: deque[dict] = deque(maxlen=512)   # drills/tests
        if drift is not None:
            self.bind(drift)

    # --- subscription -----------------------------------------------------

    def bind(self, drift) -> None:
        """Subscribe to the detector's event stream, CHAINING any
        existing subscriber (the detector has one ``on_event`` slot).
        Idempotent: re-binding the same detector is a no-op — the guard
        compares against the INSTALLED fanout closure, not the inner
        handler, so a second bind can never chain the fanout to itself
        (which would recurse on the first event)."""
        self.drift = drift
        prev = drift.on_event
        if prev is not None and prev is self._bound_fanout:
            return
        self._prev_on_event = prev

        def fanout(ev):
            if self._prev_on_event is not None:
                self._prev_on_event(ev)
            self._on_drift_event(ev)

        self._bound_fanout = fanout
        drift.on_event = fanout

    def _on_drift_event(self, ev: HealthEvent) -> None:
        if ev.event != "prediction_drift" or ev.severity != CRITICAL:
            return
        tenant = ev.data.get("tenant")
        if not isinstance(tenant, str):
            return
        self.trigger(tenant, feature=str(ev.data.get("feature", "")))

    def restore_exhausted(self, exhausted) -> None:
        """Re-prime the PERMANENT exhaustion latches from a recovered
        journal (fleet/journal.JournalState.adapt_exhausted: tenant ->
        attempts). The latch is journaled at exhaustion time so a
        router restart cannot forget it — without this read-back, a
        recovered fleet would absorb nothing and the next drift
        CRITICAL on a quarantined flapper would re-enter exactly the
        retrain storm the damper stopped. Accepts a mapping or an
        iterable of tenant names."""
        items = (exhausted.items() if hasattr(exhausted, "items")
                 else ((t, 0.0) for t in exhausted))
        with self._lock:
            for tenant, attempts in items:
                loop = self._loops.setdefault(str(tenant), _Loop())
                loop.state = EXHAUSTED
                loop.attempts = max(loop.attempts,
                                    int(float(attempts or 0.0)))

    # --- trigger ----------------------------------------------------------

    def trigger(self, tenant: str, feature: str = "",
                now: float | None = None) -> bool:
        """One drift CRITICAL arrived for ``tenant``. Returns whether a
        NEW adaptation loop armed (re-triggers during a running loop,
        cooldown, or after exhaustion are absorbed — except during
        VERIFYING, where a re-trip marks the published candidate failed
        so the next ``tick`` rolls it back)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            loop = self._loops.setdefault(tenant, _Loop())
            if loop.state == VERIFYING:
                # Post-publish drift re-trip inside the verification
                # window: the adaptation made nothing better. The tick
                # path performs the rollback (it owns the publish
                # plumbing); here we only flip the verdict bit.
                loop.retripped = True
                return False
            if loop.state == COOLDOWN and now >= loop.cooldown_until:
                loop.state = ARMED
            if loop.state != ARMED:
                return False     # running / backing off / cooling /
                                 # exhausted: absorbed, no retrain storm
            loop.state = TRIGGERED
            loop.triggered_at = now
            loop.feature = feature
            loop.retripped = False
            # The HEALTHY normal the verification phase must return to:
            # the tenant's calibration baseline as of the trigger (the
            # detector replaces it only on re-arm, so at trigger time it
            # is still the pre-drift baseline).
            loop.healthy = (
                self.drift.baseline_for(tenant)
                if self.drift is not None else None
            )
        self._record(tenant, "trigger", state=TRIGGERED,
                     attempt=float(loop.attempts + 1), feature=feature)
        return True

    # --- the adaptation job ----------------------------------------------

    def run_once(self, now: float | None = None) -> str | None:
        """Run ONE due adaptation attempt to its publish (or failure),
        synchronously on the calling thread. Returns the tenant
        processed, or None when nothing is due. The background thread
        (``start``) calls this in its loop; drills and tests call it
        directly with an injected clock."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._busy:
                return None
            tenant = next(
                (t for t, lp in sorted(self._loops.items())
                 if lp.state == TRIGGERED and now >= lp.not_before),
                None,
            )
            if tenant is None:
                return None
            loop = self._loops[tenant]
            loop.state = TRAINING
            self._busy = True
        try:
            self._attempt(tenant, loop, now)
        except Exception:
            # An unexpected failure in the attempt MACHINERY itself —
            # e.g. a raising telemetry write between the guarded stages
            # — must not strand the tenant in TRAINING/CANARY/PUBLISHING
            # (states neither run_once nor tick can ever schedule
            # again). The state repair in _attempt_failed happens under
            # the lock BEFORE any telemetry, so even a re-raising
            # record leaves the loop schedulable; the error then
            # surfaces to the caller (the background worker logs on).
            with self._lock:
                wedged = loop.state in (TRAINING, CANARY, PUBLISHING)
            if wedged:
                self._attempt_failed(tenant, loop, "internal", now)
            raise
        finally:
            with self._lock:
                self._busy = False
        return tenant

    def _attempt(self, tenant: str, loop: _Loop, now: float) -> None:
        attempt = loop.attempts + 1
        # Wall clock at attempt entry: the verification deadline must be
        # anchored at PUBLISH time, not at run_once() entry — a 200-step
        # fine-tune plus the canary can take minutes, and charging that
        # against a 30 s verify window would roll back every good
        # candidate as "expired" before post-publish traffic could
        # possibly re-baseline the detector. ``now`` may be an injected
        # test clock, so the attempt's real elapsed wall is ADDED to it
        # rather than re-read from time.monotonic() (zero under injected
        # clocks, exact in production where now IS monotonic).
        entry_wall = time.monotonic()
        # -- training -----------------------------------------------------
        t0 = time.monotonic()
        try:
            if chaos_fire("adapt.train_raise", tenant=tenant,
                          step=self._seq) is not None:
                raise ChaosError(
                    f"injected fine-tune failure for {tenant!r} (chaos)"
                )
            candidate = self.train_fn(
                tenant, attempt, self.step_budget, self.wall_budget_s
            )
        except BaseException as e:  # noqa: BLE001 — budget kills included
            self._record(
                tenant, "train", state=TRAINING, attempt=float(attempt),
                ok=0.0, train_s=round(time.monotonic() - t0, 3),
                error=f"{type(e).__name__}: {e}",
            )
            self._attempt_failed(tenant, loop, "train", now)
            return
        self._record(tenant, "train", state=CANARY, attempt=float(attempt),
                     ok=1.0, train_s=round(time.monotonic() - t0, 3),
                     steps=float(self.step_budget))
        # -- canary gate --------------------------------------------------
        with self._lock:
            loop.state = CANARY
        if chaos_fire("adapt.canary_fail", tenant=tenant,
                      step=self._seq) is not None:
            verdict = {"passed": False, "injected": True,
                       "failures": ["chaos: adapt.canary_fail"]}
        elif self.canary_fn is not None:
            try:
                verdict = self.canary_fn(candidate)
            except BaseException as e:  # noqa: BLE001 — a raising canary
                # is a failed gate, never a publish
                verdict = {"passed": False,
                           "failures": [f"{type(e).__name__}: {e}"]}
        else:
            verdict = {"passed": True, "failures": []}
        failures = verdict.get("failures") or []
        self._record(
            tenant, "canary", state=CANARY, attempt=float(attempt),
            passed=1.0 if verdict.get("passed") else 0.0,
            failures=float(len(failures)),
            **({"first_failure": str(failures[0])[:160]}
               if failures else {}),
        )
        if not verdict.get("passed"):
            # Discarded, never published — the canary is a hard bar.
            self._cleanup(candidate)
            self._attempt_failed(tenant, loop, "canary", now)
            return
        # -- publish ------------------------------------------------------
        with self._lock:
            loop.state = PUBLISHING
            loop.prior = (
                self.current_fn() if self.current_fn is not None else None
            )
            loop.candidate = candidate
        t1 = time.monotonic()
        try:
            if chaos_fire("adapt.publish_raise", tenant=tenant,
                          step=self._seq) is not None:
                raise ChaosError(
                    f"injected publish failure for {tenant!r} (chaos)"
                )
            version = self.publish_fn(candidate)
        except BaseException as e:  # noqa: BLE001 — fan-out refusals
            # (FleetPublishError et al.) rolled the fleet back already;
            # the candidate is discarded and the attempt counts failed.
            self._record(
                tenant, "publish", state=PUBLISHING,
                attempt=float(attempt), ok=0.0,
                error=f"{type(e).__name__}: {e}",
            )
            self._cleanup(candidate)
            with self._lock:
                loop.candidate = None
            self._attempt_failed(tenant, loop, "publish", now)
            return
        with self._lock:
            loop.published_version = version
            loop.state = VERIFYING
            loop.verify_deadline = (
                now + (time.monotonic() - entry_wall)
                + self.verify_window_s
            )
        self._record(
            tenant, "publish", state=VERIFYING, attempt=float(attempt),
            ok=1.0, params_version=float(version),
            publish_s=round(time.monotonic() - t1, 3),
        )

    # --- verification ----------------------------------------------------

    def _verify_ok(self, tenant: str, loop: _Loop) -> dict | None:
        """The success bar: the drift detector re-armed (re-baselined
        from post-publish traffic) AND the tenant's NOTA rate is back
        inside the band of the healthy trigger-time baseline. Returns
        the check's numbers, or None when not (yet) satisfied."""
        if self.drift is None:
            return {"nota_base": -1.0, "nota_healthy": -1.0,
                    "nota_band": -1.0}   # no detector: publish = success
        if not self.drift.armed(tenant):
            return None
        base = self.drift.baseline_for(tenant)
        if base is None or loop.healthy is None:
            return {"nota_base": -1.0, "nota_healthy": -1.0,
                    "nota_band": -1.0}
        import math

        h_mean, h_std = loop.healthy["nota_rate"]
        cur = base["nota_rate"][0]
        band = max(
            self.drift.band_sigma * h_std
            / math.sqrt(max(self.drift.baseline_n, 1)),
            self.drift.nota_rate_floor,
        )
        if abs(cur - h_mean) > band:
            return None
        return {"nota_base": round(cur, 6),
                "nota_healthy": round(h_mean, 6),
                "nota_band": round(band, 6)}

    def tick(self, now: float | None = None) -> None:
        """Advance time-driven states: verification success/rollback and
        cooldown expiry. Called by the background loop and by drills
        after driving post-publish traffic."""
        now = time.monotonic() if now is None else now
        with self._lock:
            items = list(self._loops.items())
        for tenant, loop in items:
            if loop.state == VERIFYING:
                if loop.retripped:
                    self._rollback(tenant, loop,
                                   "post-publish drift re-trip", now)
                    continue
                ok = self._verify_ok(tenant, loop)
                if ok is not None:
                    self._verified(tenant, loop, now, ok)
                elif now >= loop.verify_deadline:
                    self._rollback(
                        tenant, loop,
                        "verification window expired without re-arm/"
                        "in-band NOTA rate", now,
                    )
            elif loop.state == COOLDOWN and now >= loop.cooldown_until:
                with self._lock:
                    if loop.state == COOLDOWN:
                        loop.state = ARMED

    def _verified(self, tenant: str, loop: _Loop, now: float,
                  check: dict) -> None:
        with self._lock:
            recover_s = (
                now - loop.triggered_at
                if loop.triggered_at is not None else -1.0
            )
            loop.state = COOLDOWN
            loop.cooldown_until = now + self.cooldown_s
            loop.attempts = 0          # damper resets on success
            loop.loops += 1
            loop.prior = None
            loop.candidate = None
        self._record(
            tenant, "verified", state=COOLDOWN, attempt=0.0,
            recover_s=round(recover_s, 3),
            params_version=float(loop.published_version
                                 if loop.published_version is not None
                                 else -1),
            **check,
        )

    def _rollback(self, tenant: str, loop: _Loop, reason: str,
                  now: float) -> None:
        with self._lock:
            prior, candidate = loop.prior, loop.candidate
            loop.prior = None
            loop.candidate = None
        rolled_version = None
        if prior is not None:
            try:
                rolled_version = self.publish_fn(prior)
            except BaseException as e:  # noqa: BLE001 — a failing
                # rollback publish must not wedge the controller; the
                # fleet stays on the (bad) candidate and the record +
                # exhaustion path tell the operator.
                reason = f"{reason}; rollback publish FAILED: {e}"
        self._record(
            tenant, "rollback", state=TRIGGERED,
            attempt=float(loop.attempts + 1), reason=reason[:200],
            params_version=float(rolled_version
                                 if rolled_version is not None else -1),
        )
        # The candidate directory is deletable ONLY once the prior
        # artifact actually recommitted: with no rollback target, or a
        # rollback publish that failed, the fleet is still SERVING the
        # candidate — deleting it would orphan the live params_version
        # (and fail every later fine-tune reading the live artifact).
        if rolled_version is not None:
            self._cleanup(candidate)
        self._attempt_failed(tenant, loop, "verify", now)

    # --- failure / exhaustion --------------------------------------------

    def _attempt_failed(self, tenant: str, loop: _Loop, stage: str,
                        now: float) -> None:
        with self._lock:
            loop.attempts += 1
            attempts = loop.attempts
            if attempts >= self.retry_budget:
                loop.state = EXHAUSTED
            else:
                loop.state = TRIGGERED
                loop.not_before = (
                    now + self.backoff_s * (2.0 ** (attempts - 1))
                )
        if attempts >= self.retry_budget:
            self._record(tenant, "exhausted", state=EXHAUSTED,
                         attempt=float(attempts), stage=stage)
            self._send(HealthEvent(
                event="adapt_exhausted", severity=CRITICAL, step=self._seq,
                message=(
                    f"tenant {tenant!r} burned its adaptation retry "
                    f"budget ({attempts} failed attempts, last stage "
                    f"{stage!r}): quarantined, no further retrains "
                    f"without operator intervention"
                ),
                data={"tenant": tenant, "attempts": float(attempts),
                      "stage": stage},
            ))
            if self.journal is not None:
                try:
                    self.journal.append(
                        "adapt_exhausted", tenant=tenant,
                        attempts=float(attempts),
                    )
                except Exception:  # noqa: BLE001 — the CRITICAL above
                    pass           # is the hard signal either way
            if self.quarantine_fn is not None:
                try:
                    self.quarantine_fn(
                        tenant, reason="adapt retry budget exhausted"
                    )
                except Exception:  # noqa: BLE001 — best-effort: the
                    pass           # CRITICAL above is the hard signal

    def _cleanup(self, candidate) -> None:
        if candidate is None or self.cleanup_fn is None:
            return
        try:
            self.cleanup_fn(candidate)
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass

    # --- emission ---------------------------------------------------------

    def _record(self, tenant: str, action: str, **fields) -> None:
        self._seq += 1
        rec = {"action": action, "tenant": tenant, **fields}
        self.records.append(rec)
        if self.logger is not None:
            self.logger.log(self._seq, kind="adapt", **rec)

    def _send(self, ev: HealthEvent) -> None:
        """adapt_exhausted is PERMANENT by construction (the state
        machine never leaves EXHAUSTED), so emission is once per tenant
        without a separate latch set."""
        self.events.append(ev)
        if self.recorder is not None:
            self.recorder.record_event(ev.to_dict())
        if self.logger is not None:
            self.logger.log(
                ev.step, kind="health", event=ev.event,
                severity=ev.severity, message=ev.message, **ev.data,
            )
        if self.capture is not None:
            self.capture.capture(reason=f"adapt: {ev.message}")
        elif self.recorder is not None:
            self.recorder.dump(reason=f"adapt: {ev.message}")
        if self.on_event is not None:
            self.on_event(ev)

    # --- introspection / lifecycle ---------------------------------------

    def state_of(self, tenant: str) -> str:
        with self._lock:
            loop = self._loops.get(tenant)
            return loop.state if loop is not None else ARMED

    def loop_info(self, tenant: str) -> dict:
        with self._lock:
            loop = self._loops.get(tenant)
            if loop is None:
                return {"state": ARMED, "attempts": 0, "loops": 0}
            return {
                "state": loop.state, "attempts": loop.attempts,
                "loops": loop.loops, "not_before": loop.not_before,
                "published_version": loop.published_version,
            }

    def unquarantine(self, tenant: str) -> None:
        """Operator escape hatch: reset an EXHAUSTED tenant to ARMED
        (the quarantine itself is lifted at the registry/control plane
        by the operator — RUNBOOK §19)."""
        with self._lock:
            loop = self._loops.get(tenant)
            if loop is not None and loop.state == EXHAUSTED:
                loop.state = ARMED
                loop.attempts = 0
                loop.not_before = 0.0

    def start(self, poll_s: float = 0.5) -> None:
        """Run the loop on a background daemon thread (the serving CLI
        spelling); drills/tests stay on the synchronous entry points."""
        if self._thread is not None:
            return
        self._stop.clear()

        def worker():
            while not self._stop.is_set():
                try:
                    self.run_once()
                    self.tick()
                except Exception:  # noqa: BLE001 — the supervisor
                    pass           # thread must survive any one loop
                self._stop.wait(poll_s)

        self._thread = threading.Thread(
            target=worker, name="adapt-controller", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
