"""Flight recorder: last-N telemetry kept in memory, dumped on incident.

Answers "why did this run get slow/diverge" *after the fact* without
re-running under a profiler: the recorder rides along holding bounded
rings of (a) recent metric records, (b) health events, and (c) the span
window from the tracker, and writes one ``flight_recorder.json`` when
something goes wrong — a crash (``armed()`` context), SIGTERM, or a
watchdog trip (obs/health.py calls ``dump`` on critical events).

Everything is bounded; a recorder attached to a week-long soak costs the
same memory as one attached to a smoke test.
"""

from __future__ import annotations

import contextlib
import json
import signal
import threading
import time
from collections import deque
from pathlib import Path


class FlightRecorder:
    def __init__(
        self,
        out_dir: str | Path | None = None,
        tracker=None,
        max_metrics: int = 512,
        max_events: int = 256,
    ):
        """``out_dir`` is where ``flight_recorder.json`` lands (defaults to
        the cwd at dump time). ``tracker`` is a SpanTracker whose current
        window is included in dumps (default: the process-global one)."""
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self._tracker = tracker
        self._metrics: deque = deque(maxlen=max_metrics)
        self._events: deque = deque(maxlen=max_events)
        # RLock, not Lock: the SIGTERM handler runs dump() on the main
        # thread between bytecodes — if the signal lands while that same
        # thread is inside record_metric, a plain lock would deadlock the
        # exit path instead of dumping.
        self._lock = threading.RLock()
        self._t0 = time.monotonic()
        self.dump_count = 0
        self.last_dump_path: Path | None = None
        self._prev_sigterm = None

    # --- feeding ---------------------------------------------------------

    def record_metric(self, rec: dict) -> None:
        """MetricsLogger hook: retain the most recent metric records."""
        with self._lock:
            self._metrics.append(rec)

    def record_event(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)

    # --- dumping ---------------------------------------------------------

    def _tracker_snapshot(self) -> list[dict]:
        tracker = self._tracker
        if tracker is None:
            from induction_network_on_fewrel_tpu.obs.spans import get_tracker

            tracker = get_tracker()
        return tracker.snapshot()

    def dump(self, reason: str, path: str | Path | None = None) -> Path:
        """Write flight_recorder.json (atomically via tmp+rename) and
        return its path. Multiple dumps overwrite — the newest incident is
        the one being debugged; ``dump_count`` records that earlier dumps
        happened."""
        from induction_network_on_fewrel_tpu.utils.metrics import json_sanitize

        with self._lock:
            payload = {
                "reason": reason,
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "dumped_unix_s": time.time(),
                "dump_count": self.dump_count + 1,
                "events": list(self._events),
                # Retained records carry raw floats (the watchdog needs
                # them); the dump must stay strict JSON — no NaN tokens.
                "metrics": [
                    {k: json_sanitize(v) for k, v in m.items()}
                    for m in self._metrics
                ],
                "spans": self._tracker_snapshot(),
            }
            self.dump_count += 1
        out = Path(path) if path is not None else (
            (self.out_dir or Path(".")) / "flight_recorder.json"
        )
        out.parent.mkdir(parents=True, exist_ok=True)
        tmp = out.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(payload, default=str, indent=1))
        tmp.replace(out)
        self.last_dump_path = out
        return out

    # --- triggers --------------------------------------------------------

    @contextlib.contextmanager
    def armed(self, reason_prefix: str = "crash"):
        """Dump on any exception escaping the block (then re-raise).
        KeyboardInterrupt dumps too — an interrupted soak is exactly when
        the window matters."""
        try:
            yield self
        except BaseException as e:
            self.dump(reason=f"{reason_prefix}: {type(e).__name__}: {e}")
            raise

    def install_sigterm_handler(self) -> bool:
        """Dump on SIGTERM before chaining to the previous handler (or
        default exit). Main-thread only — Python restricts signal() to it;
        returns False (no-op) elsewhere so library use stays safe."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _handler(signum, frame):
            self.dump(reason="SIGTERM")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
        return True
