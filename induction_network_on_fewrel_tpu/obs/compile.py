"""XLA compile forensics: every compile observed, stamped, attributable.

Serving has counted its compiles since ISSUE 1 (``QueryProgramCache`` ->
``ServingStats.steady_compiles``, the zero-steady-state-recompile
acceptance gate). Training had nothing: a shape leak (a ragged tail
batch, a knob flipped mid-run, a donated-buffer dtype drift) recompiles
the 30-second flagship step silently and the only symptom is a
throughput crater nobody can attribute. This module closes that gap
(ISSUE 11 tentpole, layer 2).

Mechanism — two hooks, one record:

* ``jax.monitoring`` duration events: ``/jax/core/compile/
  backend_compile_duration`` fires once per actual XLA backend compile,
  on the compiling thread, with the elapsed seconds. This is the
  authoritative "a compile happened" signal (cache hits never fire it).
* The ``jax._src.interpreters.pxla`` DEBUG log line ``"Compiling <fn>
  with global shapes and types [...]"`` carries what monitoring does not:
  the jitted function's NAME and its argument SHAPE SIGNATURE. A
  logging.Handler parses it into a thread-local pending slot; the
  monitoring event closes the slot into one ``CompileRecord``. (With
  ``jax_log_compiles`` off the line is emitted at DEBUG — the handler
  listens at DEBUG without promoting anything to the console.)

Attribution: the record also captures the innermost OPEN host span on
the compiling thread (obs/spans) — a compile observed inside
``train/dispatch`` vs ``train/eval`` vs ``serve/execute`` names its
trigger — plus the active trace id, so a recompile burst lands in the
same waterfall as the step that paid for it.

Steady-state gate (the serving invariant, mirrored): two layers.

* The ``phase`` stamped on every record is a NOVELTY rule — the first
  compile of each distinct function name is ``warmup`` (train step, eval
  step, grad probe all compile once, whenever they first run); a SEEN
  function compiling a NEW shape signature is a ``recompile``; a seen
  (fn, signature) pair re-compiling (cache eviction, weak-type quirks)
  is a ``dup``. Pure forensics — every compile is recorded either way.
* The GATE (``steady_recompiles`` + the once-latched CRITICAL
  ``recompile_burst``) counts only recompiles observed after
  ``arm_steady()`` (the trainer arms at the first metric window, once
  the setup storm of single-primitive utility pjits — convert/concat/
  threefry, which legitimately compile many shapes — is over) AND
  costing at least ``gate_min_s``: the invariant exists to catch the
  multi-second flagship step recompiling mid-run, not a 10 ms
  convert_element_type shape variant at an eval boundary. Ungated
  novelty counts stay visible as ``shape_variant_compiles``.

Process-global plumbing: jax.monitoring listeners cannot be unregistered
individually, so ONE module-level dispatcher registers lazily and fans
out to the active watchers (a set this module owns). ``uninstall()``
detaches a watcher from the set and the logging handler; tests create
and drop watchers freely.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import threading
from collections import deque
from typing import Callable

_PXLA_LOGGER = "jax._src.interpreters.pxla"
# "Compiling <fn> with global shapes and types [ShapedArray(...), ...].
#  Argument mapping: (...)." — greedy capture: the signature itself
# contains "]" (float32[4,4]), so the match must run to the LAST bracket.
_COMPILING_RE = re.compile(
    r"Compiling ([^\s]+) with global shapes and types \[(.*)\]", re.S
)


@dataclasses.dataclass
class CompileRecord:
    fn: str                  # jitted function name ("?" if the log line
    #                          was missed — monitoring still counts it)
    shapes: str              # argument shape signature, as pxla prints it
    elapsed_s: float         # backend compile seconds (monitoring)
    trigger: str             # innermost open host span, or "untraced"
    thread: str
    step: int                # last step stamped via observe_step()
    phase: str               # "warmup" | "recompile" | "dup"
    trace_id: str | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# --- process-global dispatch ----------------------------------------------

_active: set["CompileWatcher"] = set()
_dispatch_lock = threading.Lock()
_monitoring_registered = False
# (level, propagate) of the pxla logger BEFORE the first watcher lowered
# it — module-global (not per-watcher) so overlapping watchers restore
# correctly: with per-watcher state, A-installs/B-installs/A-uninstalls
# (B's handler blocks A's restore, A clears its state)/B-uninstalls
# (B saved nothing) left the logger at DEBUG+no-propagate forever.
_pxla_saved: tuple[int, bool] | None = None
# Thread-local pending (fn, shapes) parsed from the pxla log line, shared
# by every watcher: the log fires immediately before the backend compile
# on the same thread.
_tls = threading.local()


def _on_duration(event: str, duration: float, **kw) -> None:
    if event != "/jax/core/compile/backend_compile_duration":
        return
    pending = getattr(_tls, "pending", None)
    _tls.pending = None
    with _dispatch_lock:
        watchers = list(_active)
    for w in watchers:
        w._observe_compile(pending, duration)


class _PendingHandler(logging.Handler):
    """Parses the pxla "Compiling <fn> ..." line into the thread-local
    pending slot. One instance per installed watcher set is enough, but a
    per-watcher instance keeps uninstall symmetrical and idempotent.

    While a watcher is installed the pxla logger's propagation is OFF
    (the lowered DEBUG level would otherwise print one "Compiling ..."
    line per compile through the root handler) — so this handler must
    FORWARD the records that would have reached the console anyway:
    anything at WARNING or above (real pxla diagnostics — sharding
    warnings, jax_log_compiles-promoted lines) is re-dispatched to the
    root logger's handlers. Only the sub-WARNING noise our level change
    surfaced is dropped, which is exactly the pre-watcher behavior."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILING_RE.match(record.getMessage())
            if m:
                _tls.pending = (m.group(1), m.group(2))
            if record.levelno >= logging.WARNING:
                logging.getLogger().handle(record)
        except Exception:  # a logging handler must never raise
            pass


def _ensure_monitoring() -> None:
    global _monitoring_registered
    with _dispatch_lock:
        if _monitoring_registered:
            return
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
            _monitoring_registered = True
        except Exception:
            # No jax in this process: the watcher stays installable (it
            # just never observes anything) — the obs layer must not
            # require a device runtime (obs/spans discipline).
            pass


class CompileWatcher:
    """Bounded ring of CompileRecords + the steady-recompile gate.

    ``logger`` (a MetricsLogger) gets one ``kind="compile"`` record per
    observed compile; ``on_recompile`` (usually a HealthWatchdog-shaped
    emitter — see ``bind_health``) fires once-latched on the first
    steady-state recompile. All counters are plain ints read without the
    lock for display (GIL-atomic); mutation is locked.
    """

    GATE_MIN_S = 0.05   # a gated recompile must cost at least this

    def __init__(self, logger=None, capacity: int = 256,
                 on_recompile: Callable[[CompileRecord], None] | None = None,
                 gate_min_s: float | None = None):
        self.logger = logger
        self.on_recompile = on_recompile
        self.gate_min_s = (
            self.GATE_MIN_S if gate_min_s is None else gate_min_s
        )
        self.records: deque[CompileRecord] = deque(maxlen=capacity)
        self.compiles = 0
        self.warmup_compiles = 0
        self.shape_variant_compiles = 0
        self.steady_recompiles = 0
        self.dup_compiles = 0
        self.compile_s_total = 0.0
        self.armed = False
        self._sigs: dict[str, set[str]] = {}   # fn -> seen signatures
        self._step = 0
        self._lock = threading.Lock()
        self._latched = False
        self._installed = False
        self._handler: _PendingHandler | None = None

    # --- lifecycle --------------------------------------------------------

    def install(self) -> "CompileWatcher":
        """Start observing this process's compiles. Idempotent."""
        global _pxla_saved
        if self._installed:
            return self
        _ensure_monitoring()
        self._handler = _PendingHandler(level=logging.DEBUG)
        pxla = logging.getLogger(_PXLA_LOGGER)
        with _dispatch_lock:
            if not any(
                isinstance(h, _PendingHandler) for h in pxla.handlers
            ):
                # FIRST watcher: save the logger's pre-watcher state
                # (module-global — the LAST uninstalling watcher restores
                # it, whoever that is), then lower the level so the DEBUG
                # "Compiling ..." line reaches our handler, and stop
                # propagation so it does NOT also print through the root
                # handler this image's absl logging installs (one line of
                # console noise per compile otherwise).
                _pxla_saved = (pxla.level, pxla.propagate)
                if pxla.level == logging.NOTSET or pxla.level > logging.DEBUG:
                    pxla.setLevel(logging.DEBUG)
                pxla.propagate = False
            pxla.addHandler(self._handler)
            _active.add(self)
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Stop observing; the LAST uninstalling watcher restores the
        pxla logger's saved level/propagation."""
        global _pxla_saved
        if not self._installed:
            return
        pxla = logging.getLogger(_PXLA_LOGGER)
        with _dispatch_lock:
            _active.discard(self)
            if self._handler is not None:
                pxla.removeHandler(self._handler)
                self._handler = None
            others = any(
                isinstance(h, _PendingHandler) for h in pxla.handlers
            )
            if not others and _pxla_saved is not None:
                level, propagate = _pxla_saved
                pxla.setLevel(level)
                pxla.propagate = propagate
                _pxla_saved = None
        self._installed = False

    def __enter__(self) -> "CompileWatcher":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # --- feeding ----------------------------------------------------------

    def observe_step(self, step: int) -> None:
        """Stamp the current training step onto subsequent records (the
        trainer calls this once per loop iteration — one int store)."""
        self._step = int(step)

    def _observe_compile(
        self, pending: tuple[str, str] | None, duration: float
    ) -> None:
        fn, shapes = pending if pending else ("?", "")
        trigger, trace_id = self._attribution()
        with self._lock:
            self.compiles += 1
            self.compile_s_total += duration
            seen = self._sigs.get(fn)
            gated = False
            if seen is None:
                phase = "warmup"
                self.warmup_compiles += 1
                self._sigs[fn] = {shapes}
            elif shapes not in seen:
                phase = "recompile"
                self.shape_variant_compiles += 1
                seen.add(shapes)
                gated = self.armed and duration >= self.gate_min_s
                if gated:
                    self.steady_recompiles += 1
            else:
                phase = "dup"
                self.dup_compiles += 1
            rec = CompileRecord(
                fn=fn, shapes=shapes, elapsed_s=round(duration, 6),
                trigger=trigger,
                thread=threading.current_thread().name,
                step=self._step, phase=phase, trace_id=trace_id,
            )
            self.records.append(rec)
            fire = gated and not self._latched
            if fire:
                self._latched = True
        if self.logger is not None:
            extra = {"trace_id": trace_id} if trace_id else {}
            self.logger.log(
                rec.step, kind="compile", fn=fn, shapes=shapes,
                elapsed_ms=round(duration * 1e3, 3), trigger=trigger,
                phase=phase, **extra,
            )
        if fire and self.on_recompile is not None:
            self.on_recompile(rec)

    def arm_steady(self) -> None:
        """Begin steady state: from here on, a seen fn compiling a new
        shape signature at >= ``gate_min_s`` is a gated recompile (the
        trainer arms at its first metric window — the training twin of
        ``ServingStats``'s warmup()/steady split)."""
        self.armed = True

    def rearm(self) -> None:
        """Re-arm the once-latched recompile alert (an operator
        acknowledged the burst; the next NEW burst is a new incident)."""
        with self._lock:
            self._latched = False

    # --- reading ----------------------------------------------------------

    def _attribution(self) -> tuple[str, str | None]:
        """(innermost open span name, trace id) on THIS thread — the
        compile's trigger. Reaches into the tracker's thread-local stack;
        read-only, same-thread, so no lock is needed."""
        try:
            from induction_network_on_fewrel_tpu.obs.spans import get_tracker

            tracker = get_tracker()
            stack = getattr(tracker._tls, "stack", None)
            ctx = tracker.current_trace()
            trigger = stack[-1][0] if stack else "untraced"
            return trigger, (ctx.trace_id if ctx is not None else None)
        except Exception:
            return "untraced", None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "compiles": self.compiles,
                "warmup_compiles": self.warmup_compiles,
                "shape_variant_compiles": self.shape_variant_compiles,
                "steady_recompiles": self.steady_recompiles,
                "dup_compiles": self.dup_compiles,
                "compile_s_total": round(self.compile_s_total, 4),
                "armed": self.armed,
                "records": [r.to_dict() for r in self.records],
            }

def bind_health(watcher: CompileWatcher, health_emit) -> None:
    """Wire the once-latched recompile burst into a HealthWatchdog-style
    emitter: ``health_emit`` is called with an ``obs.health.HealthEvent``.
    Kept as a free function so obs/compile.py has no import-time
    dependency on obs/health.py."""
    from induction_network_on_fewrel_tpu.obs.health import CRITICAL, HealthEvent

    def _on(rec: CompileRecord) -> None:
        health_emit(HealthEvent(
            event="recompile_burst", severity=CRITICAL, step=rec.step,
            message=(
                f"steady-state recompile: {rec.fn} compiled a NEW shape "
                f"signature mid-run ({rec.elapsed_s * 1e3:.1f} ms, "
                f"trigger {rec.trigger})"
            ),
            data={
                "fn": rec.fn, "trigger": rec.trigger,
                "elapsed_ms": round(rec.elapsed_s * 1e3, 3),
                "steady_recompiles": watcher.steady_recompiles,
            },
        ))

    watcher.on_recompile = _on
