"""obs/ — the unified telemetry spine (ISSUE 2).

One shared model for what used to be three fragmented mechanisms:

* ``spans``    — host-side timed regions (ring-buffered, named-scope
                 bridged to XPlane traces).
* ``health``   — run-health watchdog over the metrics stream (NaN/Inf,
                 throughput regression, routing collapse, queue stall).
* ``recorder`` — flight recorder; dumps the last-N window on crash,
                 SIGTERM, or a watchdog trip.
* ``export``   — counter/gauge registry + Prometheus text exposition.

``tools/obs_report.py`` renders the emitted stream (metrics.jsonl +
flight_recorder.json) into a single run report and schema-checks it.
"""

from induction_network_on_fewrel_tpu.obs.export import (
    CounterRegistry,
    get_registry,
    set_registry,
)
from induction_network_on_fewrel_tpu.obs.health import (
    HealthEvent,
    HealthWatchdog,
)
from induction_network_on_fewrel_tpu.obs.recorder import FlightRecorder
from induction_network_on_fewrel_tpu.obs.spans import (
    SpanTracker,
    get_tracker,
    set_tracker,
    span,
)

__all__ = [
    "CounterRegistry",
    "FlightRecorder",
    "HealthEvent",
    "HealthWatchdog",
    "SpanTracker",
    "get_registry",
    "get_tracker",
    "set_registry",
    "set_tracker",
    "span",
]
