"""obs/ — the unified telemetry spine (ISSUE 2).

One shared model for what used to be three fragmented mechanisms:

* ``spans``    — host-side timed regions (ring-buffered, named-scope
                 bridged to XPlane traces) + request-scoped trace
                 contexts with cross-thread propagation (ISSUE 9).
* ``health``   — run-health watchdog over the metrics stream (NaN/Inf,
                 throughput regression, routing collapse, queue stall),
                 plus the per-tenant SLO burn-rate engine with
                 auto-capture diagnostics.
* ``drift``    — online prediction-drift detector over serving verdicts
                 (per-tenant NOTA rate / margin / entropy vs a
                 calibration baseline, re-armed on publish; ISSUE 10).
* ``perf``     — online step-time decomposition (ISSUE 11): per-window
                 data-wait / dispatch / device-sync / checkpoint segments
                 that TILE the measured window, out-of-band windows
                 classified into named causes with auto-captured
                 diagnostics.
* ``compile``  — XLA compile forensics: every backend compile stamped
                 with fn / shape signature / elapsed / trigger, with the
                 training twin of serving's zero-steady-state-recompile
                 gate.
* ``adapt``    — drift-triggered adaptation controller (ISSUE 14): a
                 drift CRITICAL kicks off a supervised, bounded,
                 canary-gated mixture-ramp fine-tune published into the
                 live fleet, with automatic rollback and a retry-budget
                 flap damper.
* ``chaos``    — unified chaos-injection registry (ISSUE 12): named
                 fault points across layers (checkpoint corruption,
                 publish poisoning, serving execute failures) driven by
                 one deterministic ``--chaos`` spec; off = zero-cost.
* ``recorder`` — flight recorder; dumps the last-N window on crash,
                 SIGTERM, or a watchdog trip.
* ``export``   — counter/gauge/histogram registry + Prometheus text
                 exposition (latency histograms carry exemplar
                 trace_ids).

``tools/obs_report.py`` renders the emitted stream (metrics.jsonl +
flight_recorder.json) into a single run report — per-request trace
waterfalls included — and schema-checks it.
"""

from induction_network_on_fewrel_tpu.obs.adapt import AdaptationController
from induction_network_on_fewrel_tpu.obs.chaos import (
    ChaosError,
    ChaosRegistry,
    chaos_active,
    chaos_fire,
    corrupt_step_dir,
)
from induction_network_on_fewrel_tpu.obs.compile import (
    CompileWatcher,
    bind_health,
)
from induction_network_on_fewrel_tpu.obs.export import (
    CounterRegistry,
    Histogram,
    get_registry,
    set_registry,
)
from induction_network_on_fewrel_tpu.obs.drift import DriftDetector
from induction_network_on_fewrel_tpu.obs.perf import PerfObserver
from induction_network_on_fewrel_tpu.obs.health import (
    DiagnosticsCapture,
    HealthEvent,
    HealthWatchdog,
    SLOEngine,
    SLOObjective,
)
from induction_network_on_fewrel_tpu.obs.recorder import FlightRecorder
from induction_network_on_fewrel_tpu.obs.spans import (
    SpanTracker,
    TraceContext,
    TraceSampler,
    get_tracker,
    new_trace_id,
    set_tracker,
    span,
)

__all__ = [
    "AdaptationController",
    "ChaosError",
    "ChaosRegistry",
    "chaos_active",
    "chaos_fire",
    "corrupt_step_dir",
    "CompileWatcher",
    "CounterRegistry",
    "DiagnosticsCapture",
    "DriftDetector",
    "FlightRecorder",
    "HealthEvent",
    "HealthWatchdog",
    "Histogram",
    "PerfObserver",
    "SLOEngine",
    "SLOObjective",
    "SpanTracker",
    "TraceContext",
    "TraceSampler",
    "bind_health",
    "get_registry",
    "get_tracker",
    "new_trace_id",
    "set_registry",
    "set_tracker",
    "span",
]
