"""Unified chaos-injection registry: named fault points across layers
(ISSUE 12 tentpole, piece 1).

``datapipe/faults.py`` gave the FEED path a drillable fault plan; the
rest of the stack grew ad-hoc knobs (``--fault_step``,
``--nan_inject_step``) or nothing at all. This module generalizes the
plan grammar to one registry of NAMED fault points that any layer can
consult, so a single ``--chaos`` spec drives checkpoint corruption,
publish poisoning, and serving execute failures from one place — and the
containment machinery (quarantine, circuit breakers, transactional
rollback) is drilled against the same injector the tests pin.

Grammar (``ChaosRegistry.parse``): comma-separated directives

    POINT@AT[*COUNT][:ARG]

* ``POINT`` — one of ``KNOWN_POINTS`` (a typo raises; a drill that
  silently injects nothing is worse than no drill — the FeedFaults
  rule).
* ``AT``    — 0-based arrival index at that point: the directive fires
  when the point's (ARG-filtered) hit counter reaches AT.
* ``COUNT`` — consecutive fires from AT (default 1).
* ``ARG``   — point-specific filter/payload: the tenant name on serving
  points (only that tenant's arrivals count and fire), the ring kind
  (``ring``/``ring_base``/``ring_delta``) on checkpoint points.

Examples::

    serve.execute_raise@0*3:tenant0   # fail tenant0's first 3 launches
    ckpt.bitflip@1:ring_delta         # corrupt the 2nd delta ring save
    publish.nan_params@0              # NaN-poison the next publish

Determinism: firing is a pure function of the arrival sequence (no
clocks, no RNG on the decision path); corruption offsets derive from a
hash of the corrupted file's name. The SAME spec against the SAME
workload injects the SAME faults.

Off = zero-cost: with nothing installed, ``chaos_fire`` is one module
global load plus an ``is None`` check — no allocation, no locks
(pinned in tests/test_chaos.py).

Every fired directive emits one ``kind="fault"`` record
(``action="inject"``) through the registry's logger; the containment
sites emit their own ``kind="fault"`` records (quarantine / breaker
transition / rollback / degraded verdicts) so tools/obs_report.py's
faults section shows injections and reactions side by side.
"""

from __future__ import annotations

import dataclasses
import threading
from pathlib import Path


class ChaosError(RuntimeError):
    """An injected fault (never raised by real failures — drills and
    tests assert on the type to separate injection from regression)."""


# Fault-point catalog: name -> where it fires / what it models. A point
# not listed here is a parse error (RUNBOOK §17 documents each).
KNOWN_POINTS: dict[str, str] = {
    "ckpt.bitflip": (
        "after a ring-family checkpoint save completes: flip one byte in "
        "the slot's largest data file (silent media corruption). ARG "
        "filters the ring kind (ring/ring_base/ring_delta)."
    ),
    "ckpt.truncate": (
        "after a ring-family checkpoint save completes: truncate the "
        "slot's largest data file to half (torn write / full disk). ARG "
        "filters the ring kind."
    ),
    "ckpt.restore_raise": (
        "at a slot restore attempt: raise ChaosError (a flaky read — "
        "contained exactly like corruption: quarantine + ring-walk "
        "fallback). ARG filters the ring kind."
    ),
    "publish.nan_params": (
        "at publish entry: NaN-poison the params handed to "
        "publish_params — the pre-swap validation gate must refuse and "
        "roll back."
    ),
    "publish.distill_raise": (
        "inside the publish re-distill pass: raise ChaosError mid-"
        "transaction — the rollback must leave every tenant on its old "
        "snapshot."
    ),
    "serve.execute_raise": (
        "in the serving worker before the device program runs: raise "
        "ChaosError — must fail ONLY that batch's futures (typed "
        "ExecuteError) and feed the tenant's circuit breaker. ARG "
        "filters the tenant."
    ),
    "fleet.replica_kill": (
        "at a fleet-router submit arrival: mark the request's owning "
        "replica DEAD (simulated process death, ISSUE 13) — the router "
        "must fail over: degraded NOTA verdicts for the replica's "
        "tenants until re-placement, then normal service from the new "
        "owners. ARG filters the replica id."
    ),
    "adapt.train_raise": (
        "at an adaptation fine-tune launch (obs/adapt.py, ISSUE 14): "
        "raise ChaosError instead of training — the controller must "
        "count the attempt failed, honor its backoff, and exhaust after "
        "the retry budget. ARG filters the tenant."
    ),
    "adapt.canary_fail": (
        "at the adaptation canary gate: force a failed verdict — the "
        "candidate must be DISCARDED (checkpoint cleanup, zero "
        "publishes), never reach the fleet. ARG filters the tenant."
    ),
    "adapt.publish_raise": (
        "at the adaptation publish step, after the canary passed: raise "
        "ChaosError before the fan-out — the controller must count the "
        "attempt failed with the fleet untouched. ARG filters the "
        "tenant."
    ),
    "net.partition": (
        "at a SocketReplica call: raise ConnectionError before any bytes "
        "move (the network between router and replica is gone, ISSUE 15) "
        "— idempotent calls must retry within their bounded budget, the "
        "breaker must count the failures. ARG filters the replica id."
    ),
    "net.drop": (
        "at a SocketReplica call: the request is sent but the response "
        "is 'lost' — the connection is invalidated and ConnectionError "
        "raised (a dropped packet / dying peer mid-response). ARG "
        "filters the replica id."
    ),
    "net.slow": (
        "at a SocketReplica call: sleep before the call proceeds — "
        "injected network latency for latency/SLO drills. ARG is the "
        "PAYLOAD here — the delay in seconds (default 0.05), not a "
        "filter (every arrival counts)."
    ),
    "journal.torn_write": (
        "at a fleet-journal append (fleet/journal.py, ISSUE 15): write "
        "a torn record — the header claims the full payload but only "
        "half reaches disk (a crash mid-write) — and refuse further "
        "appends from this journal object; reopening the directory must "
        "truncate the tear and recover every record before it. ARG "
        "filters the journal op name."
    ),
}


# Points whose ARG is a PAYLOAD the fired site reads (directive.arg),
# not an arrival filter — every arrival at the point counts.
PAYLOAD_ARG_POINTS = frozenset({"net.slow"})


@dataclasses.dataclass
class FaultDirective:
    point: str
    at: int
    count: int = 1
    arg: str = ""
    hits: int = 0       # matching arrivals observed so far
    fired: int = 0      # times this directive actually fired

    def matches(self, ctx_arg: str | None) -> bool:
        return not self.arg or (ctx_arg is not None and self.arg == ctx_arg)


class ChaosRegistry:
    """Parsed fault plan + per-directive arrival counters (thread-safe:
    fault points fire from the saver thread, the serving worker, and the
    main thread)."""

    def __init__(self, directives: list[FaultDirective], logger=None):
        self.directives = directives
        self.logger = logger
        self._lock = threading.Lock()
        self.fired_log: list[dict] = []   # every fired directive (drills)

    @classmethod
    def parse(cls, spec: str | None, logger=None) -> "ChaosRegistry | None":
        """``"serve.execute_raise@0*3:t0,publish.nan_params@0"`` -> a
        registry; empty/None -> None (off). Unknown points and malformed
        directives raise ValueError."""
        if not spec:
            return None
        directives = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            head, _, arg = part.partition(":")
            point, at_sep, at_part = head.partition("@")
            if point not in KNOWN_POINTS:
                raise ValueError(
                    f"unknown chaos point {point!r} "
                    f"(known: {', '.join(sorted(KNOWN_POINTS))})"
                )
            if not at_sep:
                raise ValueError(
                    f"chaos directive {part!r} lacks '@AT' (grammar: "
                    f"POINT@AT[*COUNT][:ARG])"
                )
            at_s, star, count_s = at_part.partition("*")
            at = int(at_s)
            count = int(count_s) if star else 1
            if at < 0 or count < 1:
                raise ValueError(
                    f"chaos directive {part!r}: AT must be >= 0 and "
                    f"COUNT >= 1"
                )
            directives.append(
                FaultDirective(point=point, at=at, count=count, arg=arg)
            )
        if not directives:
            return None
        return cls(directives, logger=logger)

    def fire(self, point: str, **ctx) -> FaultDirective | None:
        """One arrival at ``point``; returns the directive when it fires
        (the site then applies the fault), else None. ``ctx`` carries the
        ARG-filter key (``tenant`` on serving points, ``kind`` on
        checkpoint points) plus telemetry fields."""
        # ARG-filter key by point family: tenant on serving points, ring
        # kind on checkpoint points, replica id on fleet/net points, op
        # name on journal points. On PAYLOAD-ARG points the ARG is data
        # the fired site reads (net.slow's delay), never a filter.
        ctx_arg = (ctx.get("tenant") or ctx.get("kind")
                   or ctx.get("replica") or ctx.get("op"))
        payload_arg = point in PAYLOAD_ARG_POINTS
        fired = None
        with self._lock:
            for d in self.directives:
                if d.point != point or not (
                    payload_arg or d.matches(ctx_arg)
                ):
                    continue
                # EVERY matching directive counts this arrival — AT is
                # "0-based arrival index at the point", and an earlier
                # directive firing must not make later ones miscount.
                hit = d.hits
                d.hits += 1
                if fired is None and d.at <= hit < d.at + d.count:
                    d.fired += 1
                    fired = d   # one fault per arrival (first match wins)
        if fired is not None:
            rec = {
                "action": "inject", "point": point,
                "seq": fired.fired,
                # "step" is the record's positional field below and
                # "kind" is the record's KIND field — the ckpt points'
                # ring-kind context re-keys as ckpt_kind (the quarantine
                # records' spelling).
                **{("ckpt_kind" if k == "kind" else k): v
                   for k, v in ctx.items()
                   if k != "step" and isinstance(v, (int, float, str))},
            }
            self.fired_log.append(rec)
            if self.logger is not None:
                self.logger.log(
                    int(ctx.get("step", 0)), kind="fault", **rec
                )
        return fired

    def install(self) -> "ChaosRegistry":
        install(self)
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None


# Module-global active registry. The OFF path is the whole point of this
# spelling: one global load + `is None`, no call into the registry.
_ACTIVE: ChaosRegistry | None = None


def install(registry: ChaosRegistry | None) -> None:
    global _ACTIVE
    _ACTIVE = registry


def get_chaos() -> ChaosRegistry | None:
    return _ACTIVE


def chaos_fire(point: str, **ctx) -> FaultDirective | None:
    """The fault-point call sites' single entry: returns the fired
    directive or None. With no registry installed this is allocation-free
    (ctx is built lazily by callers passing literals; the kwargs dict is
    the only cost, and hot paths guard with ``chaos_active()``)."""
    reg = _ACTIVE
    if reg is None:
        return None
    return reg.fire(point, **ctx)


def chaos_active() -> bool:
    """Hot-path guard: lets per-request sites skip even the kwargs-dict
    construction when chaos is off."""
    return _ACTIVE is not None


# --- checkpoint corruption helpers -----------------------------------------
#
# Shared by the ckpt.* fault points (train/checkpoint.py fires them on the
# saver thread) and by drills corrupting slots on disk directly (the
# kill -> corrupt -> resume recipe). Deterministic: the byte offset
# derives from the file name, never from an RNG.


def _largest_file(step_dir: Path) -> Path | None:
    files = [p for p in step_dir.rglob("*") if p.is_file()]
    if not files:
        return None
    return max(files, key=lambda p: p.stat().st_size)


def corrupt_step_dir(step_dir: str | Path, mode: str = "bitflip") -> str | None:
    """Corrupt one checkpoint step directory in place: ``bitflip`` flips
    one byte mid-file (silent corruption — the file still parses as far
    as sizes go, only the integrity chain catches it), ``truncate`` cuts
    the largest file to half (torn write — the restore itself fails).
    Returns the corrupted file path (str) or None when the dir holds no
    files. Deterministic per file name."""
    step_dir = Path(step_dir)
    target = _largest_file(step_dir)
    if target is None:
        return None
    size = target.stat().st_size
    if size == 0:
        return None
    if mode == "bitflip":
        # One flipped byte per stripe, offsets jittered by the name
        # hash. A SINGLE flip proved flaky: orbax/tensorstore chunk
        # file names are run-unique, so the name-hash offset lands at
        # a different byte every run — and a 65 KB ocdbt chunk holds
        # framing/slack bytes that never materialize into any verified
        # leaf, so occasionally the corruption was invisible to the
        # integrity chain and the quarantine tests flaked. Striping 8
        # flips across the file keeps the "silent corruption" shape
        # (size unchanged, superficially parseable) while making a
        # miss require EVERY stripe to land in slack.
        jitter = sum(target.name.encode()) * 2654435761
        stripes = min(8, size)
        with open(target, "r+b") as f:
            for i in range(stripes):
                off = (i * size) // stripes + jitter % max(
                    size // stripes, 1
                )
                off %= size
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
    elif mode == "truncate":
        with open(target, "r+b") as f:
            f.truncate(max(size // 2, 1))
    else:
        raise ValueError(
            f"unknown corruption mode {mode!r} (bitflip|truncate)"
        )
    return str(target)
