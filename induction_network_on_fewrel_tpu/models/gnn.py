"""Few-shot graph neural network (gnn).

Toolkit-family sibling model (SURVEY.md §2.1 "Few-shot model" siblings;
Garcia & Bruna, ICLR 2018, "Few-Shot Learning with Graph Neural Networks").
One graph per query: nodes are the N·K support instances plus the query,
node features are the sentence encoding concatenated with the label one-hot
(uniform 1/N for the unlabeled query node). Each GNN block

1. learns an adjacency from pairwise absolute feature differences:
   ``A_ij = softmax_j MLP(|x_i - x_j|)``, and
2. aggregates: ``x ← concat(x, leaky_relu(Dense(A @ x)))`` (dense/residual
   feature growth, as in the original architecture).

A final graph layer maps the query node's aggregated features to N logits.

TPU notes: the graph is tiny (N·K+1 ≤ 51 nodes) but there is one graph per
query — all TQ graphs run as one batched einsum via a leading [B·TQ] axis,
so the adjacency MLP and the aggregation matmuls are large and MXU-shaped.
Static node count per compile; no dynamic graph construction.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel

# Default bound for _AdjacencyMLP.one_hot_max_t, shared with the FLOPs
# model (utils/flops.py) so accounting follows the same form the module
# actually executes at a given T.
ONE_HOT_MAX_T = 64


class _AdjacencyMLP(nn.Module):
    """Pairwise |x_i - x_j| -> scalar edge logit; softmax over neighbors."""

    hidden: int
    compute_dtype: jnp.dtype
    # SIZE GUARD on the one-hot form (ADVICE round 5): its selection
    # constants are [P, T] ≈ O(T³)/2 and the reconstruction constant is
    # [T², P+1] ≈ O(T⁴)/2 floats, with a 2·G·T²·(P+1) reconstruction
    # matmul on top. At zoo shapes (T = N·K+1 ≤ ~26) that is <1 MB of
    # constants and the form wins 1.68x over broadcast; by T=64 the recon
    # constant alone is ~33 MB, and around T≈100 (~200 MB) the
    # reconstruction matmul dominates the MLP it was meant to shrink.
    # Above this bound the module falls back to the broadcast pair form
    # (same params, same math; O(T²·F) memory, no one-hot constants).
    one_hot_max_t: int = ONE_HOT_MAX_T

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: [G, T, F] node features -> [G, T, T] row-stochastic adjacency.
        import numpy as np

        G, T, F = x.shape
        cd = self.compute_dtype

        def mlp(diff):
            h = nn.Dense(self.hidden, dtype=cd, param_dtype=jnp.float32)(diff)
            h = nn.leaky_relu(h)
            h = nn.Dense(self.hidden, dtype=cd, param_dtype=jnp.float32)(h)
            h = nn.leaky_relu(h)
            return nn.Dense(1, dtype=cd, param_dtype=jnp.float32)(h)[..., 0]

        if T > self.one_hot_max_t:
            # Broadcast form: full [G, T, T, F] pair tensor, edge MLP over
            # every ordered pair, diagonal masked directly. More FLOPs on
            # the MLP (T² vs T(T-1)/2 pairs) but no O(T⁴) constants.
            diff = jnp.abs(x[:, :, None, :] - x[:, None, :, :])
            logit = mlp(diff).astype(jnp.float32)       # [G, T, T]
            logit = logit + jnp.asarray(
                np.where(np.eye(T, dtype=bool), -1e9, 0.0), jnp.float32
            )
            return jax.nn.softmax(logit, axis=-1).astype(cd)

        # Pair selection and [T, T] reconstruction both ride ONE-HOT
        # MATMULS, not fancy indexing: a gather's backward is a scatter-add
        # and scatters serialize badly on TPU (measured round 5: the
        # .at[].set variant ran the zoo gnn at 1.8k eps/s vs 3.2k for the
        # original broadcast form; the one-hot form wins 1.68x over the
        # broadcast form at the zoo shape, in-jit A/B 3.66 -> 2.18
        # ms/iter). One-hot rows select exactly (1.0 * value), so the
        # result is bitwise the gathered value, and the backward is
        # another MXU matmul.
        iu, ju = np.triu_indices(T, k=1)               # static [P], P=T(T-1)/2
        P = iu.shape[0]
        sel1 = np.zeros((P, T), np.float32)
        sel1[np.arange(P), iu] = 1.0
        sel2 = np.zeros((P, T), np.float32)
        sel2[np.arange(P), ju] = 1.0
        a = jnp.einsum("pt,gtf->gpf", jnp.asarray(sel1, cd), x)
        b = jnp.einsum("pt,gtf->gpf", jnp.asarray(sel2, cd), x)
        # |x_i - x_j| is SYMMETRIC in (i, j): the edge MLP runs over the
        # strict upper triangle only — T(T-1)/2 unordered pairs instead of
        # the full T^2 pair tensor (the gnn's dominant HBM term, round-4
        # zoo trace) — and each value lands at (i,j) AND (j,i) below.
        diff = jnp.abs(a - b)                          # [G, P, F]
        logit_p = mlp(diff).astype(jnp.float32)        # [G, P]
        # Reconstruction map: (i, j) -> pair slot, diagonal -> the -1e9
        # pad slot so self-edges stay masked (a node aggregates neighbors,
        # not itself; its own features persist via the residual concat).
        pair_id = np.full((T, T), P, np.int32)
        pair_id[iu, ju] = np.arange(P)
        pair_id[ju, iu] = np.arange(P)
        recon = np.zeros((T * T, P + 1), np.float32)
        recon[np.arange(T * T), pair_id.reshape(-1)] = 1.0
        pad = jnp.full((G, 1), -1e9, jnp.float32)
        lp_pad = jnp.concatenate([logit_p, pad], axis=1)   # [G, P+1]
        logit = (lp_pad @ jnp.asarray(recon).T).reshape(G, T, T)
        return jax.nn.softmax(logit, axis=-1).astype(cd)


class GNN(FewShotModel):
    """Per-query support graph with learned adjacency."""

    gnn_dim: int = 64      # features added by each block
    gnn_blocks: int = 2
    adj_hidden: int = 64

    @nn.compact
    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        B, N, K, H = sup_enc.shape
        TQ = qry_enc.shape[1]
        cd = self.compute_dtype
        T = N * K + 1  # nodes per graph

        with jax.named_scope("graph_build"):
            # Label one-hots: support gets its class, the query gets uniform.
            sup_lab = jnp.broadcast_to(
                jnp.eye(N, dtype=cd)[None, :, None, :], (B, N, K, N)
            )
            sup_nodes = jnp.concatenate(
                [sup_enc.astype(cd), sup_lab], axis=-1
            ).reshape(B, 1, N * K, H + N)
            sup_nodes = jnp.broadcast_to(sup_nodes, (B, TQ, N * K, H + N))
            qry_lab = jnp.full((B, TQ, 1, N), 1.0 / N, dtype=cd)
            qry_nodes = jnp.concatenate(
                [qry_enc.astype(cd)[:, :, None, :], qry_lab], axis=-1
            )
            #

            # Query node first (index 0), then supports; one graph per query,
            # flattened to a single big batch of graphs.
            x = jnp.concatenate([qry_nodes, sup_nodes], axis=2)  # [B,TQ,T,F]
            x = x.reshape(B * TQ, T, H + N)

        for i in range(self.gnn_blocks):
            with jax.named_scope(f"gnn_block_{i}"):
                A = _AdjacencyMLP(self.adj_hidden, cd, name=f"adj_{i}")(x)
                agg = jnp.einsum("gij,gjf->gif", A, x)           # [G, T, F]
                new = nn.Dense(self.gnn_dim, dtype=cd, param_dtype=jnp.float32,
                               name=f"gc_{i}")(jnp.concatenate([x, agg], -1))
                x = jnp.concatenate([x, nn.leaky_relu(new)], axis=-1)

        with jax.named_scope("gnn_readout"):
            A = _AdjacencyMLP(self.adj_hidden, cd, name="adj_out")(x)
            agg = jnp.einsum("gij,gjf->gif", A, x)
            logits = nn.Dense(N, dtype=cd, param_dtype=jnp.float32,
                              name="gc_out")(
                jnp.concatenate([x, agg], -1)
            )[:, 0, :]                                           # query node
            logits = logits.reshape(B, TQ, N)

        logits = self.append_nota(logits.astype(jnp.float32))
        return logits.astype(jnp.float32)
