"""Shared base for few-shot episode models.

Every few-shot model in the toolkit family (SURVEY.md §2.1 "Few-shot model":
``models/induction.py`` plus siblings like ``proto.py``) exposes the same
surface: ``__call__(support, query) -> logits [B, TQ, N(+1)]`` where support /
query are dicts of ``{word, pos1, pos2, mask}`` int arrays. The base class
holds the encoder plumbing (token features -> sentence vectors via the shared
embedding + encoder modules) and the NOTA head (a learned none-of-the-above
threshold logit appended as class N — static shapes per compile, SURVEY.md §7
"NOTA"), so each concrete model only implements its episode-level math.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from induction_network_on_fewrel_tpu.models.embedding import is_offset_form


class FewShotModel(nn.Module):
    """Base: encoder plumbing + NOTA logit for episode models.

    Subclasses implement ``__call__(support, query) -> logits`` and call
    ``self.encode`` / ``self.append_nota`` for the shared pieces.
    """

    embedding: nn.Module
    encoder: nn.Module
    nota: bool = False
    # NOTA head style: "scalar" = one learned global threshold logit (the
    # round-1/2 head); "stats" = a learned affine over each query's class-
    # score statistics (max/mean/std) — a query whose best class score is
    # low RELATIVE to its own score distribution is none-of-the-above,
    # which a global constant cannot express. Swept in BASELINE.md.
    nota_head: str = "scalar"
    compute_dtype: jnp.dtype = jnp.float32
    # Episode-head dtype (cfg.head_dtype): distance/metric logits reach
    # magnitudes where bf16's spacing swamps O(1) class-score differences
    # (the round-2 induction finding, measured again on the zoo in round
    # 3: proto_hatt 0.365 -> fixed by f32 heads). f32 default; the knob
    # exists so the bf16-vs-f32 head A/B stays runnable.
    head_dtype: jnp.dtype = jnp.float32

    def encode(self, word, pos1, pos2, mask) -> jnp.ndarray:
        """[..., L] token features -> [..., H] sentence vectors.

        ``pos1``/``pos2`` may arrive one rank BELOW ``word`` — the
        token-cache per-sentence position OFFSETS (full ids are exactly
        ``off + l``; train/token_cache._compact_pos_offsets). They flatten
        to [M] and the Embedding reconstructs the vectors via its windowed
        matmul instead of per-token gathers."""
        lead = word.shape[:-1]
        L = word.shape[-1]
        flat = lambda x: x.reshape(-1, L)
        # Each pos key carries its own form (see is_offset_form): decide
        # per leaf, not from pos1's rank alone.
        word_rank = word.ndim
        fpos = lambda x: (
            x.reshape(-1) if is_offset_form(x, word_rank) else flat(x)
        )
        if getattr(self.encoder, "wants_time_major", False):
            # Transpose the int IDS to time-major BEFORE the gathers, not
            # the gathered embeddings after: [M, L] int32 is ~25x fewer
            # bytes than [M, L, D] bf16, and the gather then lands directly
            # in the [L, M, D] layout the time-major encoder consumes —
            # profiled: the post-gather [3200, 40, 50] layout-copy chains
            # were ~15% of headline device time (tools/profile_headline.py).
            tmj = lambda x: jnp.swapaxes(flat(x), 0, 1)  # noqa: E731
            tpos = lambda x: (
                x.reshape(-1) if is_offset_form(x, word_rank) else tmj(x)
            )
            emb_t = self.embedding(
                tmj(word), tpos(pos1), tpos(pos2), time_major=True
            )
            enc = self.encoder(emb_t, flat(mask), time_major=True)
        else:
            emb = self.embedding(flat(word), fpos(pos1), fpos(pos2))
            enc = self.encoder(emb, flat(mask))
        return enc.reshape(*lead, -1)

    def encode_episode(self, support, query) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(support dict, query dict) -> ([B,N,K,H], [B,TQ,H]) encodings.

        Pre-encoded feature episodes (train/feature_cache.py: frozen-encoder
        training) arrive as plain arrays instead of token dicts and pass
        straight through — the episode-level math is encoder-agnostic.
        """
        if not isinstance(support, dict):
            return jnp.asarray(support), jnp.asarray(query)
        # ONE encoder call over support ⧺ query rows (the encoders are
        # row-independent, so concat-encode-split is exact): halves the
        # kernel/embedding/projection dispatches per step and doubles the
        # row count each MXU op sees — measured win on the fused headline
        # path where per-op overhead is comparable to the op itself.
        L = support["word"].shape[-1]
        if query["word"].shape[-1] != L:
            raise ValueError(
                f"support/query sequence lengths differ: {L} vs "
                f"{query['word'].shape[-1]} — concat-encode would garble rows"
            )
        sup_lead = support["word"].shape[:-1]
        qry_lead = query["word"].shape[:-1]
        word_rank = support["word"].ndim

        def cat(k):
            # Offset-form pos leaves flatten to [M]; token leaves to [M, L].
            f = (
                (lambda x: x.reshape(-1))
                if is_offset_form(support[k], word_rank)
                else (lambda x: x.reshape(-1, L))
            )
            return jnp.concatenate([f(support[k]), f(query[k])], axis=0)
        enc = self.encode(cat("word"), cat("pos1"), cat("pos2"), cat("mask"))
        ns = int(np.prod(sup_lead)) if sup_lead else 1
        sup_enc = enc[:ns].reshape(*sup_lead, -1)
        qry_enc = enc[ns:].reshape(*qry_lead, -1)
        return sup_enc, qry_enc

    def append_nota(self, logits: jnp.ndarray) -> jnp.ndarray:
        """Append the learned NOTA threshold logit as class N (if enabled).

        Setup-style models create the param via ``make_nota_param()`` in
        ``setup``; ``@nn.compact`` models just call this — the param is
        created lazily here (attribute assignment is illegal in compact).
        """
        if not self.nota:
            return logits
        B, TQ, _ = logits.shape
        if self.nota_head == "stats":
            # Per-query threshold from the class-score distribution. The
            # f32 cast matters: std of near-equal bf16 logits quantizes to
            # zero and the head loses its discriminative feature.
            lf = logits.astype(jnp.float32)
            feats = jnp.stack(
                [lf.max(-1), lf.mean(-1), lf.std(-1)], axis=-1
            )  # [B, TQ, 3]
            w = getattr(self, "nota_stats_w", None)
            if w is None:  # compact models create lazily; setup-style via
                w = self.param("nota_stats_w", nn.initializers.zeros, (3,))
                b = self.param("nota_stats_b", nn.initializers.zeros, (1,))
            else:          # ...make_nota_param (attr assignment is illegal
                b = self.nota_stats_b  # in compact, param() in setup-less)
            na = (feats @ w + b).astype(logits.dtype)[..., None]
            return jnp.concatenate([logits, na], axis=-1)
        nota_logit = getattr(self, "nota_logit", None)
        if nota_logit is None:
            nota_logit = self.param("nota_logit", nn.initializers.zeros, (1,))
        na = jnp.broadcast_to(nota_logit.astype(logits.dtype), (B, TQ, 1))
        return jnp.concatenate([logits, na], axis=-1)

    def make_nota_param(self):
        if not self.nota:
            return
        if self.nota_head == "stats":
            self.nota_stats_w = self.param(
                "nota_stats_w", nn.initializers.zeros, (3,)
            )
            self.nota_stats_b = self.param(
                "nota_stats_b", nn.initializers.zeros, (1,)
            )
        else:
            self.nota_logit = self.param("nota_logit", nn.initializers.zeros, (1,))
