"""Prototypical network — the classic toolkit sibling of the induction model.

Toolkit-family repos ship an induction model alongside Snell et al. (2017)
prototypical networks (SURVEY.md §2.1 "Few-shot model": ``models/induction.py``
(+ siblings like ``proto.py`` in toolkit forks)). Episode math:

* prototype per class = mean of the K support encodings:  p_i = mean_j e_ij
* query logit for class i = similarity(q, p_i):
    - ``euclid`` (toolkit default): -‖q - p_i‖²
    - ``dot``: q · p_i

TPU notes: the distance expands to one batched matmul plus two squared-norm
reductions (‖q-p‖² = ‖q‖² - 2 q·p + ‖p‖²), so the hot op is a single MXU
contraction over the hidden axis; everything else fuses into it.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel


class PrototypicalNetwork(FewShotModel):
    metric: str = "euclid"  # euclid | dot

    def setup(self):
        self.make_nota_param()

    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        with jax.named_scope("proto"):
            # head_dtype (f32 default) scoring: -||q - p||^2 logits reach
            # magnitudes of hundreds at H=230, where bf16's spacing is ~2.0
            # — class-score differences of O(1) quantize away and training
            # stalls (the round-2 induction-head noise floor). The encoder
            # stays in compute_dtype; this einsum pair is negligible.
            qry_f = qry_enc.astype(self.head_dtype)
            proto = jnp.mean(sup_enc.astype(self.head_dtype), axis=2)
            dots = jnp.einsum("bqh,bnh->bqn", qry_f, proto)
            if self.metric == "dot":
                logits = dots
            elif self.metric == "euclid":
                q2 = jnp.sum(jnp.square(qry_f), axis=-1)         # [B, TQ]
                p2 = jnp.sum(jnp.square(proto), axis=-1)         # [B, N]
                logits = 2.0 * dots - q2[..., None] - p2[:, None, :]
            else:
                raise ValueError(f"unknown proto metric {self.metric!r}")
        logits = self.append_nota(logits)
        return logits.astype(jnp.float32)
