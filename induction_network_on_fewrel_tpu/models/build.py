"""Model factory: ExperimentConfig -> InductionNetwork instance."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.models.embedding import Embedding
from induction_network_on_fewrel_tpu.models.encoders import (
    BiLSTMSelfAttnEncoder,
    CNNEncoder,
)
from induction_network_on_fewrel_tpu.models.gnn import GNN
from induction_network_on_fewrel_tpu.models.induction import InductionNetwork
from induction_network_on_fewrel_tpu.models.proto import PrototypicalNetwork
from induction_network_on_fewrel_tpu.models.proto_hatt import ProtoHATT
from induction_network_on_fewrel_tpu.models.siamese import SiameseNetwork
from induction_network_on_fewrel_tpu.models.snail import SNAIL

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def resolve_runtime_backends(cfg: ExperimentConfig) -> dict:
    """ONE home for the TPU-aware resolution of the encoder's runtime
    backend knobs (cli.py help text points here instead of restating it).
    None of these are architecture fields: params, outputs, and
    checkpoints are identical across every setting.

    ==================  =========  ========================================
    knob                default    resolution
    ==================  =========  ========================================
    --lstm_backend      auto       pallas on a real TPU backend, scan
                                   elsewhere (the CPU interpreter is for
                                   tests, not throughput)
    --remat_attn        on         with the resolved attention path "xla"
                                   on a TPU backend, the backward runs
                                   through the one-pass kernel
                                   ("xla_remat"); elsewhere the two-pass
                                   backward stays (the compiled kernel
                                   needs a chip)
    --lstm_cs_window    8          engages on the kernel (pallas/
                                   interpret) lstm paths only — the scan
                                   backend keeps no residuals; 0 = the
                                   round-6 full-residual A/B twin
    --lstm_residuals    auto       follow compute_dtype (bf16 on the
                                   flagship) on the kernel paths; "f32"/
                                   "bf16" force the storage dtype, carries
                                   stay f32 either way
    --grad_bucketing    auto       "on" on a real TPU backend (the
                                   DDP-style bucketed gradient psums,
                                   parallel/grad_buckets), "off"
                                   elsewhere — the CPU ledger/tests force
                                   "on" explicitly; mesh-shape refusals
                                   (tp/sp/pp/ep, MoE) stay in
                                   grad_buckets_for, which takes the mesh
    --async_collectives auto       "on" on a real TPU backend — the
                                   latency-hiding scheduler's async
                                   start/done collective spelling, the
                                   runtime half of the dataflow windows
                                   the comms ledger measures; "off"
                                   elsewhere (CPU emits sync collectives;
                                   the ledger's windows are the
                                   projection, wall-clock A/B queued in
                                   BASELINE round 21)
    ==================  =========  ========================================

    ``--attn_backend auto`` resolves to the two-pass XLA form on every
    backend (the fused online-softmax kernel measured 0.97-0.98x of XLA
    on this chip, BASELINE.md round 5; it stays selectable for A/Bs on
    other silicon).

    Returns {lstm_backend, attn_backend, lstm_cs_window,
    lstm_residual_dtype, grad_bucketing, grad_bucket_count,
    async_collectives} with every "auto" resolved. grad_bucketing here is
    the BACKEND half only ("on"/"off" for this process's default
    backend); the mesh-shape half lives in
    parallel/grad_buckets.grad_buckets_for, which composes both.
    """
    import jax

    on_tpu = jax.default_backend() == "tpu"
    backend = cfg.lstm_backend
    if backend == "auto":
        backend = "pallas" if on_tpu else "scan"
    attn = getattr(cfg, "attn_backend", "auto")
    if attn == "auto":
        attn = "xla"
    if attn == "xla" and getattr(cfg, "remat_attn", False) and on_tpu:
        attn = "xla_remat"
    kernel_lstm = backend in ("pallas", "interpret")
    # Validate the RAW knob even where scan makes it inert — a negative
    # window must fail here with a named error on every backend, not as
    # an opaque shape error deep in pallas tracing on the TPU resolve.
    raw_window = int(getattr(cfg, "lstm_cs_window", 0))
    if raw_window < 0:
        raise ValueError(
            f"lstm_cs_window must be >= 0, got {raw_window} "
            "(0 = full residual streams, W > 0 = windowed-cs remat)"
        )
    cs_window = raw_window if kernel_lstm else 0
    residuals = getattr(cfg, "lstm_residuals", "auto")
    if residuals not in ("auto", "f32", "bf16"):
        raise ValueError(
            f"unknown lstm_residuals {residuals!r} (auto | f32 | bf16)"
        )
    residual_dtype = (
        {"f32": jnp.float32, "bf16": jnp.bfloat16}.get(residuals)
        if kernel_lstm else None
    )  # None = follow the compute dtype
    bucketing = getattr(cfg, "grad_bucketing", "auto")
    if bucketing not in ("auto", "on", "off"):
        raise ValueError(
            f"unknown grad_bucketing {bucketing!r} (auto | on | off)"
        )
    if bucketing == "auto":
        # TPU + lazy-embed only: the dense word-table arms keep the
        # compact-demb spelling (grad_buckets_for docstring has the
        # full rationale — the two are mutually exclusive).
        lazy = getattr(cfg, "embed_optimizer", "shared") == "lazy"
        bucketing = "on" if (on_tpu and lazy) else "off"
    async_coll = getattr(cfg, "async_collectives", "auto")
    if async_coll not in ("auto", "on", "off"):
        raise ValueError(
            f"unknown async_collectives {async_coll!r} (auto | on | off)"
        )
    if async_coll == "auto":
        async_coll = "on" if on_tpu else "off"
    return {
        "lstm_backend": backend,
        "attn_backend": attn,
        "lstm_cs_window": cs_window,
        "lstm_residual_dtype": residual_dtype,
        "grad_bucketing": bucketing,
        "grad_bucket_count": max(
            1, int(getattr(cfg, "grad_bucket_count", 4))
        ),
        "async_collectives": async_coll,
    }


def build_model(
    cfg: ExperimentConfig,
    glove_init: np.ndarray | None = None,
    attn_impl=None,
    pipeline_impl=None,
    demb_impl=None,
) -> InductionNetwork:
    """``attn_impl``: override the transformer encoder's attention — e.g.
    ``parallel.ring.make_ring_attention(mesh)`` for sp-sharded long-context
    runs. ``pipeline_impl``: executor for the layer-stacked transformer —
    ``parallel.pipeline.make_gpipe(mesh)`` for pp-sharded runs (implies the
    stacked parameter layout). Both ignored by the other encoders.
    ``demb_impl``: mesh-aware word-table lookup for dp-sharded runs
    (``parallel.sharding.demb_impl_for``) — shard-local demb backward with
    a compact [U, D] all-reduce instead of the replicated [L, M, word_dim]
    embedding cotangent; ignored by the BERT paths (their own table)."""
    dtype = _DTYPES[cfg.compute_dtype]
    if cfg.moe_experts > 0 and cfg.encoder != "transformer":
        raise ValueError(
            "--moe_experts requires --encoder transformer (the MoE FFN "
            "lives in the transformer blocks; other encoders have no MoE "
            "path and would silently train dense)"
        )
    if cfg.moe_experts > 0 and cfg.tfm_layers < cfg.moe_every:
        raise ValueError(
            f"--moe_experts with --moe_every {cfg.moe_every} > --tfm_layers "
            f"{cfg.tfm_layers} would create zero expert layers (block i is "
            "MoE when (i+1) % moe_every == 0) — the model would silently "
            "train dense"
        )
    use_stacked = cfg.tfm_stacked or pipeline_impl is not None
    if use_stacked:
        if cfg.encoder != "transformer":
            raise ValueError(
                "--pp / tfm_stacked requires --encoder transformer "
                "(pipeline stages are transformer layers)"
            )
        if cfg.moe_experts > 0 or attn_impl is not None:
            raise ValueError(
                "the layer-stacked (pipeline) transformer does not compose "
                "with MoE or ring attention yet; drop --moe_experts/--sp"
            )
    if cfg.model == "pair":
        # BERT-PAIR consumes raw token ids pairwise — it owns its backbone
        # and bypasses the embedding/encoder split entirely.
        if cfg.encoder != "bert":
            raise ValueError(
                "--model pair requires --encoder bert "
                "(token-level sequence-pair input)"
            )
        from induction_network_on_fewrel_tpu.models.pair import PairModel

        if cfg.nota_head != "scalar":
            # PairModel scores pairs through its own backbone head and
            # only implements the scalar NOTA logit; silently recording
            # nota_head='stats' in the checkpoint while saving scalar
            # params would corrupt the architecture contract.
            raise ValueError(
                "--model pair supports only --nota_head scalar"
            )
        return PairModel(
            vocab_size=cfg.bert_vocab_size,
            num_layers=cfg.bert_layers,
            hidden_size=cfg.bert_hidden,
            num_heads=cfg.bert_heads,
            intermediate_size=cfg.bert_intermediate,
            frozen=cfg.bert_frozen,
            remat=cfg.bert_remat,
            nota=cfg.na_rate > 0,
            compute_dtype=dtype,
        )
    if cfg.encoder == "bert":
        try:
            from induction_network_on_fewrel_tpu.models.bert import (
                BertEmbeddingPassthrough,
                BertEncoder,
            )
        except ImportError as e:
            raise NotImplementedError(
                "bert encoder module not available yet"
            ) from e

        embedding = BertEmbeddingPassthrough()
        encoder = BertEncoder(
            num_layers=cfg.bert_layers,
            hidden_size=cfg.bert_hidden,
            num_heads=cfg.bert_heads,
            intermediate_size=cfg.bert_intermediate,
            vocab_size=cfg.bert_vocab_size,
            max_length=cfg.max_length,
            frozen=cfg.bert_frozen,
            remat=cfg.bert_remat,
            compute_dtype=dtype,
        )
    else:
        embedding = Embedding(
            vocab_size=cfg.vocab_size,
            word_dim=cfg.word_dim,
            pos_dim=cfg.pos_dim,
            max_length=cfg.max_length,
            glove_init=glove_init,
            compute_dtype=dtype,
            freeze_word_table=cfg.embed_optimizer == "frozen",
            demb_impl=demb_impl,
        )
        if cfg.encoder == "cnn":
            encoder = CNNEncoder(hidden_size=cfg.hidden_size, compute_dtype=dtype)
        elif cfg.encoder == "transformer" and use_stacked:
            from induction_network_on_fewrel_tpu.models.pipeline_transformer import (
                PipelinedTransformerEncoder,
            )

            encoder = PipelinedTransformerEncoder(
                num_layers=cfg.tfm_layers, d_model=cfg.tfm_model,
                num_heads=cfg.tfm_heads, d_ff=cfg.tfm_ff,
                max_length=cfg.max_length, compute_dtype=dtype,
                pipeline_impl=pipeline_impl,
            )
        elif cfg.encoder == "transformer":
            from induction_network_on_fewrel_tpu.models.transformer import (
                TransformerEncoder,
            )

            encoder = TransformerEncoder(
                num_layers=cfg.tfm_layers, d_model=cfg.tfm_model,
                num_heads=cfg.tfm_heads, d_ff=cfg.tfm_ff,
                max_length=cfg.max_length, compute_dtype=dtype,
                attn_impl=attn_impl,
                num_experts=cfg.moe_experts, moe_top_k=cfg.moe_top_k,
                moe_capacity=cfg.moe_capacity, moe_every=cfg.moe_every,
                moe_group_size=cfg.moe_group_size,
            )
        elif cfg.encoder == "bilstm":
            # Rationale for each resolution lives in ONE place:
            # resolve_runtime_backends' table (and BASELINE.md round 5 for
            # the attn kernel rejection). Every knob here is runtime-only.
            r = resolve_runtime_backends(cfg)
            encoder = BiLSTMSelfAttnEncoder(
                lstm_hidden=cfg.lstm_hidden, att_dim=cfg.att_dim,
                lstm_backend=r["lstm_backend"],
                attn_backend=r["attn_backend"],
                lstm_cs_window=r["lstm_cs_window"],
                lstm_residual_dtype=r["lstm_residual_dtype"],
                compute_dtype=dtype,
            )
        else:
            raise ValueError(f"unknown encoder {cfg.encoder!r}")

    if cfg.model == "induction":
        return InductionNetwork(
            embedding=embedding,
            encoder=encoder,
            induction_dim=cfg.induction_dim,
            routing_iters=cfg.routing_iters,
            ntn_slices=cfg.ntn_slices,
            nota=cfg.na_rate > 0,
            nota_head=cfg.nota_head,
            compute_dtype=dtype,
            head_dtype=_DTYPES[cfg.head_dtype],
        )
    common = dict(
        embedding=embedding,
        encoder=encoder,
        nota=cfg.na_rate > 0,
        nota_head=cfg.nota_head,
        compute_dtype=dtype,
        head_dtype=_DTYPES[cfg.head_dtype],
    )
    if cfg.model == "proto":
        if cfg.proto_metric not in ("euclid", "dot"):
            raise ValueError(f"unknown proto metric {cfg.proto_metric!r}")
        return PrototypicalNetwork(metric=cfg.proto_metric, **common)
    if cfg.model == "proto_hatt":
        return ProtoHATT(k=cfg.k, **common)
    if cfg.model == "siamese":
        return SiameseNetwork(**common)
    if cfg.model in ("gnn", "snail", "metanet"):
        # These models bake N into parameter shapes (gnn/snail: label
        # one-hot width and Dense(N) readout; metanet: the slow head
        # W_slow[H, N]), so unlike induction/proto the train-time and
        # eval-time N must agree.
        if cfg.train_n != cfg.n:
            raise ValueError(
                f"model {cfg.model!r} ties parameter shapes to N; "
                f"--trainN ({cfg.train_n}) must equal --N ({cfg.n})"
            )
        if cfg.model == "gnn":
            return GNN(gnn_dim=cfg.gnn_dim, gnn_blocks=cfg.gnn_blocks,
                       adj_hidden=cfg.gnn_adj_hidden, **common)
        if cfg.model == "snail":
            return SNAIL(tc_filters=cfg.snail_tc_filters, **common)
        from induction_network_on_fewrel_tpu.models.metanet import MetaNet

        return MetaNet(**common)
    raise ValueError(f"unknown model {cfg.model!r}")


def encoder_output_dim(cfg: ExperimentConfig) -> int:
    """Sentence-vector width produced by cfg's encoder (discriminator input)."""
    if cfg.encoder == "bert":
        return cfg.bert_hidden
    if cfg.encoder == "bilstm":
        return 2 * cfg.lstm_hidden
    if cfg.encoder == "transformer":
        return cfg.tfm_model
    return cfg.hidden_size  # cnn


def batch_to_model_inputs(batch) -> tuple[dict, dict, jnp.ndarray]:
    """EpisodeBatch (numpy) -> (support dict, query dict, label) for the model.

    FeatureEpisodeBatch (train/feature_cache.py) passes its pre-encoded
    support/query arrays through unchanged — the models' ``encode_episode``
    accepts either form.
    """
    if hasattr(batch, "support_idx"):  # IndexEpisodeBatch (cached path)
        return batch.support_idx, batch.query_idx, batch.label
    if hasattr(batch, "support"):  # FeatureEpisodeBatch
        return batch.support, batch.query, batch.label
    # Wire-dtype narrowing: pos offsets live in [0, 2·max_length) and the
    # mask in {0, 1}, so they cross host->device as int16/int8 — on this
    # TPU that boundary is a network tunnel and batch bytes are ~45% of the
    # per-step payload. Device-side consumers are gathers and `> 0`
    # comparisons, which take any int dtype; word ids stay int32 (GloVe
    # vocab is 400k > int16).
    support = {
        "word": batch.support_word,
        "pos1": batch.support_pos1.astype(np.int16),
        "pos2": batch.support_pos2.astype(np.int16),
        "mask": batch.support_mask.astype(np.int8),
    }
    query = {
        "word": batch.query_word,
        "pos1": batch.query_pos1.astype(np.int16),
        "pos2": batch.query_pos2.astype(np.int16),
        "mask": batch.query_mask.astype(np.int8),
    }
    return support, query, batch.label
