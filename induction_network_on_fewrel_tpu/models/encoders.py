"""Sentence encoders: CNN and BiLSTM + structured self-attention.

Contract (SURVEY.md §1 L4): ``(embedded tokens [M, L, D], mask [M, L]) ->
sentence vector [M, H]``. The leading axis M flattens (batch, N, K|Q) — the
encoders are oblivious to episode structure, which keeps their matmuls large
and MXU-shaped.

* CNN (SURVEY.md §2.1): Conv1d(hidden filters, window 3) + ReLU + masked
  max-pool over time — thunlp defaults, hidden=230.
* BiLSTM + self-attention (paper §3.1): bidirectional LSTM, then structured
  self-attention ``a = softmax(w2 · tanh(W1 · Hᵀ))``, sentence vector
  ``e = Σ aₜ hₜ``. TPU decomposition (ops/lstm.py): the input projection is
  hoisted out of the recurrence into one [M·L, D] x [D, 4u] MXU matmul; only
  the true recurrence runs per-step — as a ``lax.scan`` or as the fused
  Pallas kernel that keeps h/c in VMEM for all L steps (``lstm_backend``).
  Both directions share cell weights and run stacked along the batch axis,
  so the per-step matmul is twice as tall. The two backends share the same
  parameters: checkpoints are interchangeable and equality is testable.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.ops import masked_max, masked_softmax
from induction_network_on_fewrel_tpu.ops.lstm import lstm_recurrence


class CNNEncoder(nn.Module):
    hidden_size: int = 230
    window: int = 3
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(
            self.hidden_size,
            kernel_size=(self.window,),
            padding="SAME",
            dtype=self.compute_dtype,
            param_dtype=jnp.float32,
        )(emb)
        x = nn.relu(x)
        return masked_max(x, mask[..., None], axis=-2).astype(self.compute_dtype)

    @property
    def output_dim(self) -> int:
        return self.hidden_size


class BiLSTMSelfAttnEncoder(nn.Module):
    lstm_hidden: int = 128   # per direction; output dim is 2*lstm_hidden
    att_dim: int = 64
    lstm_backend: str = "scan"  # scan | pallas | interpret (ops/lstm.py)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        M, L, D = emb.shape
        u = self.lstm_hidden
        emb = emb.astype(self.compute_dtype)

        # Stack forward and time-reversed sequences along the batch axis:
        # same cell weights serve both directions, and every matmul below is
        # twice as tall — friendlier to the MXU than two half-size passes.
        rev = jnp.flip(emb, axis=1)
        both = jnp.concatenate([emb, rev], axis=0)  # [2M, L, D]

        # Gate order [i, f, g, o] (matches torch.nn.LSTM; golden-tested).
        w_ih = self.param("w_ih", nn.initializers.lecun_normal(), (D, 4 * u))
        w_hh = self.param("w_hh", nn.initializers.orthogonal(), (u, 4 * u))
        # Forget-gate bias starts at 1 so early training doesn't flush the
        # cell state (standard LSTM practice).
        b = self.param(
            "bias",
            lambda key, shape: jnp.zeros(shape).at[u : 2 * u].set(1.0),
            (4 * u,),
        )
        # Sequential-free input projection: one big MXU matmul over all
        # timesteps; only the recurrence below runs per-step.
        xg = both @ w_ih.astype(self.compute_dtype) + b.astype(self.compute_dtype)
        # [2M, L, u] in xg's dtype (pallas; f32 internal recurrence) or f32
        # (scan) — consumers see compute_dtype either way.
        hs = lstm_recurrence(xg, w_hh, backend=self.lstm_backend)
        hs = hs.astype(self.compute_dtype)
        h_fwd, h_bwd = hs[:M], jnp.flip(hs[M:], axis=1)
        H = jnp.concatenate([h_fwd, h_bwd], axis=-1)   # [M, L, 2u]

        # Structured self-attention (Lin et al. 2017 form used by the paper):
        # scores = w2 · tanh(W1 hᵀ), masked softmax over L.
        proj = nn.Dense(
            self.att_dim, use_bias=False, dtype=self.compute_dtype, param_dtype=jnp.float32
        )(H)
        scores = nn.Dense(
            1, use_bias=False, dtype=self.compute_dtype, param_dtype=jnp.float32
        )(jnp.tanh(proj))[..., 0]                      # [M, L]
        att = masked_softmax(scores.astype(jnp.float32), mask, axis=-1)
        return jnp.einsum("ml,mlh->mh", att.astype(self.compute_dtype), H)

    @property
    def output_dim(self) -> int:
        return 2 * self.lstm_hidden
