"""Sentence encoders: CNN and BiLSTM + structured self-attention.

Contract (SURVEY.md §1 L4): ``(embedded tokens [M, L, D], mask [M, L]) ->
sentence vector [M, H]``. The leading axis M flattens (batch, N, K|Q) — the
encoders are oblivious to episode structure, which keeps their matmuls large
and MXU-shaped.

* CNN (SURVEY.md §2.1): Conv1d(hidden filters, window 3) + ReLU + masked
  max-pool over time — thunlp defaults, hidden=230.
* BiLSTM + self-attention (paper §3.1): bidirectional LSTM, then structured
  self-attention ``a = softmax(w2 · tanh(W1 · Hᵀ))``, sentence vector
  ``e = Σ aₜ hₜ``. The scan serializes over L (≤128 tokens, SURVEY.md §7
  "hard parts") but each scan step is a fused 4-gate matmul on the MXU; both
  directions run in a single scan over a stacked/flipped copy so the weights
  are shared-shape and the kernel count halves.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.ops import masked_max, masked_softmax


class CNNEncoder(nn.Module):
    hidden_size: int = 230
    window: int = 3
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(
            self.hidden_size,
            kernel_size=(self.window,),
            padding="SAME",
            dtype=self.compute_dtype,
            param_dtype=jnp.float32,
        )(emb)
        x = nn.relu(x)
        return masked_max(x, mask[..., None], axis=-2).astype(self.compute_dtype)

    @property
    def output_dim(self) -> int:
        return self.hidden_size


class BiLSTMSelfAttnEncoder(nn.Module):
    lstm_hidden: int = 128   # per direction; output dim is 2*lstm_hidden
    att_dim: int = 64
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        M, L, D = emb.shape
        emb = emb.astype(self.compute_dtype)

        # Stack forward and time-reversed sequences along the batch axis and
        # run ONE scan: same cell weights serve both directions, and the
        # per-step gate matmul is twice as tall — friendlier to the MXU than
        # two half-size scans.
        rev = jnp.flip(emb, axis=1)
        both = jnp.concatenate([emb, rev], axis=0)  # [2M, L, D]
        cell = nn.OptimizedLSTMCell(
            self.lstm_hidden, dtype=self.compute_dtype, param_dtype=jnp.float32
        )
        # nn.RNN is flax's lifted lax.scan over the time axis.
        hs = nn.RNN(cell)(both)                        # [2M, L, u]
        h_fwd, h_bwd = hs[:M], jnp.flip(hs[M:], axis=1)
        H = jnp.concatenate([h_fwd, h_bwd], axis=-1)   # [M, L, 2u]

        # Structured self-attention (Lin et al. 2017 form used by the paper):
        # scores = w2 · tanh(W1 hᵀ), masked softmax over L.
        proj = nn.Dense(
            self.att_dim, use_bias=False, dtype=self.compute_dtype, param_dtype=jnp.float32
        )(H)
        scores = nn.Dense(
            1, use_bias=False, dtype=self.compute_dtype, param_dtype=jnp.float32
        )(jnp.tanh(proj))[..., 0]                      # [M, L]
        att = masked_softmax(scores.astype(jnp.float32), mask, axis=-1)
        return jnp.einsum("ml,mlh->mh", att.astype(self.compute_dtype), H)

    @property
    def output_dim(self) -> int:
        return 2 * self.lstm_hidden
