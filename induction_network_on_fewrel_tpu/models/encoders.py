"""Sentence encoders: CNN and BiLSTM + structured self-attention.

Contract (SURVEY.md §1 L4): ``(embedded tokens [M, L, D], mask [M, L]) ->
sentence vector [M, H]``. The leading axis M flattens (batch, N, K|Q) — the
encoders are oblivious to episode structure, which keeps their matmuls large
and MXU-shaped.

* CNN (SURVEY.md §2.1): Conv1d(hidden filters, window 3) + ReLU + masked
  max-pool over time — thunlp defaults, hidden=230.
* BiLSTM + self-attention (paper §3.1): bidirectional LSTM, then structured
  self-attention ``a = softmax(w2 · tanh(W1 · Hᵀ))``, sentence vector
  ``e = Σ aₜ hₜ``. TPU decomposition (ops/lstm.py): the whole body runs
  TIME-MAJOR — one cheap [M, L, D] -> [L, M, D] transpose of the 60-wide
  embedding, then the input projection as ONE tall [L·M, D] x [D, 8u] MXU
  matmul against the direction-concatenated weights, the recurrence via
  ``bilstm_recurrence_tm`` (the reverse direction's time flip and the
  direction select live in the Pallas kernel's BlockSpec index maps — no
  stack/flip/transpose of the 512-wide gates ever materializes), and the
  attention directly over the natural-time [L, M, 2u] hidden states. Only
  the true recurrence runs per-step — as a ``lax.scan`` or as the fused
  Pallas kernel that keeps h/c in VMEM for all L steps (``lstm_backend``).
  The two directions have INDEPENDENT weights (matching torch
  ``nn.LSTM(bidirectional=True)``'s separate ``*_reverse`` tensors — params
  carry a leading direction axis [2, ...]) and still run in one fused
  dispatch. The backends share the same parameters: checkpoints are
  interchangeable and equality is testable.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.ops import masked_max, masked_softmax
from induction_network_on_fewrel_tpu.ops.attn import masked_selfattn_tm
from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm


def _per_direction(init):
    """Lift a 1-direction initializer to a leading [2, ...] direction axis.

    Splitting the key per direction keeps each direction's init distribution
    identical to a standalone LSTM's (a plain lecun/orthogonal over the
    stacked shape would compute fan/orthogonality over the wrong axes).
    """

    def f(key, shape, dtype=jnp.float32):
        keys = jax.random.split(key, shape[0])
        return jnp.stack([init(k, shape[1:], dtype) for k in keys])

    return f


class CNNEncoder(nn.Module):
    hidden_size: int = 230
    window: int = 3
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(
            self.hidden_size,
            kernel_size=(self.window,),
            padding="SAME",
            dtype=self.compute_dtype,
            param_dtype=jnp.float32,
        )(emb)
        x = nn.relu(x)
        return masked_max(x, mask[..., None], axis=-2).astype(self.compute_dtype)

    @property
    def output_dim(self) -> int:
        return self.hidden_size


class BiLSTMSelfAttnEncoder(nn.Module):
    lstm_hidden: int = 128   # per direction; output dim is 2*lstm_hidden
    att_dim: int = 64
    lstm_backend: str = "scan"  # scan | pallas | interpret (ops/lstm.py)
    # Attention impl (ops/attn.py): "xla" = two-pass (projection pass +
    # weighted-sum pass; each reads H from HBM), "pallas"/"interpret" =
    # fused one-pass online-softmax kernel (H read once per direction of
    # the pass; the round-5 roofline puts the two-pass attention at ~40%
    # of the flagship step's HBM bytes), "xla_remat"/"xla_remat_interpret"
    # = recompute-in-backward hybrid (--remat_attn): the two-pass XLA
    # forward saving only [M] softmax stats, the one-pass kernel backward
    # rebuilding the tanh projection + attention weights from H in VMEM
    # (attn-bwd 213 -> 134 MB/step at the flagship shape, ROOFLINE_r06).
    # Same params every way — checkpoints interchange across backends.
    attn_backend: str = "xla"
    # Windowed-cs remat window for the fused kernel backward (ops/lstm.py
    # round 8): W > 0 = save one (h, c) checkpoint pair per W natural-time
    # steps and recompute in-window states in VMEM; 0 = the round-6 full
    # hs/cs residual streams. Kernel (pallas/interpret) paths only — scan
    # keeps no residuals. Pure runtime knob, like the backends above.
    lstm_cs_window: int = 0
    # Storage dtype of those residuals/checkpoints (None = follow the
    # embedding dtype); VMEM carries and the recompute stay f32.
    lstm_residual_dtype: jnp.dtype | None = None
    compute_dtype: jnp.dtype = jnp.float32
    # Callers that can supply embeddings already time-major ([L, M, D])
    # should: FewShotModel.encode then transposes the int IDS before the
    # gathers instead of the gathered bf16 embeddings after (~25x fewer
    # transposed bytes, and the layout-copy chains XLA emitted to feed the
    # kernel disappear — profiled in tools/profile_headline.py).
    wants_time_major = True

    @nn.compact
    def __call__(
        self, emb: jnp.ndarray, mask: jnp.ndarray, time_major: bool = False
    ) -> jnp.ndarray:
        if time_major:
            L, M, D = emb.shape
        else:
            M, L, D = emb.shape
        u = self.lstm_hidden
        emb = emb.astype(self.compute_dtype)

        # Each direction has its own weights (torch bidirectional-LSTM
        # convention: independent `*_reverse` tensors; leading param axis
        # 2 = direction, 0 forward / 1 backward). The grouped recurrence
        # runs both directions in one fused dispatch with a per-tile weight
        # select — no extra kernel calls vs the old weight-shared layout.
        w_ih = self.param(
            "w_ih", _per_direction(nn.initializers.lecun_normal()), (2, D, 4 * u)
        )
        w_hh = self.param(
            "w_hh", _per_direction(nn.initializers.orthogonal()), (2, u, 4 * u)
        )
        # Forget-gate bias starts at 1 so early training doesn't flush the
        # cell state (standard LSTM practice).
        b = self.param(
            "bias",
            lambda key, shape: jnp.zeros(shape).at[:, u : 2 * u].set(1.0),
            (2, 4 * u),
        )
        # The whole encoder body runs TIME-MAJOR. Preferred entry is
        # time_major=True (embeddings gathered straight into [L, M, D] from
        # transposed ids — see wants_time_major); the [M, L, D] entry keeps
        # working for direct callers and transposes the 60-wide embedding
        # here, still ~1/8 the bytes of transposing the 512-wide projected
        # gates that the pre-time-major layout moved.
        emb_t = emb if time_major else jnp.swapaxes(emb, 0, 1)  # [L, M, D]
        # Projection + recurrence in one fused kernel (ops/lstm.py): the
        # projected gates never materialize in HBM on the pallas path; the
        # scan path computes them explicitly with identical math.
        H = bilstm_encoder_tm(
            emb_t, w_ih, b[:, None, :], w_hh, backend=self.lstm_backend,
            cs_window=self.lstm_cs_window,
            residual_dtype=self.lstm_residual_dtype,
        )                                                     # [L, M, 2u]
        H = H.astype(self.compute_dtype)

        # Structured self-attention (Lin et al. 2017 form used by the paper):
        # scores = w2 · tanh(W1 hᵀ), masked softmax over L (axis 0 here).
        # Explicit params (not nn.Dense) so the fused kernel and the
        # two-pass path share one tree — checkpoint format v4.
        att_w1 = self.param(
            "att_w1", nn.initializers.lecun_normal(), (2 * u, self.att_dim)
        )
        att_w2 = self.param(
            "att_w2", nn.initializers.lecun_normal(), (self.att_dim, 1)
        )
        if self.attn_backend != "xla":
            return masked_selfattn_tm(
                H, mask, att_w1, att_w2, backend=self.attn_backend
            )
        cd = self.compute_dtype
        proj = H @ att_w1.astype(cd)
        scores = (jnp.tanh(proj) @ att_w2.astype(cd))[..., 0]  # [L, M]
        att = masked_softmax(
            scores.astype(jnp.float32), jnp.swapaxes(mask, 0, 1), axis=0
        )
        return jnp.einsum("lm,lmh->mh", att.astype(cd), H)

    @property
    def output_dim(self) -> int:
        return 2 * self.lstm_hidden
