"""Layer-stacked transformer encoder — the pipeline-parallel form.

Same math as ``models/transformer.py`` (pre-LN blocks, learned positions,
masked-mean pooling), but every per-layer parameter is STACKED along a
leading layer axis ``[NL, ...]`` instead of living in per-layer submodules.
That layout is what makes pipeline parallelism a pure sharding decision:

* single device: the layer axis is scanned (``lax.scan`` over the stacked
  pytree) — XLA compiles ONE block body, reused NL times;
* ``pp > 1``: the layer axis shards over the mesh's ``pp`` axis
  (``P('pp', ...)`` rules in parallel/sharding.py) and the executor is the
  GPipe microbatch schedule in parallel/pipeline.py — activations hop
  stage-to-stage over ICI via ``ppermute``.

The executor is injectable exactly like the attention in the unstacked
encoder: ``pipeline_impl(block_fn, stacked, x, mask) -> x``. ``None``
means the sequential scan. Param trees are identical for both executors,
so a ``pp=1`` checkpoint restores into a ``pp=8`` run unchanged (tested
equal in tests/test_pipeline.py).
"""

from __future__ import annotations

import math
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.ops import masked_mean

_NEG = -1e30


def _layer_norm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def block_apply(layer: dict, x: jnp.ndarray, mask: jnp.ndarray,
                num_heads: int) -> jnp.ndarray:
    """One pre-LN transformer block with UNstacked params (one layer's
    slice of the stack). x: [M, L, d]; mask: [M, L]."""
    M, L, d = x.shape
    H = num_heads
    hd = d // H
    cd = x.dtype

    h = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"])
    qkv = h @ layer["qkv_w"].astype(cd) + layer["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda t: t.reshape(M, L, H, hd).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
    s = jnp.where(mask[:, None, None, :] > 0, s, _NEG)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(cd)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    out = out.transpose(0, 2, 1, 3).reshape(M, L, d)
    x = x + out @ layer["att_out_w"].astype(cd) + layer["att_out_b"].astype(cd)

    h = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"])
    h = nn.gelu(h @ layer["mlp_up_w"].astype(cd) + layer["mlp_up_b"].astype(cd))
    return x + h @ layer["mlp_down_w"].astype(cd) + layer["mlp_down_b"].astype(cd)


def sequential_blocks(block_fn: Callable, stacked: dict, x: jnp.ndarray,
                      mask: jnp.ndarray) -> jnp.ndarray:
    """Reference executor: scan the stacked layer axis on one device."""

    def body(carry, layer):
        return block_fn(layer, carry, mask), None

    out, _ = jax.lax.scan(body, x, stacked)
    return out


class PipelinedTransformerEncoder(nn.Module):
    """[M, L, D] embedded tokens + [M, L] mask -> [M, d_model] sentence vec."""

    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 4
    d_ff: int = 1024
    max_length: int = 40
    compute_dtype: jnp.dtype = jnp.float32
    # (block_fn, stacked_params, x, mask) -> x. None -> sequential scan;
    # parallel.pipeline.make_gpipe(mesh, ...) for pp-sharded runs.
    pipeline_impl: Callable | None = None

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        M, L, _ = emb.shape
        cd = self.compute_dtype
        NL, d, f = self.num_layers, self.d_model, self.d_ff
        assert d % self.num_heads == 0

        x = nn.Dense(d, dtype=cd, param_dtype=jnp.float32, name="in_proj")(
            emb.astype(cd)
        )
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (self.max_length, d))
        x = x + pos[None, :L].astype(cd)

        # Layer-stacked parameters. The "stack_" prefix keys the pp
        # partition rules; fan-in-scaled normal init matches what
        # lecun_normal gives each per-layer slice.
        def w(name, shape, fan_in):
            return self.param(
                f"stack_{name}",
                nn.initializers.normal(1.0 / math.sqrt(fan_in)),
                (NL,) + shape,
            )

        def b(name, shape, value=0.0):
            return self.param(
                f"stack_{name}",
                nn.initializers.constant(value),
                (NL,) + shape,
            )

        stacked = {
            "ln1_scale": b("ln1_scale", (d,), 1.0),
            "ln1_bias": b("ln1_bias", (d,)),
            "qkv_w": w("qkv_w", (d, 3 * d), d),
            "qkv_b": b("qkv_b", (3 * d,)),
            "att_out_w": w("att_out_w", (d, d), d),
            "att_out_b": b("att_out_b", (d,)),
            "ln2_scale": b("ln2_scale", (d,), 1.0),
            "ln2_bias": b("ln2_bias", (d,)),
            "mlp_up_w": w("mlp_up_w", (d, f), d),
            "mlp_up_b": b("mlp_up_b", (f,)),
            "mlp_down_w": w("mlp_down_w", (f, d), f),
            "mlp_down_b": b("mlp_down_b", (d,)),
        }

        def block_fn(layer, xx, mm):
            return block_apply(layer, xx, mm, self.num_heads)

        run = self.pipeline_impl or sequential_blocks
        x = run(block_fn, stacked, x, mask)

        scale = self.param("final_ln_scale", nn.initializers.ones, (d,))
        bias = self.param("final_ln_bias", nn.initializers.zeros, (d,))
        x = _layer_norm(x, scale, bias)
        return masked_mean(x, mask[..., None], axis=-2).astype(cd)

    @property
    def output_dim(self) -> int:
        return self.d_model
