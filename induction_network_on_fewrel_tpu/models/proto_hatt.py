"""Hybrid-attention prototypical network (proto_hatt).

Toolkit-family sibling of the induction model (SURVEY.md §2.1 "Few-shot
model": siblings like ``proto.py`` in toolkit forks — the hybrid-attention
variant is Gao et al., AAAI 2019, "Hybrid Attention-Based Prototypical
Networks for Noisy Few-Shot Relation Classification"). Two attentions refine
the vanilla prototype:

* **Instance-level**: each query re-weights the K support instances of every
  class before averaging, so noisy support sentences contribute less:
  ``α_jk = softmax_k( Σ_h tanh(g(e_jk)) ⊙ g(q) )`` with a shared linear
  ``g``; the prototype becomes query-conditioned: ``p_j(q) = Σ_k α_jk e_jk``.
* **Feature-level**: a small conv stack over the K support encodings of a
  class scores which hidden dimensions matter for that class; the squared
  distance is re-weighted per-dimension: ``d(q, j) = Σ_h z_jh (q_h - p_jh)²``.

TPU notes: the instance-attention inner product and the weighted prototype
are einsums over the hidden axis (MXU contractions); the conv stack runs as
NHWC ``nn.Conv`` with the K axis as height — all static shapes, one compile.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel


class ProtoHATT(FewShotModel):
    """Prototypical network with instance- and feature-level attention."""

    k: int = 5  # K-shot (conv kernel over the support axis is K-sized)

    @nn.compact
    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        B, N, K, H = sup_enc.shape
        TQ = qry_enc.shape[1]
        cd = self.compute_dtype
        sup_enc = sup_enc.astype(cd)
        qry_enc = qry_enc.astype(cd)

        with jax.named_scope("feature_attention"):
            # Conv stack over the K support instances of each class: which
            # hidden dims are stable (hence discriminative) for this class.
            x = sup_enc.reshape(B * N, K, H, 1)  # NHWC: height=K, width=H
            # Total padding k-1 keeps the support axis at exactly K rows for
            # any k (a symmetric k//2 each side over-pads even k: K grows per
            # conv and the strided VALID conv below then reads zero-pad rows).
            pad = (((self.k - 1) // 2, self.k // 2), (0, 0))
            x = nn.relu(
                nn.Conv(32, (self.k, 1), padding=pad, dtype=cd,
                        param_dtype=jnp.float32)(x)
            )
            x = nn.relu(
                nn.Conv(64, (self.k, 1), padding=pad, dtype=cd,
                        param_dtype=jnp.float32)(x)
            )
            x = nn.Conv(1, (self.k, 1), strides=(self.k, 1), padding="VALID",
                        dtype=cd, param_dtype=jnp.float32)(x)
            # 1 + relu(·): strictly positive per-dimension weights. A bare
            # relu here can die wholesale (all logits become exactly 0 and
            # gradients vanish — observed at lr=3e-3); the unit floor makes
            # the distance fall back to plain euclidean when the conv stack
            # abstains, which is also the sane init-time behavior.
            fea_att = (1.0 + nn.relu(x[:, 0, :, 0])).reshape(B, N, H)

        with jax.named_scope("instance_attention"):
            g = nn.Dense(H, use_bias=True, dtype=cd, param_dtype=jnp.float32)
            sup_g = jnp.tanh(g(sup_enc))                       # [B, N, K, H]
            qry_g = g(qry_enc)                                 # [B, TQ, H]
            # score[b, t, n, k] = Σ_h tanh(g(e_nk)) · g(q_t)
            score = jnp.einsum("bnkh,bth->btnk", sup_g, qry_g)
            alpha = jax.nn.softmax(score.astype(jnp.float32), axis=-1).astype(cd)
            # Query-conditioned prototypes: [B, TQ, N, H]
            proto = jnp.einsum("btnk,bnkh->btnh", alpha, sup_enc)

        with jax.named_scope("distance"):
            # head_dtype distance (see models/proto.py): the fea_att-
            # weighted squared distance reaches magnitudes where bf16
            # spacing (~2.0 at 256) swamps the O(1) class-score differences
            # — measured as a quality collapse (0.365 vs proto's 0.69 at
            # the round-3 flagship recipe) before this cast.
            hd = self.head_dtype
            diff = (
                proto.astype(hd) - qry_enc.astype(hd)[:, :, None, :]
            )                                                   # [B, TQ, N, H]
            logits = -jnp.einsum(
                "btnh,bnh->btn", jnp.square(diff), fea_att.astype(hd)
            )

        logits = self.append_nota(logits.astype(jnp.float32))
        return logits.astype(jnp.float32)
