"""Domain discriminator for FewRel 2.0 adversarial domain adaptation.

The reference family's FewRel 2.0 recipe trains the sentence encoder against
a domain classifier fed with unlabeled target-domain (PubMed) instances so
the encoder's features become domain-invariant (SURVEY.md §0 pillar 7:
"FewRel 2.0 domain adaptation (PubMed)"). There, the adversary is a small
MLP over sentence encodings with three alternating optimizers; here the same
game runs as ONE jitted step via ``ops.gradient_reversal`` (DANN), which is
both simpler and XLA-friendly (no optimizer interleaving across compiles).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class DomainDiscriminator(nn.Module):
    """Sentence encoding [M, H] -> domain logits [M, 2] (0=source, 1=target)."""

    hidden: int = 256
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> jnp.ndarray:
        dense = lambda d, name: nn.Dense(
            d, dtype=self.compute_dtype, param_dtype=jnp.float32, name=name
        )
        x = nn.leaky_relu(dense(self.hidden, "fc1")(feat.astype(self.compute_dtype)))
        x = nn.leaky_relu(dense(self.hidden, "fc2")(x))
        return dense(2, "out")(x).astype(jnp.float32)
