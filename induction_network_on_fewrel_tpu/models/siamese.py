"""Siamese network — learned pairwise metric over query/support pairs.

Toolkit-family repos ship a siamese few-shot model next to proto/induction
(SURVEY.md §2.1 "Few-shot model": siblings of ``models/induction.py``): every
query is scored against each of the N·K support instances through a shared
learned similarity, and a class logit is the mean of its K pair scores
(Koch et al. 2015 adapted to episodes).

Pair score here is a learned weighted distance plus a bilinear term:

    s(q, e) = -Σ_h w_h (q_h - e_h)² + Σ_h v_h q_h e_h + b

TPU notes: materializing the [B, TQ, N, K, H] pair tensor would be an HBM
disaster at real episode sizes, so both terms are expanded into einsums over
the hidden axis — Σ w (q-e)² = (q²·w) - 2 (q⊙w)·e + (e²·w) — which XLA maps
onto single MXU contractions ([B,TQ,H] × [B,N·K,H]); the K-mean then folds
into the same reduction chain. Nothing bigger than [B, TQ, N·K] ever exists.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel


class SiameseNetwork(FewShotModel):
    @nn.compact
    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        B, N, K, H = sup_enc.shape
        # head_dtype metric (see models/proto.py): the weighted-distance
        # logits reach bf16's coarse-spacing range at H=230 and the O(1)
        # class-score differences quantize away. The encoder keeps
        # compute_dtype; these small einsums do not move the step time.
        dt = self.head_dtype
        w = self.param("metric_w", nn.initializers.ones, (H,)).astype(dt)
        v = self.param("metric_v", nn.initializers.zeros, (H,)).astype(dt)
        b = self.param("metric_b", nn.initializers.zeros, ()).astype(dt)
        q = qry_enc.astype(dt)                               # [B, TQ, H]
        e = sup_enc.astype(dt).reshape(B, N * K, H)          # [B, NK, H]
        with jax.named_scope("siamese_metric"):
            # -Σ w (q-e)² + Σ v q e, expanded so the cross terms are MXU
            # contractions and no [B,TQ,NK,H] intermediate is built.
            cross = jnp.einsum("bqh,bsh->bqs", q * (2.0 * w + v), e)
            q2 = jnp.einsum("bqh,h->bq", jnp.square(q), w)
            e2 = jnp.einsum("bsh,h->bs", jnp.square(e), w)
            pair = cross - q2[..., None] - e2[:, None, :] + b  # [B, TQ, NK]
            logits = jnp.mean(pair.reshape(B, -1, N, K), axis=-1)
        logits = self.append_nota(logits)
        return logits.astype(jnp.float32)
