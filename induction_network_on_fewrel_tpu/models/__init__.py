from induction_network_on_fewrel_tpu.models.embedding import Embedding  # noqa: F401
from induction_network_on_fewrel_tpu.models.encoders import (  # noqa: F401
    BiLSTMSelfAttnEncoder,
    CNNEncoder,
)
from induction_network_on_fewrel_tpu.models.base import FewShotModel  # noqa: F401
from induction_network_on_fewrel_tpu.models.induction import (  # noqa: F401
    Induction,
    InductionNetwork,
    RelationNTN,
)
from induction_network_on_fewrel_tpu.models.proto import (  # noqa: F401
    PrototypicalNetwork,
)
from induction_network_on_fewrel_tpu.models.proto_hatt import ProtoHATT  # noqa: F401
from induction_network_on_fewrel_tpu.models.siamese import SiameseNetwork  # noqa: F401
from induction_network_on_fewrel_tpu.models.gnn import GNN  # noqa: F401
from induction_network_on_fewrel_tpu.models.snail import SNAIL  # noqa: F401
from induction_network_on_fewrel_tpu.models.losses import (  # noqa: F401
    accuracy,
    cross_entropy_loss,
    mse_onehot_loss,
)
from induction_network_on_fewrel_tpu.models.build import build_model  # noqa: F401
