"""Induction module (dynamic routing) + neural-tensor Relation scorer + the
full InductionNetwork model.

Math (Geng et al. 2019, SURVEY.md §2.1 / §3.2):

* Induction, per class i with K support vectors e_ij:
    ê_ij = squash(W_s e_ij + b_s)          (shared transform)
    b_ij = 0
    repeat `iters` times (fixed trip count -> ``lax.fori_loop``, jit-exact):
        d_i  = softmax(b_i)                 (over the K shots)
        ĉ_i  = Σ_j d_ij ê_ij
        c_i  = squash(ĉ_i)
        b_ij += ê_ij · c_i
* Relation (NTN): v_iq = relu(c_iᵀ M^[1:h] e_q)  (h bilinear slices),
  score r_iq = σ(w_vᵀ v_iq + b_v).

TPU notes: the routing state ``b`` stays shaped [B, N, K] across iterations
(no reshapes inside the loop, so XLA fuses the whole loop body, SURVEY.md §7);
the NTN bilinear is one einsum → a single large MXU contraction; its slice
axis ``h`` is the natural tensor-parallel shard axis (see parallel/sharding).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel
from induction_network_on_fewrel_tpu.ops import squash


class Induction(nn.Module):
    induction_dim: int = 100
    routing_iters: int = 3
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, support: jnp.ndarray) -> jnp.ndarray:
        """[B, N, K, D] support encodings -> [B, N, C] class vectors."""
        B, N, K, _ = support.shape
        e_hat = nn.Dense(
            self.induction_dim, dtype=self.compute_dtype, param_dtype=jnp.float32
        )(support)
        e_hat = squash(e_hat)                       # [B, N, K, C]
        # Routing runs in f32: coupling logits accumulate dot products and
        # drift in bf16 over iterations.
        e32 = e_hat.astype(jnp.float32)

        def routing_iter(_, b):
            d = jax.nn.softmax(b, axis=-1)          # [B, N, K] over shots
            c = squash(jnp.einsum("bnk,bnkc->bnc", d, e32))
            return b + jnp.einsum("bnkc,bnc->bnk", e32, c)

        b0 = jnp.zeros((B, N, K), jnp.float32)
        b = jax.lax.fori_loop(0, self.routing_iters, routing_iter, b0)
        d = jax.nn.softmax(b, axis=-1)
        c = squash(jnp.einsum("bnk,bnkc->bnc", d, e32))
        return c.astype(self.compute_dtype)


class RelationNTN(nn.Module):
    slices: int = 100       # h
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, class_vec: jnp.ndarray, query: jnp.ndarray) -> jnp.ndarray:
        """([B, N, C], [B, TQ, C]) -> pre-sigmoid relation logits [B, TQ, N]."""
        C = class_vec.shape[-1]
        M = self.param(
            "tensor_slices", nn.initializers.glorot_normal(batch_axis=(0,)), (self.slices, C, C)
        )
        # One contraction for all (query, class, slice) triples; MXU-sized.
        # Bilinear slices stay in the compute dtype; accumulation is pinned
        # to f32 (preferred_element_type) so sub-f32 residents (ISSUE 18
        # quantized serving) never accumulate in the narrow dtype. No-op
        # when everything is already f32.
        cM = jnp.einsum(
            "bnc,hcd->bnhd", class_vec, M.astype(self.compute_dtype),
            preferred_element_type=jnp.float32,
        )
        v = nn.relu(jnp.einsum(
            "bnhd,bqd->bqnh", cM, query,
            preferred_element_type=jnp.float32,
        ))
        out = nn.Dense(1, dtype=self.compute_dtype, param_dtype=jnp.float32)(v)
        return out[..., 0]  # [B, TQ, N]


class InductionNetwork(FewShotModel):
    """Full few-shot model: encoder -> induction -> relation scoring.

    ``forward(support, query) -> logits [B, TQ, num_classes]`` where
    num_classes = N (+1 when NOTA is active — see FewShotModel.append_nota).
    """

    induction_dim: int = 100
    routing_iters: int = 3
    ntn_slices: int = 100
    # The episode head runs in its own (default f32) dtype: its output is
    # the loss surface, and bf16 logit quantization (~0.4%) becomes the
    # training noise floor on long overfit runs (see config.head_dtype).
    # The FLOPs live in the encoder, which keeps compute_dtype.
    head_dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.induction = Induction(
            self.induction_dim, self.routing_iters, compute_dtype=self.head_dtype
        )
        self.relation = RelationNTN(self.ntn_slices, compute_dtype=self.head_dtype)
        self.query_proj = nn.Dense(
            self.induction_dim, dtype=self.head_dtype, param_dtype=jnp.float32
        )
        self.make_nota_param()

    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        # named_scope: HLO ops attribute to stages in profiler traces.
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        with jax.named_scope("induction"):
            class_vec = self.induction(sup_enc)             # [B, N, C]
        with jax.named_scope("relation"):
            # Queries go through the same learned transform family as support
            # (W_s analog) so the NTN compares like with like.
            qry_c = self.query_proj(qry_enc)                # [B, TQ, C]
            logits = self.relation(class_vec, qry_c)        # [B, TQ, N]
        logits = self.append_nota(logits)                   # [B, TQ, N(+1)]
        return logits.astype(jnp.float32)

    # --- serving sub-applies (serving/registry.py + serving/buckets.py) ---
    #
    # The episode forward splits cleanly at the class-vector boundary: the
    # support half (encoder + routing) depends only on the support set, the
    # query half only on the class vectors — so a serving engine runs
    # ``class_vectors`` ONCE per registered support set and then answers
    # every query with ``score_queries`` alone. Both halves reuse the exact
    # modules __call__ uses (same params, same dtypes); the encoders are
    # row-independent, so encoding support and query in separate calls is
    # the same math as __call__'s fused concat-encode-split pass
    # (numerical-tolerance parity pinned in tests/test_serving.py).

    def class_vectors(self, support: dict[str, Any]) -> jnp.ndarray:
        """[B, N, K] support token dict (or pre-encoded [B, N, K, H] array)
        -> [B, N, C] class vectors via encoder + dynamic routing."""
        if isinstance(support, dict):
            with jax.named_scope("encoder"):
                sup_enc = self.encode(
                    support["word"], support["pos1"],
                    support["pos2"], support["mask"],
                )
        else:
            sup_enc = jnp.asarray(support)
        with jax.named_scope("induction"):
            return self.induction(sup_enc)

    def score_queries(
        self, class_vec: jnp.ndarray, query: dict[str, Any],
        scale: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        """([B, N, C] class vectors, [B, TQ] query token dict) -> relation
        logits [B, TQ, N(+1)] — the steady-state serving path: one encoder
        pass over the queries plus the NTN score, no support work at all.

        ``class_vec`` may be a quantized resident matrix (ISSUE 18): bf16
        rides the existing head-dtype upcast dequant-free; int8 passes its
        per-tenant symmetric f32 ``scale`` and is dequantized here, inside
        the compiled program — the [B, N, C] matrix is tiny next to the
        query encoder, so the dequant is noise while the resident (HBM)
        copy stays int8."""
        if scale is not None:
            class_vec = class_vec.astype(jnp.float32) * scale
        if isinstance(query, dict):
            with jax.named_scope("encoder"):
                qry_enc = self.encode(
                    query["word"], query["pos1"], query["pos2"], query["mask"]
                )
        else:
            qry_enc = jnp.asarray(query)
        with jax.named_scope("relation"):
            qry_c = self.query_proj(qry_enc)
            logits = self.relation(class_vec.astype(self.head_dtype), qry_c)
        logits = self.append_nota(logits)
        return logits.astype(jnp.float32)
