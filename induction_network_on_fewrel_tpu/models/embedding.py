"""Token embedding: GloVe word vectors ⧺ two entity-position embeddings.

Reference behavior (SURVEY.md §2.1 "Embedding"): word embedding initialized
from the GloVe 50-d matrix (+2 rows UNK/BLANK), concatenated with two
``Embedding(2*max_length, pos_dim)`` lookups of the head/tail offsets,
yielding (word_dim + 2*pos_dim)-d token vectors.

Gathers are HBM-bandwidth ops, not MXU ops; XLA fuses the three gathers and
the concat into the consumer. The gathers' BACKWARD is the expensive part:
autodiff transposes them into serialized scatter-adds (profiled at ~19% of
headline device time — tools/profile_headline.py), so the small-table
lookups (position tables always; the word table when it is compact, i.e.
the lazy-embed rows or a small vocab) route through
``ops.segsum.lookup_matmul_grad``, whose gradient is a one-hot MXU matmul
instead. The full 400k GloVe table keeps the native scatter — at that row
count the one-hot matmul loses (see ops/segsum.py).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from induction_network_on_fewrel_tpu.ops.segsum import (
    MATMUL_GRAD_MAX_ROWS,
    lookup_matmul_grad,
)


def is_offset_form(pos: jnp.ndarray, word_rank: int) -> bool:
    """True when a position leaf is in per-sentence OFFSET form (one rank
    below ``word``; full per-token ids are exactly ``off + l`` —
    train/token_cache._compact_pos_offsets). The producer compacts pos1
    and pos2 INDEPENDENTLY, so every consumer must test each leaf with
    this predicate rather than letting pos1's rank decide for both
    (advisor finding, round 4). Single definition: the form contract has
    exactly one owner."""
    return pos.ndim == word_rank - 1


class Embedding(nn.Module):
    vocab_size: int
    word_dim: int = 50
    pos_dim: int = 5
    max_length: int = 40
    glove_init: np.ndarray | None = None  # [vocab_size, word_dim] or None
    compute_dtype: jnp.dtype = jnp.float32
    # embed_optimizer="frozen": stop_gradient on the word table, so AD never
    # materializes the dense [vocab, word_dim] gradient and the global-norm
    # clip reduces over symbolic zeros (XLA folds them away) — a frozen
    # table costs nothing per step, instead of a full-table grad pass.
    freeze_word_table: bool = False
    # Mesh-aware word lookup (parallel/sharding.make_compact_demb_lookup,
    # threaded by build_model on multi-device dp runs; None elsewhere):
    # ``(table, ids, batch_dim) -> vecs``. Same forward values as the plain
    # gather; its custom-VJP backward keeps the demb segment-sum LOCAL to
    # each dp shard and all-reduces only the compact [U, D] touched-row
    # gradient — instead of GSPMD replicating the [L, M, word_dim]
    # cotangent (26 MB/step/device at the flagship shape, COMMS_r06).
    # Like attn_impl on the transformer: an execution strategy, not an
    # architecture field — params and checkpoints are unchanged.
    demb_impl: Any = None

    @nn.compact
    def __call__(
        self,
        word: jnp.ndarray,
        pos1: jnp.ndarray,
        pos2: jnp.ndarray,
        time_major: bool = False,
    ) -> jnp.ndarray:
        """[..., L] int32 ids -> [..., L, word_dim + 2*pos_dim].

        OFFSET position form: when ``pos1``/``pos2`` arrive with one rank
        LESS than ``word`` they are per-SENTENCE start offsets (the
        token-cache compaction, train/token_cache._compact_pos_offsets:
        full ids are exactly ``off + l``). The position vectors are then
        reconstructed as ``one_hot(off, L+1) @ windows(P)`` — a [rows,
        L+1] x [L+1, L*pos_dim] MXU matmul over windows of the position
        table instead of a per-token row gather (the windows themselves
        are a tiny [L+1, L] gather of the [2L, pos_dim] table). Row
        selection by an exact 0/1 one-hot in f32 reproduces the gathered
        values BITWISE, so the two forms are interchangeable per episode.
        ``time_major`` orients the reconstruction: word [L, M] (time
        first) vs [M, L]."""
        if self.glove_init is not None:
            if self.glove_init.shape != (self.vocab_size, self.word_dim):
                raise ValueError(
                    f"glove_init {self.glove_init.shape} != "
                    f"({self.vocab_size}, {self.word_dim})"
                )
            init = lambda *_: jnp.asarray(self.glove_init, jnp.float32)
        else:
            init = nn.initializers.normal(0.1)
        lazy_rows = self.has_variable("lazy_embed", "rows")
        if lazy_rows:
            # embed_optimizer=lazy (train/lazy_embed.py): the step body
            # passes the batch's CAUGHT-UP unique rows [U, word_dim] via
            # this collection, with word ids already remapped into them —
            # autodiff then yields a compact [U, word_dim] cotangent
            # instead of a dense [vocab, word_dim] scatter. The param
            # below still exists; it is simply not read on this path.
            word_table = self.get_variable("lazy_embed", "rows")
        else:
            word_table = self.param(
                "word_embedding", init, (self.vocab_size, self.word_dim)
            )
        if self.freeze_word_table:
            word_table = jax.lax.stop_gradient(word_table)
        pos1_table = self.param(
            "pos1_embedding", nn.initializers.normal(0.1), (2 * self.max_length, self.pos_dim)
        )
        pos2_table = self.param(
            "pos2_embedding", nn.initializers.normal(0.1), (2 * self.max_length, self.pos_dim)
        )
        # On dp-sharded runs the mesh-aware demb_impl takes the word
        # lookup whenever the table's row gradient is COMPACT: always for
        # the lazy rows leaf (any size — real corpora run 40-60k rows and
        # its shard-local backward picks matmul-grad vs scatter by the
        # segsum crossover internally; gating the whole path behind
        # MATMUL_GRAD_MAX_ROWS would deactivate the comms fix exactly
        # there — round-7 review finding), and for dense tables only
        # below the crossover. A LARGE dense shared table must NOT take
        # it: psumming the full [vocab, D] gradient (~80 MB at 400k rows)
        # costs more wire than the replicated-cotangent gather it would
        # replace — shared-mode 400k runs keep the native path (ledger-
        # only territory; round-7 review finding, pass 3). Off-mesh:
        # matmul-gradient lookups where the table is small enough to win
        # (module docstring); frozen tables have no backward at all, so
        # the plain gather is strictly simpler there.
        small = word_table.shape[0] <= MATMUL_GRAD_MAX_ROWS
        if self.freeze_word_table:
            word_vecs = word_table[word]
        elif self.demb_impl is not None and (lazy_rows or small):
            word_vecs = self.demb_impl(
                word_table, word, 1 if time_major else 0
            )
        elif small:
            word_vecs = lookup_matmul_grad(word_table, word)
        else:
            word_vecs = word_table[word]
        # Offset form is decided PER KEY: the token-cache compacts pos1 and
        # pos2 independently, so one may arrive as per-sentence offsets
        # while the other stays per-token (advisor finding, round 4).
        L = word.shape[0] if time_major else word.shape[-1]

        def pos_vecs(table, pos):
            if is_offset_form(pos, word.ndim):
                return self._pos_from_offsets(table, pos, L, time_major)
            return lookup_matmul_grad(table, pos)

        pos1_vecs = pos_vecs(pos1_table, pos1)
        pos2_vecs = pos_vecs(pos2_table, pos2)
        out = jnp.concatenate([word_vecs, pos1_vecs, pos2_vecs], axis=-1)
        return out.astype(self.compute_dtype)

    @staticmethod
    def _pos_from_offsets(table, off, L, time_major):
        """[rows] offsets -> position vectors [L, rows, P] (time_major) or
        [rows, L, P]: one_hot(off, L+1) @ windows(table). Window base o
        covers off's exact range [0, L] (the tokenizer's ids are
        (l - head) + L with head in [0, L), so off = L - head in [1, L];
        base 0 is headroom, never out of table: max index L + L-1 =
        2L - 1). f32 throughout: exact row selection, bitwise equal to the
        gather form."""
        win_idx = (
            jnp.arange(L + 1, dtype=jnp.int32)[:, None]
            + jnp.arange(L, dtype=jnp.int32)[None, :]
        )                                               # [L+1, L] in [0, 2L)
        win = lookup_matmul_grad(table, win_idx)        # [L+1, L, P]
        oh = jax.nn.one_hot(off, L + 1, dtype=jnp.float32)
        pat = "mo,olp->lmp" if time_major else "mo,olp->mlp"
        return jnp.einsum(pat, oh, win.astype(jnp.float32))

    @property
    def output_dim(self) -> int:
        return self.word_dim + 2 * self.pos_dim
