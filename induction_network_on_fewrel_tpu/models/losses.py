"""Losses and metrics.

The paper trains with MSE between sigmoid relation scores and the one-hot
episode label (Geng et al. §3.4); toolkit-family forks often use CE over
logits instead (SURVEY.md §2.1 "Loss / metrics" — ambiguous in the unreadable
reference, so both are supported and flag-selected).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def mse_onehot_loss(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Mean squared error between sigmoid(logits) and one-hot(label).

    logits: [B, TQ, num_classes] pre-sigmoid; label: [B, TQ] int.
    """
    scores = jax.nn.sigmoid(logits)
    onehot = jax.nn.one_hot(label, logits.shape[-1], dtype=scores.dtype)
    return jnp.mean(jnp.square(scores - onehot))


def cross_entropy_loss(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, label)
    )


def predict(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1)


def accuracy(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((predict(logits) == label).astype(jnp.float32))


def metric_keys(cfg) -> tuple[str, ...]:
    """Keys of the per-step metric dict (loss/accuracy + NOTA counts when
    na_rate > 0) — single source for the sharded steps' out_shardings."""
    base = ("loss", "accuracy")
    return base + (("nota_tp", "nota_pred", "nota_true") if cfg.na_rate > 0
                   else ())


def episode_metrics(logits: jnp.ndarray, label: jnp.ndarray, nota: bool) -> dict:
    """accuracy (+ NOTA confusion fractions when the N+1 'none' class is
    active — BASELINE config #5). The three NOTA entries share one
    denominator (all queries), so aggregated precision/recall are exact:
    p = Σtp/Σpred, r = Σtp/Σtrue (see FewShotTrainer.evaluate)."""
    m = {"accuracy": accuracy(logits, label)}
    if nota:
        n = logits.shape[-1] - 1  # the appended none-of-the-above class
        is_pred = predict(logits) == n
        is_true = label == n
        m["nota_tp"] = jnp.mean((is_pred & is_true).astype(jnp.float32))
        m["nota_pred"] = jnp.mean(is_pred.astype(jnp.float32))
        m["nota_true"] = jnp.mean(is_true.astype(jnp.float32))
    return m
