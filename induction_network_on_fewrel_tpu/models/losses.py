"""Losses and metrics.

The paper trains with MSE between sigmoid relation scores and the one-hot
episode label (Geng et al. §3.4); toolkit-family forks often use CE over
logits instead (SURVEY.md §2.1 "Loss / metrics" — ambiguous in the unreadable
reference, so both are supported and flag-selected).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def mse_onehot_loss(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Mean squared error between sigmoid(logits) and one-hot(label).

    logits: [B, TQ, num_classes] pre-sigmoid; label: [B, TQ] int.
    """
    scores = jax.nn.sigmoid(logits)
    onehot = jax.nn.one_hot(label, logits.shape[-1], dtype=scores.dtype)
    return jnp.mean(jnp.square(scores - onehot))


def cross_entropy_loss(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, label)
    )


def predict(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1)


def accuracy(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((predict(logits) == label).astype(jnp.float32))
