"""SNAIL meta-learner (snail).

Toolkit-family sibling model (SURVEY.md §2.1 "Few-shot model" siblings;
Mishra et al., ICLR 2018, "A Simple Neural Attentive Meta-Learner"). The
episode is serialized per query: a sequence of the N·K (encoding, label
one-hot) support pairs followed by the query with a zero label, length
T = N·K + 1. The network interleaves

* **TC blocks** — ⌈log₂ T⌉ causal dense blocks, each a gated causal conv
  (dilation 1, 2, 4, …) whose output concatenates onto the features, and
* **attention blocks** — single-head causal soft attention with learned
  key/value projections, output concatenated onto the features,

and reads the N class logits off the final (query) position.

TPU notes: all queries run as one batch ([B·TQ] leading axis); causal convs
are ``nn.Conv`` with left padding and kernel dilation (static shapes, MXU
matmuls over the channel axis); causal attention is one masked softmax —
sequence length is ≤ 51, so no blockwise machinery is needed (SURVEY.md
§5.7: long-context machinery lives in ``parallel/ring.py``, not here).
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel


class _CausalConvBlock(nn.Module):
    """Gated causal conv (WaveNet-style): concat(x, tanh(f) * sigmoid(g))."""

    filters: int
    dilation: int
    compute_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        pad = ((self.dilation, 0),)  # left-pad: position t sees ≤ t only
        conv = lambda name: nn.Conv(
            self.filters, kernel_size=(2,), kernel_dilation=(self.dilation,),
            padding=pad, dtype=self.compute_dtype, param_dtype=jnp.float32,
            name=name,
        )
        gate = jnp.tanh(conv("filter")(x)) * jax.nn.sigmoid(conv("gate")(x))
        return jnp.concatenate([x, gate], axis=-1)


class _TCBlock(nn.Module):
    """Stack of causal conv blocks with dilations 1, 2, 4, … covering T."""

    seq_len: int
    filters: int
    compute_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i in range(max(1, math.ceil(math.log2(self.seq_len)))):
            x = _CausalConvBlock(self.filters, 2 ** i, self.compute_dtype,
                                 name=f"cc_{i}")(x)
        return x


class _AttentionBlock(nn.Module):
    """Single-head causal attention; output concatenated onto features."""

    key_dim: int
    value_dim: int
    compute_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        G, T, _ = x.shape
        dense = lambda d, name: nn.Dense(
            d, dtype=self.compute_dtype, param_dtype=jnp.float32, name=name
        )
        q = dense(self.key_dim, "q")(x)
        k = dense(self.key_dim, "k")(x)
        v = dense(self.value_dim, "v")(x)
        scores = jnp.einsum("gtd,gsd->gts", q, k) / math.sqrt(self.key_dim)
        causal = jnp.tril(jnp.ones((T, T), dtype=bool))
        scores = jnp.where(causal[None], scores.astype(jnp.float32), -1e9)
        att = jax.nn.softmax(scores, axis=-1).astype(self.compute_dtype)
        out = jnp.einsum("gts,gsd->gtd", att, v)
        return jnp.concatenate([x, out], axis=-1)


class SNAIL(FewShotModel):
    """Attentive meta-learner over the serialized episode."""

    tc_filters: int = 128
    att1: tuple[int, int] = (64, 32)    # (key_dim, value_dim)
    att2: tuple[int, int] = (256, 128)

    @nn.compact
    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        B, N, K, H = sup_enc.shape
        TQ = qry_enc.shape[1]
        cd = self.compute_dtype
        T = N * K + 1

        with jax.named_scope("serialize"):
            sup_lab = jnp.broadcast_to(
                jnp.eye(N, dtype=cd)[None, :, None, :], (B, N, K, N)
            )
            sup_seq = jnp.concatenate(
                [sup_enc.astype(cd), sup_lab], -1
            ).reshape(B, 1, N * K, H + N)
            sup_seq = jnp.broadcast_to(sup_seq, (B, TQ, N * K, H + N))
            qry_tok = jnp.concatenate(
                [qry_enc.astype(cd)[:, :, None, :],
                 jnp.zeros((B, TQ, 1, N), dtype=cd)], -1
            )
            # Supports first, query LAST — causal attention lets the query
            # position attend to every support.
            x = jnp.concatenate([sup_seq, qry_tok], axis=2)
            x = x.reshape(B * TQ, T, H + N)

        with jax.named_scope("snail_stack"):
            x = _AttentionBlock(*self.att1, cd, name="att_1")(x)
            x = _TCBlock(T, self.tc_filters, cd, name="tc_1")(x)
            x = _AttentionBlock(*self.att2, cd, name="att_2")(x)
            x = _TCBlock(T, self.tc_filters, cd, name="tc_2")(x)
            x = _AttentionBlock(512, 256, cd, name="att_3")(x)

        with jax.named_scope("readout"):
            logits = nn.Dense(N, dtype=cd, param_dtype=jnp.float32,
                              name="out")(x[:, -1, :])
            logits = logits.reshape(B, TQ, N)

        logits = self.append_nota(logits.astype(jnp.float32))
        return logits.astype(jnp.float32)
