"""Mixture-of-Experts FFN with expert parallelism over an ``ep`` mesh axis.

The reference has no MoE anywhere (SURVEY.md §2.2 "Expert parallel: NO"),
but this framework treats every parallelism axis as first-class: the
transformer encoder's dense MLP can be swapped for a sparsely-activated
expert layer whose experts shard over the mesh's ``ep`` axis — the pattern
that scales encoder capacity without scaling per-token FLOPs.

TPU-shaped design (GShard/Switch style, einsum formulation):

* **Token grouping**: tokens are routed in fixed-size groups of ``S =
  group_size`` (padded with masked tokens), so the dispatch/combine
  one-hots are ``[G, S, E, C]`` with ``C = ceil(k·S/E · capacity_factor)``
  — memory stays LINEAR in total tokens (a single flat [T, E, C] would be
  quadratic: C itself grows with T).
* **Routing** is one [G, S, E] matmul + top-k selection with a STATIC
  per-group capacity — no dynamic shapes, no sorts; everything lowers to
  one-hot matmuls and cumsums the MXU/VPU eat directly.
* **Padding-aware**: the sentence mask zeroes a pad token's routing
  one-hot BEFORE the capacity cumsum, so pads consume no expert slots and
  the load-balance statistics count real tokens only. (The dense MLP
  merely wastes FLOPs on pads; a capacity-bounded MoE would silently drop
  REAL tokens to make room for pad traffic.)
* **Dispatch/combine** are einsums against the one-hot tensors: each
  expert's tokens land in a dense ``[G, E, C, d]`` block and the expert
  FFN is a *batched* GEMM — large, static, bf16-friendly.
* **Expert parallelism**: expert-stacked parameters ``[E, d, f]`` carry a
  ``P('ep', None, None)`` sharding (parallel/sharding.py). Under GSPMD the
  dispatch einsum becomes the all-to-all that scatters token blocks to the
  devices owning each expert, and the combine einsum the inverse — XLA
  inserts both over ICI; there is no hand-written collective here.
* **Load balance**: the standard aux loss ``E · Σ_e f_e·p_e`` (fraction of
  real tokens routed to e × their mean router prob of e) is sown into the
  "losses" collection; the train step adds it with weight
  ``cfg.moe_aux_weight`` (train/steps.py). Eval applies without the
  mutable collection, so the sow is dropped — no eval-time overhead.

Capacity overflow drops tokens (their residual path still carries them —
the layer is residual in TransformerEncoder), matching the standard
trade-off; tests pin the no-drop regime to exact-dense equivalence.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoeFfn(nn.Module):
    """Top-k routed expert FFN: [M, L, d] (+ [M, L] mask) -> [M, L, d]."""

    num_experts: int
    d_ff: int
    top_k: int = 2
    capacity_factor: float = 2.0
    group_size: int = 512  # tokens per routing group (memory knob)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray | None = None):
        M, L, d = x.shape
        E, k = self.num_experts, min(self.top_k, self.num_experts)
        T = M * L
        S = min(self.group_size, T)
        G = math.ceil(T / S)
        pad = G * S - T
        # Static per-group expert buffer; every shape below is compile-time.
        C = min(max(1, math.ceil(k * S / E * self.capacity_factor)), S)
        cd = self.compute_dtype

        xt = x.reshape(T, d)
        mk = (
            jnp.ones((T,), jnp.float32) if mask is None
            else (mask.reshape(T) > 0).astype(jnp.float32)
        )
        if pad:
            xt = jnp.pad(xt, ((0, pad), (0, 0)))
            mk = jnp.pad(mk, (0, pad))  # pad slots are masked out
        xt = xt.reshape(G, S, d)
        mk = mk.reshape(G, S)

        # Router runs in f32: tiny matmul, and routing decisions should not
        # flap with bf16 rounding.
        logits = nn.Dense(E, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")(xt.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1) * mk[..., None]  # [G, S, E]

        # Iterative top-k assignment. Each round: argmax over still-unchosen
        # experts -> one-hot (masked tokens contribute nothing) ->
        # capacity-bounded slot index via a within-group cumsum.
        remaining = probs
        slot_count = jnp.zeros((G, E), jnp.float32)  # slots used per expert
        dispatch = jnp.zeros((G, S, E, C), jnp.float32)
        combine = jnp.zeros((G, S, E, C), jnp.float32)  # gate-weighted
        gate_sum = jnp.zeros((G, S), jnp.float32)
        first_oh = None
        for _ in range(k):
            choice = jnp.argmax(remaining, axis=-1)              # [G, S]
            oh = jax.nn.one_hot(choice, E, dtype=jnp.float32)
            oh = oh * mk[..., None]  # pads take no slots, count nowhere
            first_oh = oh if first_oh is None else first_oh
            # Position of each token within its chosen expert's buffer:
            # running count over the group's token axis + slots used by
            # earlier rounds. (Token order = priority; later drop first.)
            pos = jnp.cumsum(oh, axis=1) - oh + slot_count[:, None, :]
            pos_tok = jnp.sum(pos * oh, axis=-1)                 # [G, S]
            fits = (pos_tok < C).astype(jnp.float32)
            ohf = oh * fits[..., None]
            slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), C,
                                  dtype=jnp.float32)             # [G, S, C]
            piece = ohf[..., None] * slot[:, :, None, :]         # [G,S,E,C]
            dispatch = dispatch + piece
            # Fold the gate into combine NOW (renormalized after the loop
            # by the per-token gate sum) so per-round [G, S, E, C] slices
            # never outlive their iteration.
            gp = jnp.sum(probs * ohf, axis=-1)                   # [G, S]
            combine = combine + gp[..., None, None] * piece
            gate_sum = gate_sum + gp
            slot_count = slot_count + jnp.sum(ohf, axis=1)
            remaining = remaining * (1.0 - oh)  # mask chosen expert out

        # Load-balance aux over REAL tokens (first-round assignment,
        # pre-capacity): sown for the train step; silently dropped when
        # "losses" is not mutable. Never sown during init — otherwise the
        # collection would leak into the initialized variables (and from
        # there into TrainState and checkpoints).
        if not self.is_initializing():
            nreal = jnp.sum(mk) + 1e-9
            f_e = jnp.sum(first_oh, axis=(0, 1)) / nreal         # [E]
            p_e = jnp.sum(probs, axis=(0, 1)) / nreal            # [E]
            self.sow("losses", "moe_aux", E * jnp.sum(f_e * p_e))

        # Renormalize over the selected (surviving) experts: each token's
        # combine weights sum to 1 unless every selection was dropped.
        combine = combine / (gate_sum[..., None, None] + 1e-9)

        # Expert computation: dense [G, E, C, d] blocks through per-expert
        # weights — batched GEMMs on the MXU. Param names carry the
        # "experts_" prefix the ep partition rules key on.
        w_up = self.param("experts_up", nn.initializers.lecun_normal(),
                          (E, d, self.d_ff), jnp.float32)
        b_up = self.param("experts_up_bias", nn.initializers.zeros,
                          (E, self.d_ff), jnp.float32)
        w_down = self.param("experts_down", nn.initializers.lecun_normal(),
                            (E, self.d_ff, d), jnp.float32)
        b_down = self.param("experts_down_bias", nn.initializers.zeros,
                            (E, d), jnp.float32)

        expert_in = jnp.einsum("gsec,gsd->gecd", dispatch.astype(cd),
                               xt.astype(cd))
        h = nn.gelu(
            jnp.einsum("gecd,edf->gecf", expert_in, w_up.astype(cd))
            + b_up[None, :, None, :].astype(cd)
        )
        out_e = (
            jnp.einsum("gecf,efd->gecd", h, w_down.astype(cd))
            + b_down[None, :, None, :].astype(cd)
        )
        out = jnp.einsum("gsec,gecd->gsd", combine.astype(cd), out_e)
        out = out.reshape(G * S, d)
        if pad:
            out = out[:T]
        return out.reshape(M, L, d)
