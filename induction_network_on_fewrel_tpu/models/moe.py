"""Mixture-of-Experts FFN with expert parallelism over an ``ep`` mesh axis.

The reference has no MoE anywhere (SURVEY.md §2.2 "Expert parallel: NO"),
but this framework treats every parallelism axis as first-class: the
transformer encoder's dense MLP can be swapped for a sparsely-activated
expert layer whose experts shard over the mesh's ``ep`` axis — the pattern
that scales encoder capacity without scaling per-token FLOPs.

TPU-shaped design (GShard/Switch style, einsum formulation):

* **Routing** is a single [T, E] matmul + top-k selection with a STATIC
  per-expert capacity ``C = ceil(k·T/E · capacity_factor)`` — no dynamic
  shapes, no sorting networks; everything lowers to one-hot matmuls the
  MXU eats directly.
* **Dispatch/combine** are einsums against a [T, E, C] one-hot tensor:
  ``expert_in = einsum('tec,td->ecd')`` gathers each expert's tokens into a
  dense [E, C, d] block; the expert FFN is then a *batched* GEMM
  ``[E, C, d] x [E, d, f]`` — large, static, bf16-friendly.
* **Expert parallelism**: expert-stacked parameters ``[E, d, f]`` carry a
  ``P('ep', None, None)`` sharding (parallel/sharding.py). Under GSPMD the
  dispatch einsum becomes the all-to-all that scatters token blocks to the
  devices owning each expert, and the combine einsum the inverse — XLA
  inserts both over ICI; there is no hand-written collective here.
* **Load balance**: the standard aux loss ``E · Σ_e f_e·p_e`` (fraction of
  tokens routed to e × mean router prob of e) is sown into the "losses"
  collection; the train step adds it with weight ``cfg.moe_aux_weight``
  (train/steps.py). Eval applies without the mutable collection, so the sow
  is dropped — no eval-time overhead.

Capacity overflow drops tokens (their residual path still carries them —
the layer is residual in TransformerEncoder), matching the standard
trade-off; tests pin the no-drop regime to exact-dense equivalence.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoeFfn(nn.Module):
    """Top-k routed expert FFN: [M, L, d] -> [M, L, d]."""

    num_experts: int
    d_ff: int
    top_k: int = 2
    capacity_factor: float = 2.0
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        M, L, d = x.shape
        E, k = self.num_experts, min(self.top_k, self.num_experts)
        T = M * L
        # Static per-expert buffer size; every shape below is compile-time.
        C = max(1, math.ceil(k * T / E * self.capacity_factor))
        C = min(C, T)
        cd = self.compute_dtype

        xt = x.reshape(T, d)
        # Router runs in f32: tiny matmul, and routing decisions should not
        # flap with bf16 rounding.
        logits = nn.Dense(E, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")(xt.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

        # Iterative top-k assignment. Each round: argmax over still-unchosen
        # experts -> one-hot -> capacity-bounded slot index via cumsum.
        remaining = probs
        slot_count = jnp.zeros((E,), jnp.int32)  # slots used per expert
        dispatch = jnp.zeros((T, E, C), jnp.float32)
        combine = jnp.zeros((T, E, C), jnp.float32)  # gate-weighted, unnorm
        gate_sum = jnp.zeros((T,), jnp.float32)
        first_oh = None
        for _ in range(k):
            choice = jnp.argmax(remaining, axis=-1)             # [T]
            oh = jax.nn.one_hot(choice, E, dtype=jnp.float32)   # [T, E]
            first_oh = oh if first_oh is None else first_oh
            # Position of each token within its chosen expert's buffer:
            # running count over the token axis + slots used by earlier
            # rounds. (Token order = priority; later tokens drop first.)
            pos = jnp.cumsum(oh, axis=0) - oh + slot_count[None, :]  # [T, E]
            pos_tok = jnp.sum(pos * oh, axis=-1)                # [T]
            fits = pos_tok < C                                  # [T]
            ohf = oh * fits[:, None].astype(jnp.float32)
            slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), C,
                                  dtype=jnp.float32)            # [T, C]
            piece = ohf[:, :, None] * slot[:, None, :]          # [T, E, C]
            dispatch = dispatch + piece
            # Fold the gate into combine NOW (renormalized after the loop by
            # the per-token gate sum) so per-round [T, E, C] slices never
            # outlive their iteration.
            gp = jnp.sum(probs * ohf, axis=-1)                  # [T]
            combine = combine + gp[:, None, None] * piece
            gate_sum = gate_sum + gp
            slot_count = slot_count + jnp.sum(ohf, axis=0).astype(jnp.int32)
            remaining = remaining * (1.0 - oh)  # mask chosen expert out

        # Load-balance aux (first-round assignment, pre-capacity): sown for
        # the train step; silently dropped when "losses" is not mutable.
        # Never sown during init — otherwise the collection would leak into
        # the initialized variables (and from there into TrainState and
        # checkpoints).
        if not self.is_initializing():
            f_e = jnp.mean(first_oh, axis=0)                    # [E]
            p_e = jnp.mean(probs, axis=0)                       # [E]
            self.sow("losses", "moe_aux", E * jnp.sum(f_e * p_e))

        # Renormalize over the selected (surviving) experts: each token's
        # combine weights sum to 1 unless every selection was dropped.
        combine = combine / (gate_sum[:, None, None] + 1e-9)

        # Expert computation: dense [E, C, d] blocks through per-expert
        # weights — ONE batched GEMM pair on the MXU. Param names carry the
        # "experts_" prefix the ep partition rules key on.
        w_up = self.param("experts_up", nn.initializers.lecun_normal(),
                          (E, d, self.d_ff), jnp.float32)
        b_up = self.param("experts_up_bias", nn.initializers.zeros,
                          (E, self.d_ff), jnp.float32)
        w_down = self.param("experts_down", nn.initializers.lecun_normal(),
                            (E, self.d_ff, d), jnp.float32)
        b_down = self.param("experts_down_bias", nn.initializers.zeros,
                            (E, d), jnp.float32)

        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cd),
                               xt.astype(cd))
        h = nn.gelu(
            jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cd))
            + b_up[:, None, :].astype(cd)
        )
        out_e = (
            jnp.einsum("ecf,efd->ecd", h, w_down.astype(cd))
            + b_down[:, None, :].astype(cd)
        )
        out = jnp.einsum("tec,ecd->td", combine.astype(cd), out_e)
        return out.reshape(M, L, d)
