"""BERT-base sentence encoder, written from scratch in Flax.

Covers the reference's third encoder option (SURVEY.md §2.1 "BERT encoder":
bert-base-uncased backbone, [CLS]/entity pooling, frozen -> fine-tuned
regime). Built TPU-first rather than imported from HF:

* bf16 matmuls throughout (params stay f32), fused QKV projection — one
  [H, 3H] matmul instead of three [H, H] — and a single einsum per attention
  contraction, all MXU-shaped.
* No data-dependent control flow; attention masking is additive -inf bias,
  shapes static in ``max_length``.
* ``frozen=True`` wraps the backbone in ``jax.lax.stop_gradient`` — the
  frozen phase of the reference's frozen->fine-tuned schedule — so the same
  compiled program serves both phases (flip the flag, recompile once).
* Layer boundaries are ``nn.remat``-able for HBM headroom at larger episode
  batches (enable via ``remat=True``; SURVEY.md §7 "BERT fine-tune on one
  v5e chip").
* The MLP kernels are named so the tensor-parallel rules in
  parallel/sharding.py (Megatron-style column/row split over 'tp') pick
  them up by path.

No pretrained weights ship in this sandbox (no network — SURVEY.md §7); the
module random-initializes unless ``load_hf_weights`` finds a compatible
``.npz``/msgpack on disk. Tokenization for the BERT path lives in
data/bert_tokenizer.py (WordPiece when a vocab file exists, whitespace+hash
fallback otherwise).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# HF BertConfig.layer_norm_eps default — bert-base-uncased ships 1e-12, not
# flax's 1e-6 default. Golden-pinned in tests/test_bert.py.
LN_EPS = 1e-12


class BertSelfAttention(nn.Module):
    hidden_size: int
    num_heads: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        B, L, H = x.shape
        d = H // self.num_heads
        qkv = nn.Dense(
            3 * H, dtype=self.compute_dtype, param_dtype=jnp.float32, name="qkv"
        )(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(B, L, self.num_heads, d)
        q, k, v = split(q), split(k), split(v)

        scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(d)
        bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e30)
        att = jax.nn.softmax(scores.astype(jnp.float32) + bias, axis=-1)
        out = jnp.einsum("bhlm,bmhd->blhd", att.astype(self.compute_dtype), v)
        return nn.Dense(
            H, dtype=self.compute_dtype, param_dtype=jnp.float32, name="out"
        )(out.reshape(B, L, H))


class BertLayer(nn.Module):
    hidden_size: int
    num_heads: int
    intermediate_size: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        att = BertSelfAttention(
            self.hidden_size, self.num_heads, self.compute_dtype, name="attention"
        )(x, mask)
        x = nn.LayerNorm(epsilon=LN_EPS, dtype=jnp.float32, name="ln_att")(x + att)
        h = nn.Dense(
            self.intermediate_size, dtype=self.compute_dtype,
            param_dtype=jnp.float32, name="intermediate",
        )(x)
        # bert-base-uncased's hidden_act is "gelu" — the exact erf form, NOT
        # the tanh approximation (HF calls that one "gelu_new"). Verified
        # numerically against transformers.BertModel in
        # tests/test_bert.py::test_golden_hf_backbone.
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(
            self.hidden_size, dtype=self.compute_dtype,
            param_dtype=jnp.float32, name="mlp_out",
        )(h)
        return nn.LayerNorm(epsilon=LN_EPS, dtype=jnp.float32, name="ln_mlp")(x + h)


class BertBackbone(nn.Module):
    vocab_size: int
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab: int = 2
    remat: bool = False
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, ids: jnp.ndarray, mask: jnp.ndarray, segment_ids=None
    ) -> jnp.ndarray:
        B, L = ids.shape
        word = nn.Embed(
            self.vocab_size, self.hidden_size, param_dtype=jnp.float32, name="tok_emb"
        )(ids)
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.02), (self.max_position, self.hidden_size)
        )[:L]
        seg_table = self.param(
            "seg_emb", nn.initializers.normal(0.02), (self.type_vocab, self.hidden_size)
        )
        # Single-sentence callers (the default) are all segment 0; the pair
        # model passes explicit 0/1 ids for its two-sentence inputs.
        seg = (
            seg_table[0][None, None] if segment_ids is None
            else seg_table[segment_ids]
        )
        x = nn.LayerNorm(epsilon=LN_EPS, dtype=jnp.float32, name="ln_emb")(word + pos[None] + seg)
        x = x.astype(self.compute_dtype)

        layer_cls = nn.remat(BertLayer) if self.remat else BertLayer
        for i in range(self.num_layers):
            x = layer_cls(
                self.hidden_size, self.num_heads, self.intermediate_size,
                self.compute_dtype, name=f"layer_{i}",
            )(x, mask)
        return x  # [B, L, H]


class BertEmbeddingPassthrough(nn.Module):
    """The BERT path owns its token embedding; the InductionNetwork's
    ``embedding(word, pos1, pos2)`` slot just forwards the ids.

    The GloVe-path position-offset features (pos1/pos2) are not consumed
    here — entity position information enters via entity-start pooling in
    BertEncoder instead, mirroring the reference family's BERT variant."""

    @nn.compact
    def __call__(self, word, pos1, pos2):
        del pos1, pos2
        return word  # ids pass through; BertEncoder embeds them itself


class BertEncoder(nn.Module):
    """(ids [M, L], mask [M, L]) -> sentence vectors [M, hidden].

    Pooling: mean of [CLS] (position 0) and the two entity-start hidden
    states when entity markers are present; plain [CLS] otherwise. The
    entity starts arrive encoded in the ids stream by the BERT tokenizer
    (data/bert_tokenizer.py) as known marker ids.
    """

    vocab_size: int
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    intermediate_size: int = 3072
    max_length: int = 128
    frozen: bool = True
    remat: bool = False
    head_marker_id: int = 1  # [E1] == [unused1]; tokenizer contract
    tail_marker_id: int = 2  # [E2] == [unused2]
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        hidden = BertBackbone(
            vocab_size=self.vocab_size,
            num_layers=self.num_layers,
            hidden_size=self.hidden_size,
            num_heads=self.num_heads,
            intermediate_size=self.intermediate_size,
            remat=self.remat,
            compute_dtype=self.compute_dtype,
            name="backbone",
        )(ids, mask)
        if self.frozen:
            # Frozen phase of the frozen->fine-tuned regime: gradients stop
            # at the backbone output; only the induction/relation head trains.
            hidden = jax.lax.stop_gradient(hidden)

        cls_vec = hidden[:, 0]
        # Entity-start pooling: first occurrence of each marker id (static
        # shapes: argmax over a boolean mask, falls back to CLS when absent).
        def marker_vec(marker_id):
            hit = (ids == marker_id) & (mask > 0)
            idx = jnp.argmax(hit, axis=1)                    # 0 when absent
            present = jnp.any(hit, axis=1, keepdims=True)
            vec = jnp.take_along_axis(hidden, idx[:, None, None], axis=1)[:, 0]
            return jnp.where(present, vec, cls_vec)

        pooled = (cls_vec + marker_vec(self.head_marker_id) + marker_vec(self.tail_marker_id)) / 3.0
        return pooled.astype(self.compute_dtype)

    @property
    def output_dim(self) -> int:
        return self.hidden_size


def load_hf_weights(params: dict, npz_path: str) -> dict:
    """Map a flat ``{hf_name: array}`` .npz of bert-base-uncased weights onto
    this module's param tree. Returns a NEW params dict; raises KeyError on
    missing tensors. Name mapping documented here for checkpoint importers:

    bert.embeddings.word_embeddings.weight          -> backbone/tok_emb/embedding
    bert.embeddings.position_embeddings.weight      -> backbone/pos_emb
    bert.embeddings.token_type_embeddings.weight    -> backbone/seg_emb
    bert.embeddings.LayerNorm.{gamma,beta}          -> backbone/ln_emb/{scale,bias}
    ...encoder.layer.N.attention.self.{q,k,v}       -> backbone/layer_N/attention/qkv (fused)
    ...attention.output.dense                       -> backbone/layer_N/attention/out
    ...intermediate.dense / output.dense            -> backbone/layer_N/{intermediate,mlp_out}
    """
    import copy

    raw = dict(np.load(npz_path))

    def ln(prefix: str, which: str):
        # TF-era exports use LayerNorm.gamma/beta; torch state_dicts use
        # LayerNorm.weight/bias. Accept both.
        alt = {"gamma": "weight", "beta": "bias"}[which]
        key = f"{prefix}LayerNorm.{which}"
        return raw[key] if key in raw else raw[f"{prefix}LayerNorm.{alt}"]

    p = copy.deepcopy(params)
    bb = p["params"]["backbone"]
    pre = "bert.embeddings."
    bb["tok_emb"]["embedding"] = raw[pre + "word_embeddings.weight"]
    bb["pos_emb"] = raw[pre + "position_embeddings.weight"]
    bb["seg_emb"] = raw[pre + "token_type_embeddings.weight"]
    bb["ln_emb"]["scale"] = ln(pre, "gamma")
    bb["ln_emb"]["bias"] = ln(pre, "beta")
    i = 0
    while f"layer_{i}" in bb:
        lp = f"bert.encoder.layer.{i}."
        lyr = bb[f"layer_{i}"]
        qkv_w = np.concatenate(
            [raw[lp + f"attention.self.{n}.weight"].T for n in ("query", "key", "value")],
            axis=1,
        )
        qkv_b = np.concatenate(
            [raw[lp + f"attention.self.{n}.bias"] for n in ("query", "key", "value")]
        )
        lyr["attention"]["qkv"]["kernel"] = qkv_w
        lyr["attention"]["qkv"]["bias"] = qkv_b
        lyr["attention"]["out"]["kernel"] = raw[lp + "attention.output.dense.weight"].T
        lyr["attention"]["out"]["bias"] = raw[lp + "attention.output.dense.bias"]
        lyr["ln_att"]["scale"] = ln(lp + "attention.output.", "gamma")
        lyr["ln_att"]["bias"] = ln(lp + "attention.output.", "beta")
        lyr["intermediate"]["kernel"] = raw[lp + "intermediate.dense.weight"].T
        lyr["intermediate"]["bias"] = raw[lp + "intermediate.dense.bias"]
        lyr["mlp_out"]["kernel"] = raw[lp + "output.dense.weight"].T
        lyr["mlp_out"]["bias"] = raw[lp + "output.dense.bias"]
        lyr["ln_mlp"]["scale"] = ln(lp + "output.", "gamma")
        lyr["ln_mlp"]["bias"] = ln(lp + "output.", "beta")
        i += 1
    return p
