"""BERT-PAIR few-shot model (pair).

The FewRel 2.0 NOTA baseline from the reference family (Gao et al., EMNLP
2019): instead of encoding sentences independently, every (query, support)
pair is concatenated at the TOKEN level and scored by the BERT backbone as a
single sequence-pair match; a query's logit for class i aggregates its K
match scores against that class's supports, and none-of-the-above falls out
naturally as a learned threshold against all N aggregated scores.

Layout per pair: ``[CLS] query [SEP] | [CLS] support [SEP]`` — each side is
an already-tokenized fixed-L block (data/bert_tokenizer.py), joined along
the token axis with segment ids 0/1; the pad positions inside each block
stay masked. (Canonical BERT-PAIR re-packs tokens tightly after one [CLS];
with fixed-shape blocks the second [CLS] serves as the separator. With
random-init backbones — no pretrained weights ship in this sandbox — the
distinction is purely conventional; swap the packing if importing HF
weights for exact parity.)

Cost note: this model runs B·TQ·N·K sequences of length 2L through the
backbone per step — quadratic in the episode, exactly like the reference's
BERT-PAIR. Batch sizes must be chosen accordingly.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.bert import BertBackbone


class PairModel(nn.Module):
    vocab_size: int
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    intermediate_size: int = 3072
    frozen: bool = False
    remat: bool = False
    nota: bool = False
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        s_ids, s_mask = support["word"], support["mask"]
        q_ids, q_mask = query["word"], query["mask"]
        B, N, K, L = s_ids.shape
        TQ = q_ids.shape[1]

        with jax.named_scope("pair_build"):
            def pairs(qx, sx):
                q = jnp.broadcast_to(qx[:, :, None, None], (B, TQ, N, K, L))
                s = jnp.broadcast_to(sx[:, None], (B, TQ, N, K, L))
                return jnp.concatenate([q, s], axis=-1).reshape(-1, 2 * L)

            ids = pairs(q_ids, s_ids)
            mask = pairs(q_mask.astype(jnp.float32), s_mask.astype(jnp.float32))
            seg = jnp.concatenate(
                [jnp.zeros((ids.shape[0], L), jnp.int32),
                 jnp.ones((ids.shape[0], L), jnp.int32)], axis=-1
            )

        with jax.named_scope("pair_backbone"):
            hidden = BertBackbone(
                vocab_size=self.vocab_size,
                num_layers=self.num_layers,
                hidden_size=self.hidden_size,
                num_heads=self.num_heads,
                intermediate_size=self.intermediate_size,
                remat=self.remat,
                compute_dtype=self.compute_dtype,
                name="backbone",
            )(ids, mask, segment_ids=seg)
            if self.frozen:
                hidden = jax.lax.stop_gradient(hidden)

        with jax.named_scope("pair_score"):
            match = nn.Dense(
                1, dtype=self.compute_dtype, param_dtype=jnp.float32,
                name="match_head",
            )(hidden[:, 0])[..., 0]                       # [B*TQ*N*K]
            logits = match.reshape(B, TQ, N, K).astype(jnp.float32).mean(-1)

        if self.nota:
            na = self.param("nota_logit", nn.initializers.zeros, (1,))
            na = jnp.broadcast_to(na, (B, TQ, 1))
            logits = jnp.concatenate([logits, na], axis=-1)
        return logits.astype(jnp.float32)
