"""Meta Networks few-shot model (metanet).

Toolkit-family sibling (SURVEY.md §2.1 "Few-shot model" siblings; Munkhdalai
& Yu, ICML 2017, "Meta Networks"). The defining mechanism — fast weights
generated from per-example loss gradients, stored in a memory indexed by
support representations and read by the query through attention — maps to
TPU/JAX cleanly because the per-example gradient of a linear+CE head has a
closed form (no autodiff loop over examples):

1. slow path: ``s_q = e_q @ W_slow`` (an episode-agnostic linear head);
2. per-support meta-gradient, closed form:
   ``G_ij = e_ij ⊗ (softmax(e_ij @ W_slow) - onehot(y_ij))  [H, N]``;
3. fast-weight generation: a learned elementwise transform
   ``F_ij = a2·tanh(a1·G_ij + b1) + b2`` (the paper's shared
   gradient-to-weight meta-learner, in its cheapest shape-agnostic form);
4. memory read: ``α_ij(q) = softmax_{ij} cos(e_q, e_ij)``,
   ``W_fast(q) = Σ_ij α_ij F_ij``;
5. logits = ``s_q + e_q @ W_fast(q)``, differentiable end-to-end (training
   flows through the gradient-generation path — second-order terms kept).

Like gnn/snail, W_slow bakes the N-way width into parameter shapes, so
trainN must equal N (enforced in build_model) and N rides along in
checkpoint config merging.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.models.base import FewShotModel


class MetaNet(FewShotModel):
    @nn.compact
    def __call__(self, support: dict[str, Any], query: dict[str, Any]) -> jnp.ndarray:
        with jax.named_scope("encoder"):
            sup_enc, qry_enc = self.encode_episode(support, query)
        B, N, K, H = sup_enc.shape
        TQ = qry_enc.shape[1]
        cd = self.compute_dtype
        sup = sup_enc.astype(jnp.float32)
        qry = qry_enc.astype(jnp.float32)

        w_slow = self.param(
            "w_slow", nn.initializers.lecun_normal(), (H, N)
        ).astype(jnp.float32)

        with jax.named_scope("meta_gradients"):
            # Closed-form per-example gradient of CE(e @ W_slow, y) wrt
            # W_slow, NEGATED: fast weights must move in the descent
            # direction (toward classifying e_ij as y_ij). With the raw
            # ascent gradient the tanh meta-learner starts anti-correlated
            # and training diverges below chance (observed).
            p = jax.nn.softmax(jnp.einsum("bnkh,hm->bnkm", sup, w_slow), axis=-1)
            y = jnp.broadcast_to(jnp.eye(N)[None, :, None, :], (B, N, K, N))
            G = jnp.einsum("bnkh,bnkm->bnkhm", sup, y - p)       # [B,N,K,H,N]

        with jax.named_scope("fast_weights"):
            a1 = self.param("meta_a1", nn.initializers.ones, (1,))
            b1 = self.param("meta_b1", nn.initializers.zeros, (1,))
            a2 = self.param("meta_a2", nn.initializers.ones, (1,))
            b2 = self.param("meta_b2", nn.initializers.zeros, (1,))
            F = a2 * jnp.tanh(a1 * G + b1) + b2                  # [B,N,K,H,N]

        with jax.named_scope("memory_read"):
            keys = sup.reshape(B, N * K, H)
            norm = lambda x: x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-8)
            att = jnp.einsum("bth,bsh->bts", norm(qry), norm(keys))  # cosine
            att = jax.nn.softmax(att, axis=-1)                   # [B,TQ,N*K]
            F_flat = F.reshape(B, N * K, H, N)
            w_fast = jnp.einsum("bts,bshm->bthm", att, F_flat)   # [B,TQ,H,N]

        with jax.named_scope("combine"):
            slow = jnp.einsum("bth,hm->btm", qry, w_slow)
            fast = jnp.einsum("bth,bthm->btm", qry, w_fast)
            logits = slow + fast

        logits = self.append_nota(logits.astype(jnp.float32))
        return logits.astype(jnp.float32)
