"""Transformer sentence encoder with pluggable (ring-capable) attention.

A fourth encoder family beyond cnn/bilstm/bert (SURVEY.md §1 L4 contract:
``(embedded tokens [M, L, D], mask [M, L]) -> sentence vector [M, H]``).
Unlike the BERT path this one is sized by config (not pinned to
bert-base) and its attention is an injectable function, which is how
long-context sequence parallelism enters the framework: pass
``parallel.ring.make_ring_attention(mesh)`` and the O(L²) softmax runs as a
ring over the mesh's ``sp`` axis with k/v blocks hopping ICI neighbors —
the model code is identical on 1 chip and on a pod.

Pre-LN blocks (stable without warmup at these depths), learned positional
embeddings, masked-mean pooling. All matmuls are [M·L, d] GEMMs on the MXU;
bf16 compute with f32 params/softmax.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from induction_network_on_fewrel_tpu.ops import masked_mean
from induction_network_on_fewrel_tpu.parallel.ring import dense_attention


class TransformerEncoder(nn.Module):
    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 4
    d_ff: int = 1024
    max_length: int = 40
    compute_dtype: jnp.dtype = jnp.float32
    # (q, k, v, kv_mask) -> out, all [M, H, L, hd] / mask [M, L]. None ->
    # dense single-device attention; ring attention for sp-sharded runs.
    attn_impl: Callable | None = None
    # Mixture-of-Experts (models/moe.py): num_experts > 0 swaps the dense
    # MLP for a routed expert layer in every ``moe_every``-th block; experts
    # shard over the mesh's ``ep`` axis.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity: float = 2.0
    moe_every: int = 2
    moe_group_size: int = 512

    @nn.compact
    def __call__(self, emb: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        M, L, _ = emb.shape
        cd = self.compute_dtype
        d, H = self.d_model, self.num_heads
        hd = d // H
        assert d % H == 0, "d_model must divide num_heads"
        attn = self.attn_impl or dense_attention
        dense = lambda dim, name: nn.Dense(
            dim, dtype=cd, param_dtype=jnp.float32, name=name
        )

        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_length, d),
        )
        x = dense(d, "in_proj")(emb.astype(cd)) + pos[None, :L].astype(cd)

        for i in range(self.num_layers):
            h = nn.LayerNorm(dtype=cd, param_dtype=jnp.float32,
                             name=f"ln_att_{i}")(x)
            qkv = dense(3 * d, f"qkv_{i}")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            split = lambda t: t.reshape(M, L, H, hd).transpose(0, 2, 1, 3)
            out = attn(split(q), split(k), split(v), mask)
            out = out.transpose(0, 2, 1, 3).reshape(M, L, d)
            x = x + dense(d, f"att_out_{i}")(out)

            h = nn.LayerNorm(dtype=cd, param_dtype=jnp.float32,
                             name=f"ln_mlp_{i}")(x)
            if self.num_experts > 0 and (i + 1) % self.moe_every == 0:
                from induction_network_on_fewrel_tpu.models.moe import MoeFfn

                # The mask matters here (unlike the dense MLP, which merely
                # wastes FLOPs on pads): routed pads would consume expert
                # capacity slots and skew the load-balance statistics.
                x = x + MoeFfn(
                    num_experts=self.num_experts, d_ff=self.d_ff,
                    top_k=self.moe_top_k, capacity_factor=self.moe_capacity,
                    group_size=self.moe_group_size,
                    compute_dtype=cd, name=f"moe_{i}",
                )(h, mask)
            else:
                # Layer names match the tp partition rules in
                # parallel/sharding.py (intermediate column-sharded, mlp_out
                # row-sharded).
                h = nn.gelu(dense(self.d_ff, f"intermediate_{i}")(h))
                x = x + dense(d, f"mlp_out_{i}")(h)

        x = nn.LayerNorm(dtype=cd, param_dtype=jnp.float32, name="ln_final")(x)
        return masked_mean(x, mask[..., None], axis=-2).astype(cd)

    @property
    def output_dim(self) -> int:
        return self.d_model
