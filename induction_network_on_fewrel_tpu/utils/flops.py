"""Analytic FLOPs/step accounting for MFU reporting.

The perf pillar of this framework is single-chip efficiency, so the bench
reports model FLOPs utilization (MFU) next to episodes/sec: achieved
matmul FLOPs/s divided by the chip's peak. Counting follows the standard
MFU convention (PaLM appendix B / the scaling-book): MATMUL terms only —
elementwise ops, gathers, softmaxes, and the optimizer update are excluded
(they are bandwidth-, not FLOP-, bound), and the training step costs 3x the
forward matmuls (1x forward + 2x backward).

Shapes mirror models/encoders.py + models/induction.py exactly; if a module
changes its contraction structure, update the matching term here (each term
is labeled with its source line).
"""

from __future__ import annotations

from induction_network_on_fewrel_tpu.config import ExperimentConfig

# Peak dense matmul throughput per chip, by jax device_kind fragments.
# v5e ("TPU v5 lite"): 197 TFLOP/s bf16, 99 TFLOP/s f32 (half rate).
_PEAK_BF16 = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_per_chip(device_kind: str, compute_dtype: str) -> float | None:
    """Best-effort peak lookup; None when the chip is unknown (CPU etc.)."""
    kind = device_kind.lower()
    for frag, peak in _PEAK_BF16.items():
        if frag in kind:
            return peak if "bfloat16" in compute_dtype else peak / 2
    return None


def _geometry(cfg: ExperimentConfig):
    B = cfg.batch_size
    N, K = cfg.train_n, cfg.k
    TQ = cfg.train_n * cfg.q + cfg.na_rate * cfg.q
    Ms = B * N * K
    Mq = B * TQ
    return B, N, K, TQ, Ms, Mq


def encoder_forward_flops(cfg: ExperimentConfig, M: float, L: int | None = None) -> float:
    """Forward matmul FLOPs of ``cfg.encoder`` over ``M`` rows of length
    ``L`` (default cfg.max_length). Shapes mirror models/encoders.py,
    models/transformer.py, and models/bert.py."""
    L = L if L is not None else cfg.max_length
    D = cfg.word_dim + 2 * cfg.pos_dim
    if cfg.encoder == "cnn":
        # encoders.py CNNEncoder: Conv1d window 3, D -> hidden_size.
        return 2.0 * M * L * 3 * D * cfg.hidden_size
    if cfg.encoder == "bilstm":
        u, A, H = cfg.lstm_hidden, cfg.att_dim, 2 * cfg.lstm_hidden
        f = 2.0 * M * L * D * (8 * u)            # input projection
        f += 2.0 * M * L * u * (4 * u) * 2       # recurrence, both dirs
        f += 2.0 * M * L * H * A + 2.0 * M * L * A + 2.0 * M * L * H  # attn
        return f
    if cfg.encoder == "transformer":
        dm, ff, nl = cfg.tfm_model, cfg.tfm_ff, cfg.tfm_layers
        f = 2.0 * M * L * D * dm                 # input projection
        per = 4 * 2.0 * M * L * dm * dm          # qkv + out proj
        per += 2 * 2.0 * M * L * L * dm          # scores + att·v
        per += 2 * 2.0 * M * L * dm * ff         # MLP (MoE top-k ~ same
        return f + nl * per                      # per-token ff work)
    if cfg.encoder == "bert":
        dm, ff, nl = cfg.bert_hidden, cfg.bert_intermediate, cfg.bert_layers
        per = 4 * 2.0 * M * L * dm * dm
        per += 2 * 2.0 * M * L * L * dm
        per += 2 * 2.0 * M * L * dm * ff
        return nl * per + 2.0 * M * dm * dm      # + pooler
    raise ValueError(f"no FLOPs model for encoder {cfg.encoder!r}")


def head_forward_flops(cfg: ExperimentConfig, H: float) -> float:
    """Forward matmul FLOPs of the episode head ``cfg.model`` given encoder
    output dim ``H``. Shapes mirror the models/*.py einsums; tiny readouts
    kept, elementwise excluded (MFU convention)."""
    B, N, K, TQ, Ms, Mq = _geometry(cfg)
    m = cfg.model
    if m == "induction":
        C, S = cfg.induction_dim, cfg.ntn_slices
        f = 2.0 * Ms * H * C + 2.0 * Mq * H * C
        f += cfg.routing_iters * 2 * (2.0 * B * N * K * C)
        f += 2.0 * B * N * S * C * C + 2.0 * B * N * S * C * TQ
        f += 2.0 * B * TQ * N * S
        return f
    if m == "proto":
        return 2.0 * B * TQ * N * H
    if m == "siamese":
        return 2.0 * B * TQ * N * K * H
    if m == "proto_hatt":
        k = K
        f = 2.0 * B * N * K * H * k * 32          # conv 1 -> 32
        f += 2.0 * B * N * K * H * k * 32 * 64    # conv 32 -> 64
        f += 2.0 * B * N * H * k * 64             # strided conv 64 -> 1
        f += 2.0 * (Ms + Mq) * H * H              # shared g() projection
        f += 2 * 2.0 * B * TQ * N * K * H         # scores + weighted proto
        f += 2.0 * B * TQ * N * H                 # weighted distance
        return f
    if m == "metanet":
        f = 2.0 * Ms * H * N                      # slow logits on supports
        f += 2.0 * Ms * H * N                     # meta-gradient outer prod
        f += 2.0 * B * TQ * N * K * H             # cosine memory read
        f += 2.0 * B * TQ * N * K * H * N         # fast-weight mix
        f += 2 * 2.0 * Mq * H * N                 # slow + fast logits
        return f
    if m == "gnn":
        G, T = B * TQ, N * K + 1
        P = _gnn_mlp_pairs(T)                     # pairs the edge MLP runs:
        # T(T-1)/2 unordered (the one-hot upper-triangle form) at zoo
        # shapes, T² ordered above the module's one_hot_max_t broadcast
        # fallback (models/gnn.py). ALGORITHMIC terms only here — the
        # one-hot pair-selection/reconstruction matmuls are data movement
        # expressed as matmul and live in head_overhead_flops (ADVICE
        # round 5: counting them as model FLOPs inflated gnn MFU vs the
        # convention every other model uses and broke round-4
        # comparability).
        adj_hidden, F = 64, H + N                 # models/gnn.py defaults
        f = 0.0
        for _ in range(cfg.gnn_blocks + 1):       # blocks + readout layer
            f += 2.0 * G * P * F * adj_hidden               # adjacency MLP
            f += 2.0 * G * P * adj_hidden * adj_hidden
            f += 2.0 * G * P * adj_hidden
            f += 2.0 * G * T * T * F                        # A @ x
            f += 2.0 * G * T * (2 * F) * cfg.gnn_dim        # gc dense
            F += cfg.gnn_dim
        return f
    if m == "snail":
        import math

        G, T = B * TQ, N * K + 1
        F = H + N
        f = 0.0
        levels = max(1, math.ceil(math.log2(T)))
        for kd, vd in ((64, 32), (256, 128), (512, 256)):  # att blocks
            f += 2.0 * G * T * F * (2 * kd + vd)
            f += 2 * 2.0 * G * T * T * (kd + vd)
            F += vd
            if (kd, vd) == (512, 256):
                break
            for _ in range(levels):               # TC block after att 1/2
                f += 2 * 2.0 * G * T * 2 * F * cfg.snail_tc_filters
                F += cfg.snail_tc_filters
        f += 2.0 * G * F * N                      # readout (query position)
        return f
    if m == "pair":
        return 2.0 * B * TQ * N * K * cfg.bert_hidden  # match head, [CLS]
    raise ValueError(f"no FLOPs model for model {cfg.model!r}")


def _gnn_one_hot_form(T: int) -> bool:
    """Whether models/gnn._AdjacencyMLP runs its one-hot form at ``T``
    nodes (above ONE_HOT_MAX_T it falls back to the broadcast pair form).
    Lazy import: flops accounting must not drag flax in for non-gnn use."""
    from induction_network_on_fewrel_tpu.models.gnn import ONE_HOT_MAX_T

    return T <= ONE_HOT_MAX_T


def _gnn_mlp_pairs(T: int) -> int:
    """Rows the adjacency edge MLP processes per graph: the unordered
    upper triangle in the one-hot form, all T² ordered pairs in the
    broadcast fallback."""
    return T * (T - 1) // 2 if _gnn_one_hot_form(T) else T * T


def head_overhead_flops(cfg: ExperimentConfig, H: float) -> float:
    """Forward matmul FLOPs that are IMPLEMENTATION overhead, not model
    math — currently only the gnn's one-hot pair-selection and [T, T]
    reconstruction matmuls (models/gnn.py `_AdjacencyMLP`: gathers
    re-expressed as MXU work because scatters serialize badly on TPU).
    Zero above the module's one_hot_max_t bound, where the broadcast
    fallback runs and no one-hot matmuls exist. Tracked separately so MFU
    keeps the algorithmic-FLOPs convention shared by every other model
    (achieved-matmul throughput = algorithmic + overhead)."""
    if cfg.model != "gnn":
        return 0.0
    B, N, K, TQ, _, _ = _geometry(cfg)
    G, T = B * TQ, N * K + 1
    if not _gnn_one_hot_form(T):
        return 0.0
    P = T * (T - 1) // 2
    F = H + N
    f = 0.0
    for _ in range(cfg.gnn_blocks + 1):
        f += 2 * 2.0 * G * P * T * F              # pair-select one-hots
        f += 2.0 * G * T * T * (P + 1)            # [T, T] reconstruction
        F += cfg.gnn_dim
    return f


def train_step_flops(cfg: ExperimentConfig) -> dict:
    """Analytic matmul FLOPs per optimizer step for ANY (encoder, model)
    config in the zoo. Returns {"forward", "train", "per_episode",
    "overhead_flops"}.

    "forward"/"train"/"per_episode" are ALGORITHMIC (MFU convention,
    comparable across models and rounds); "overhead_flops" is the
    train-time cost of matmuls that only exist as implementation artifacts
    (head_overhead_flops — the gnn one-hot select/reconstruct forms).
    Achieved-matmul throughput on such models is (train + overhead_flops)
    per step; MFU consumers must keep using the algorithmic fields.

    Train multipliers: 3x forward for everything trainable; a FROZEN BERT
    backbone on the token path costs 1x (forward only, no backward); with
    the feature cache the backbone is excluded entirely (encoded once at
    cache build, amortized to ~0 per step).
    """
    B, N, K, TQ, Ms, Mq = _geometry(cfg)
    if cfg.model == "pair":
        # B·TQ·N·K token-level pairs of length 2L through the backbone.
        M_pairs = B * TQ * N * K
        enc = encoder_forward_flops(cfg, M_pairs, L=2 * cfg.max_length)
        head = head_forward_flops(cfg, cfg.bert_hidden)
        enc_mult = 1.0 if cfg.bert_frozen else 3.0
        f_train = enc_mult * enc + 3.0 * head
        return {"forward": enc + head, "train": f_train,
                "per_episode": f_train / B, "overhead_flops": 0.0}
    M = Ms + Mq
    enc = encoder_forward_flops(cfg, M)
    H = (2 * cfg.lstm_hidden if cfg.encoder == "bilstm"
         else cfg.tfm_model if cfg.encoder == "transformer"
         else cfg.bert_hidden if cfg.encoder == "bert"
         else cfg.hidden_size)
    head = head_forward_flops(cfg, H)
    if cfg.encoder == "bert" and cfg.bert_frozen:
        enc_mult = 0.0 if cfg.feature_cache else 1.0
    else:
        enc_mult = 3.0
    f_train = enc_mult * enc + 3.0 * head
    # 3x like the head: a one-hot matmul's backward is another matmul.
    overhead = 3.0 * head_overhead_flops(cfg, H)
    return {"forward": enc + head, "train": f_train,
            "per_episode": f_train / B, "overhead_flops": overhead}


def bilstm_induction_train_flops(cfg: ExperimentConfig) -> dict:
    """Flagship wrapper (bench.py's headline contract): the general
    train_step_flops restricted to the bilstm induction config."""
    if cfg.encoder != "bilstm" or cfg.model != "induction":
        raise ValueError(
            "analytic FLOPs are derived for the bilstm induction flagship; "
            f"got encoder={cfg.encoder!r} model={cfg.model!r}"
        )
    return train_step_flops(cfg)
