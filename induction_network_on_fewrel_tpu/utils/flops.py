"""Analytic FLOPs/step accounting for MFU reporting.

The perf pillar of this framework is single-chip efficiency, so the bench
reports model FLOPs utilization (MFU) next to episodes/sec: achieved
matmul FLOPs/s divided by the chip's peak. Counting follows the standard
MFU convention (PaLM appendix B / the scaling-book): MATMUL terms only —
elementwise ops, gathers, softmaxes, and the optimizer update are excluded
(they are bandwidth-, not FLOP-, bound), and the training step costs 3x the
forward matmuls (1x forward + 2x backward).

Shapes mirror models/encoders.py + models/induction.py exactly; if a module
changes its contraction structure, update the matching term here (each term
is labeled with its source line).
"""

from __future__ import annotations

from induction_network_on_fewrel_tpu.config import ExperimentConfig

# Peak dense matmul throughput per chip, by jax device_kind fragments.
# v5e ("TPU v5 lite"): 197 TFLOP/s bf16, 99 TFLOP/s f32 (half rate).
_PEAK_BF16 = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_per_chip(device_kind: str, compute_dtype: str) -> float | None:
    """Best-effort peak lookup; None when the chip is unknown (CPU etc.)."""
    kind = device_kind.lower()
    for frag, peak in _PEAK_BF16.items():
        if frag in kind:
            return peak if "bfloat16" in compute_dtype else peak / 2
    return None


def bilstm_induction_train_flops(cfg: ExperimentConfig) -> dict:
    """Matmul FLOPs per optimizer step of the flagship BiLSTM induction
    network (batch_size episodes, train-shape rows).

    Returns {"forward": F, "train": 3F, "per_episode": 3F/B}.
    """
    if cfg.encoder != "bilstm" or cfg.model != "induction":
        raise ValueError(
            "analytic FLOPs are derived for the bilstm induction flagship; "
            f"got encoder={cfg.encoder!r} model={cfg.model!r}"
        )
    B = cfg.batch_size
    N, K = cfg.train_n, cfg.k
    TQ = cfg.train_n * cfg.q + cfg.na_rate * cfg.q
    L = cfg.max_length
    D = cfg.word_dim + 2 * cfg.pos_dim          # embedded token dim
    u = cfg.lstm_hidden
    A = cfg.att_dim
    H = 2 * u                                   # encoder output dim
    C = cfg.induction_dim
    S = cfg.ntn_slices

    Ms = B * N * K                              # support rows
    Mq = B * TQ                                 # query rows
    M = Ms + Mq                                 # rows through the encoder

    f = 0.0
    # encoders.py: input projection [M*L, D] x [D, 8u] (both directions).
    f += 2.0 * M * L * D * (8 * u)
    # ops/lstm.py recurrence: per timestep per direction [*, u] x [u, 4u].
    f += 2.0 * M * L * u * (4 * u) * 2
    # encoders.py structured attention: W1 proj, w2 scores, weighted sum.
    f += 2.0 * M * L * H * A + 2.0 * M * L * A + 2.0 * M * L * H
    # induction.py: shared squash transform on support rows [Ms, H] x [H, C],
    # and query_proj on query rows [Mq, H] x [H, C] (InductionNetwork.setup).
    f += 2.0 * Ms * H * C
    f += 2.0 * Mq * H * C
    # induction.py routing: riters x (d·e_hat and e_hat·c contractions).
    f += cfg.routing_iters * 2 * (2.0 * B * N * K * C)
    # induction.py NTN: bnc,hcd->bnhd then bnhd,bqd->bqnh, plus readout.
    f += 2.0 * B * N * S * C * C + 2.0 * B * N * S * C * TQ
    f += 2.0 * B * TQ * N * S
    return {"forward": f, "train": 3.0 * f, "per_episode": 3.0 * f / B}
