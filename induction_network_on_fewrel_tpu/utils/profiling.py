"""Tracing / profiling utilities (SURVEY.md §5.1).

The reference has no profiling beyond wall-clock prints. Here:

* ``trace(logdir)`` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable XPlane trace of device execution.
* ``timed_call`` — block_until_ready-based step timing for honest
  wall-clock numbers under async dispatch.
* ``annotate`` — ``jax.named_scope`` wrapper; the model's encoder /
  induction / relation stages are annotated so HLO ops attribute to stages
  in the profile.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.named_scope


def timed_call(fn, *args, **kw):
    """Run ``fn`` and return ``(out, seconds)`` with the clock stopped only
    after ``jax.block_until_ready(out)`` — honest device time under async
    dispatch, not dispatch time."""
    t0 = time.monotonic()
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    return out, time.monotonic() - t0
