"""Analytic per-component HBM bytes + MXU FLOPs for one flagship train step.

Extracted from tools/roofline_ledger.py (round 6) so the formulas have ONE
home: the ledger tool prints/calibrates against them, and bench.py stamps
``step_bytes`` into its artifact from the same arithmetic — the byte-diet
claims (ISSUE 3) are tracked by the bench gate, not asserted in prose.

Shapes: rows M = B*(N*K + N*Q) support+query concat-encoded; L tokens;
D = word+2*pos embedding width; u LSTM hidden/direction; A att_dim;
C induction_dim; H ntn_slices; bf16 activations (2 B), f32 head +
optimizer (4 B). Backward traffic follows the accepted kernel designs:
the fused BiLSTM backward recomputes gates (re-reads emb and h/c state;
dW/db accumulate in VMEM), and with ``remat_attn`` the attention backward
is the one-pass kernel (H read once, dH written once, the tanh projection
and attention weights rebuilt in VMEM from the [M] softmax stats the
forward saved instead of the [L, M, A] projection).
"""

from __future__ import annotations

from induction_network_on_fewrel_tpu.config import ExperimentConfig


def step_components(
    cfg: ExperimentConfig, remat_attn: bool | None = None
) -> list[tuple[str, float, float]]:
    """[(component, bytes/step, flops/step)] for the flagship train step.

    ``remat_attn`` None follows ``cfg.remat_attn``. The non-remat rows are
    the round-5 ledger unchanged (two-pass attention saving the [L, M, A]
    tanh projection); the remat rows model the recompute-in-backward path
    (ops/attn.py "xla_remat").
    """
    if remat_attn is None:
        remat_attn = getattr(cfg, "remat_attn", False)
    B, N, K, Q, L = cfg.batch_size, cfg.n, cfg.k, cfg.q, cfg.max_length
    TQ = N * Q
    M = B * (N * K + TQ)
    D = cfg.word_dim + 2 * cfg.pos_dim
    u = cfg.lstm_hidden
    A = cfg.att_dim
    C = cfg.induction_dim
    H = cfg.ntn_slices
    bf, f32 = 2, 4

    emb_b = L * M * D * bf          # [L, M, D] bf16, the gathered embedding
    hs_b = L * M * 2 * u * bf       # [L, M, 2u] hidden states
    out_b = M * 2 * u * bf          # [M, 2u] sentence vectors
    rows: list[tuple[str, float, float]] = []

    # L3 embedding: id gathers read the table rows and write emb_t; the
    # windowed pos-offset matmul touches [L+1, L*P] windows (negligible).
    rows.append(("embed gather fwd (write emb + read table)", 2 * emb_b, 0))

    # Fused BiLSTM kernel FWD: reads emb_t once (gates computed in-kernel
    # from the 60-wide embedding), writes hs AND cs (saved for backward —
    # the hs-only variant was evaluated and rejected, ops/lstm.py: the
    # atanh reconstruction of c from h is ill-conditioned at saturation).
    proj_f = 2 * L * M * D * (8 * u)          # input projection, both dirs
    rec_f = 2 * L * M * u * (4 * u) * 2       # recurrence h@whh, both dirs
    rows.append(("bilstm kernel fwd", emb_b + 2 * hs_b, proj_f + rec_f))

    att_f = 2 * L * M * 2 * u * A + 2 * L * M * 2 * u
    if remat_attn:
        # FWD: the two flat-matmul passes read hs twice and write the
        # sentence vectors + [M] softmax stats; the [L, M, A] projection
        # and [L, M] attention weights are NOT saved.
        rows.append((
            "self-attn fwd (remat: stats-only residual)",
            2 * hs_b + out_b + 2 * M * f32, att_f,
        ))
        # BWD: one-pass kernel — hs read once, dH written once, dout/out
        # read for the softmax-backward dot; projection + attention
        # weights rebuilt in VMEM (recompute adds ~1x the forward
        # projection FLOPs on top of the usual 2x-forward backward).
        rows.append((
            "self-attn bwd (kernel recompute)",
            2 * hs_b + 2 * out_b + 2 * M * f32, 3 * att_f,
        ))
    else:
        # Two-pass XLA attention saving the tanh projection: proj pass
        # reads hs, writes [L, M, A]; weighted-sum pass reads hs again.
        rows.append((
            "self-attn fwd", 2 * hs_b + L * M * A * bf + out_b, att_f
        ))
        # BWD re-reads hs three ways (softmax-backward dot, dW1, dH write)
        # plus the saved projection.
        rows.append(("self-attn bwd", 3 * hs_b + L * M * A * bf, 2 * att_f))

    # Episode head FWD (f32): induction transform + routing + NTN.
    ind_f = 2 * B * N * K * 2 * u * C + 3 * (2 * B * N * K * C * 2)
    qp_f = 2 * B * TQ * 2 * u * C
    ntn_f = 2 * B * N * C * C * H + 2 * B * TQ * N * C * H
    head_b = (B * (N * K + TQ) * 2 * u * f32      # enc rows f32
              + B * N * H * C * f32               # cM
              + B * TQ * N * H * f32)             # v
    rows.append(("episode head fwd (f32)", head_b, ind_f + qp_f + ntn_f))
    rows.append(("episode head bwd", 2 * head_b, 2 * (ind_f + qp_f + ntn_f)))

    # Kernel bwd (recompute gates): reads hs, cs, emb, d(hs); writes demb.
    # dW/db accumulate in VMEM -> no HBM term.
    rows.append((
        "bilstm kernel bwd (recompute gates)",
        3 * hs_b + 2 * emb_b, 2 * (proj_f + rec_f) + proj_f,
    ))
    rows.append(("embed scatter bwd (demb -> rows)", 2 * emb_b, 0))

    # Optimizer (f32): non-embedding params p, m, v read + write, grads
    # read. Lazy embed: only the batch's unique rows (<= M*L token ids,
    # bounded by the corpus) touch their table/moment rows.
    n_main = (
        2 * D * 4 * u + 2 * u * 4 * u + 2 * 4 * u      # lstm
        + 2 * u * A + A                                 # attention
        + 2 * u * C + C + 2 * u * C + C                 # induction + qproj
        + H * C * C + H + 1                             # ntn
        + 2 * (2 * L) * cfg.pos_dim                     # pos tables
    )
    rows.append(("optimizer main (Adam, f32)", 7 * n_main * f32, 0))
    u_rows = min(M * L, 2002)   # unique ids, corpus-bounded (synthetic)
    rows.append((
        "lazy embed rows (gather+Adam+scatter)",
        u_rows * cfg.word_dim * f32 * 8, 0,
    ))
    return rows


def step_bytes(cfg: ExperimentConfig, remat_attn: bool | None = None) -> int:
    """Total analytic HBM bytes for one flagship train step."""
    return int(sum(b for _, b, _ in step_components(cfg, remat_attn)))
