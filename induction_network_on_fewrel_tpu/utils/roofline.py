"""Analytic per-component HBM bytes + MXU FLOPs for one flagship train step.

Extracted from tools/roofline_ledger.py (round 6) so the formulas have ONE
home: the ledger tool prints/calibrates against them, and bench.py stamps
``step_bytes`` into its artifact from the same arithmetic — the byte-diet
claims (ISSUE 3) are tracked by the bench gate, not asserted in prose.

Shapes: rows M = B*(N*K + N*Q) support+query concat-encoded; L tokens;
D = word+2*pos embedding width; u LSTM hidden/direction; A att_dim;
C induction_dim; H ntn_slices; bf16 activations (2 B), f32 head +
optimizer (4 B). Backward traffic follows the accepted kernel designs:
the fused BiLSTM backward recomputes gates (re-reads emb and h/c state;
dW/db accumulate in VMEM), and with ``remat_attn`` the attention backward
is the one-pass kernel (H read once, dH written once, the tanh projection
and attention weights rebuilt in VMEM from the [M] softmax stats the
forward saved instead of the [L, M, A] projection).

Round 8 adds the BiLSTM residual knobs (ops/lstm.py windowed-cs remat):
with ``lstm_cs_window = W > 0`` the forward writes one (h, c) checkpoint
pair per W-step window instead of the full cs stream, and the backward
reads d(hs) + the checkpoints + the emb stream only — the in-window
states are recomputed in VMEM (an extra forward recurrence of FLOPs,
cheap: the kernel is bytes-bound). ``lstm_residuals`` sets the STORAGE
dtype of those residual streams/checkpoints independently of the compute
dtype ("auto" follows it). Flagship at W=8 + bf16 residuals: kernel fwd
146 -> 97, kernel bwd 227 -> 113, step 799 -> 635 MB (ROOFLINE_r08).
"""

from __future__ import annotations

from induction_network_on_fewrel_tpu.config import ExperimentConfig


def _residual_itemsize(cfg: ExperimentConfig, lstm_residuals: str | None) -> int:
    """Storage width (bytes) of the BiLSTM residual streams/checkpoints:
    "auto" follows the compute dtype, matching models/build's resolver."""
    if lstm_residuals is None:
        lstm_residuals = getattr(cfg, "lstm_residuals", "auto")
    if lstm_residuals == "auto":
        return 2 if cfg.compute_dtype == "bfloat16" else 4
    return {"f32": 4, "bf16": 2}[lstm_residuals]


def step_components(
    cfg: ExperimentConfig,
    remat_attn: bool | None = None,
    corpus_rows: int | None = None,
    lstm_cs_window: int | None = None,
    lstm_residuals: str | None = None,
) -> list[tuple[str, float, float]]:
    """[(component, bytes/step, flops/step)] for the flagship train step.

    ``corpus_rows``: the real distinct-row count when the caller has it
    (bounds the lazy-embed touched-row term; default = the synthetic
    fixture bound, which understates real 40-60k-row corpora).
    ``remat_attn`` None follows ``cfg.remat_attn``. The non-remat rows are
    the round-5 ledger unchanged (two-pass attention saving the [L, M, A]
    tanh projection); the remat rows model the recompute-in-backward path
    (ops/attn.py "xla_remat").
    ``lstm_cs_window`` / ``lstm_residuals`` (round 8): None follows the
    config; window 0 is the round-6 full-residual kernel, W > 0 the
    windowed-cs remat (module doc). Both model the fused KERNEL design —
    the arithmetic describes the flagship TPU step regardless of which
    backend the local process resolved to (same convention as the rest
    of this ledger).
    """
    if remat_attn is None:
        remat_attn = getattr(cfg, "remat_attn", False)
    if lstm_cs_window is None:
        lstm_cs_window = getattr(cfg, "lstm_cs_window", 0)
    B, N, K, Q, L = cfg.batch_size, cfg.n, cfg.k, cfg.q, cfg.max_length
    TQ = N * Q
    M = B * (N * K + TQ)
    D = cfg.word_dim + 2 * cfg.pos_dim
    u = cfg.lstm_hidden
    A = cfg.att_dim
    C = cfg.induction_dim
    H = cfg.ntn_slices
    bf, f32 = 2, 4

    emb_b = L * M * D * bf          # [L, M, D] bf16, the gathered embedding
    hs_b = L * M * 2 * u * bf       # [L, M, 2u] hidden states
    out_b = M * 2 * u * bf          # [M, 2u] sentence vectors
    rows: list[tuple[str, float, float]] = []

    # L3 embedding: id gathers read the table rows and write emb_t; the
    # windowed pos-offset matmul touches [L+1, L*P] windows (negligible).
    rows.append(("embed gather fwd (write emb + read table)", 2 * emb_b, 0))

    # BiLSTM residual streams (round 8): W = 0 saves the full [L, M, 2u]
    # cs stream (and the backward re-reads hs as a residual too); W > 0
    # saves one (h, c) checkpoint pair per W-step window — ceil(L/W)
    # blocks of [M, 2u] each, stored at the residual dtype. ONE home for
    # the formula: lstm_residual_bytes (the bench diet headline) — the
    # rows below must stay in sync with it by construction.
    W = min(int(lstm_cs_window), L) if lstm_cs_window else 0
    res_b = lstm_residual_bytes(cfg, lstm_cs_window, lstm_residuals)

    # Fused BiLSTM kernel FWD: reads emb_t once (gates computed in-kernel
    # from the 60-wide embedding), writes hs plus the residuals the
    # backward needs — the full cs stream (W=0; the hs-only variant was
    # evaluated and rejected, ops/lstm.py: the atanh reconstruction of c
    # from h is ill-conditioned at saturation) or the windowed (h, c)
    # checkpoint pairs (W>0, 1/W the write traffic).
    proj_f = 2 * L * M * D * (8 * u)          # input projection, both dirs
    rec_f = 2 * L * M * u * (4 * u) * 2       # recurrence h@whh, both dirs
    if W:
        rows.append((
            "bilstm kernel fwd (windowed-cs ckpts)",
            emb_b + hs_b + res_b, proj_f + rec_f,
        ))
    else:
        rows.append(("bilstm kernel fwd", emb_b + hs_b + res_b, proj_f + rec_f))

    att_f = 2 * L * M * 2 * u * A + 2 * L * M * 2 * u
    if remat_attn:
        # FWD: the two flat-matmul passes read hs twice and write the
        # sentence vectors + [M] softmax stats; the [L, M, A] projection
        # and [L, M] attention weights are NOT saved.
        rows.append((
            "self-attn fwd (remat: stats-only residual)",
            2 * hs_b + out_b + 2 * M * f32, att_f,
        ))
        # BWD: one-pass kernel — hs read once, dH written once, dout/out
        # read for the softmax-backward dot; projection + attention
        # weights rebuilt in VMEM (recompute adds ~1x the forward
        # projection FLOPs on top of the usual 2x-forward backward).
        rows.append((
            "self-attn bwd (kernel recompute)",
            2 * hs_b + 2 * out_b + 2 * M * f32, 3 * att_f,
        ))
    else:
        # Two-pass XLA attention saving the tanh projection: proj pass
        # reads hs, writes [L, M, A]; weighted-sum pass reads hs again.
        rows.append((
            "self-attn fwd", 2 * hs_b + L * M * A * bf + out_b, att_f
        ))
        # BWD re-reads hs three ways (softmax-backward dot, dW1, dH write)
        # plus the saved projection.
        rows.append(("self-attn bwd", 3 * hs_b + L * M * A * bf, 2 * att_f))

    # Episode head FWD (f32): induction transform + routing + NTN.
    ind_f = 2 * B * N * K * 2 * u * C + 3 * (2 * B * N * K * C * 2)
    qp_f = 2 * B * TQ * 2 * u * C
    ntn_f = 2 * B * N * C * C * H + 2 * B * TQ * N * C * H
    head_b = (B * (N * K + TQ) * 2 * u * f32      # enc rows f32
              + B * N * H * C * f32               # cM
              + B * TQ * N * H * f32)             # v
    rows.append(("episode head fwd (f32)", head_b, ind_f + qp_f + ntn_f))
    rows.append(("episode head bwd", 2 * head_b, 2 * (ind_f + qp_f + ntn_f)))

    # Kernel bwd. Full-cs (W=0): reads d(hs), hs, cs, emb; writes demb;
    # gates recomputed per step; dW/db accumulate in VMEM -> no HBM term.
    # Windowed (W>0): reads d(hs), the checkpoint pairs, and the emb
    # stream (the [W, tm, D] window block each recompute AND gradient
    # sweep share from VMEM); writes demb. The in-window state replay
    # costs one extra forward recurrence of FLOPs — cheap, the kernel is
    # bytes-bound (ops/lstm.py module doc).
    if W:
        rows.append((
            "bilstm kernel bwd (in-window recompute)",
            hs_b + res_b + 2 * emb_b,
            2 * (proj_f + rec_f) + proj_f + (proj_f + rec_f),
        ))
    else:
        rows.append((
            "bilstm kernel bwd (recompute gates)",
            2 * hs_b + res_b + 2 * emb_b, 2 * (proj_f + rec_f) + proj_f,
        ))
    rows.append(("embed scatter bwd (demb -> rows)", 2 * emb_b, 0))

    # Optimizer (f32): non-embedding params p, m, v read + write, grads
    # read. Lazy embed: only the batch's unique rows (<= M*L token ids,
    # bounded by the corpus) touch their table/moment rows.
    n_main = main_param_count(cfg)
    rows.append(("optimizer main (Adam, f32)", 7 * n_main * f32, 0))
    u_rows = touched_rows(cfg, corpus_rows)
    rows.append((
        "lazy embed rows (gather+Adam+scatter)",
        u_rows * cfg.word_dim * f32 * 8, 0,
    ))
    return rows


def main_param_count(cfg: ExperimentConfig) -> int:
    """Non-embedding (word-table-excluded) param count of the flagship
    BiLSTM induction model — the payload of the dp gradient all-reduce."""
    D = cfg.word_dim + 2 * cfg.pos_dim
    u, A, C, H, L = (
        cfg.lstm_hidden, cfg.att_dim, cfg.induction_dim, cfg.ntn_slices,
        cfg.max_length,
    )
    return (
        2 * D * 4 * u + 2 * u * 4 * u + 2 * 4 * u      # lstm
        + 2 * u * A + A                                 # attention
        + 2 * u * C + C + 2 * u * C + C                 # induction + qproj
        + H * C * C + H + 1                             # ntn
        + 2 * (2 * L) * cfg.pos_dim                     # pos tables
    )


# Distinct-row bound of the SYNTHETIC corpus fixtures (the shapes the
# ledger legs and bench CPU-fallback compile) — callers that know the real
# corpus (the token-cache lazy path has uids in hand) must pass it.
SYNTHETIC_CORPUS_ROWS = 2002


def touched_rows(cfg: ExperimentConfig, corpus_rows: int | None = None) -> int:
    """Unique word-table rows a step can touch: bounded by tokens per
    batch and by the corpus vocabulary. ``corpus_rows`` is the actual
    distinct-row count (len(uids)) when the caller knows it; the default
    is the synthetic-fixture bound — real FewRel corpora run ~40-60k rows,
    so leaving the default in place on real data understates the demb
    term several-fold (round-7 review finding)."""
    bound = corpus_rows if corpus_rows else SYNTHETIC_CORPUS_ROWS
    return min(episode_rows(cfg) * cfg.max_length, bound)


def step_bytes(
    cfg: ExperimentConfig,
    remat_attn: bool | None = None,
    corpus_rows: int | None = None,
    lstm_cs_window: int | None = None,
    lstm_residuals: str | None = None,
) -> int:
    """Total analytic HBM bytes for one flagship train step."""
    return int(sum(
        b for _, b, _ in step_components(
            cfg, remat_attn, corpus_rows, lstm_cs_window, lstm_residuals
        )
    ))


# Public v5e spec numbers — the nominal-silicon projection the ledger
# prints ("projected floor on nominal v5e") and the perf observer stamps
# into kind="perf" records (ISSUE 11). One home; tools/roofline_ledger.py
# aliases these.
NOMINAL_V5E_BW = 819e9      # HBM bytes/s
NOMINAL_V5E_MXU = 197e12    # bf16 FLOP/s
# Inter-chip interconnect: public v5e spec, 1600 Gbps/chip = 200 GB/s.
# The comms ledger's dataflow-window overlap measure (round 10) prices a
# collective's wire time against the HBM time of the independent compute
# scheduled after it using THIS ratio — one home, same nominal-silicon
# convention as the floor projection above.
NOMINAL_V5E_ICI = 200e9     # ICI bytes/s per chip


def projected_floor_ms(
    cfg: ExperimentConfig,
    bw: float = NOMINAL_V5E_BW,
    mxu: float = NOMINAL_V5E_MXU,
    corpus_rows: int | None = None,
) -> float:
    """Analytic per-step time floor (ms) at a given bandwidth/MXU rate:
    each component pays max(bytes/bw, flops/mxu) — the roofline-ledger
    floor formula, extracted so the ledger tool and the online perf
    observer (obs/perf.py kind="perf" ``floor_ms``) share ONE spelling."""
    return sum(
        max(b / bw, f / mxu) * 1e3
        for _, b, f in step_components(cfg, corpus_rows=corpus_rows)
    )


def lstm_residual_bytes(
    cfg: ExperimentConfig,
    lstm_cs_window: int | None = None,
    lstm_residuals: str | None = None,
) -> int:
    """Bytes/step the BiLSTM forward writes SOLELY for the backward (the
    diet headline bench.py stamps): the full [L, M, 2u] cs stream at
    W = 0, or the windowed (h, c) checkpoint pairs — 2 * ceil(L/W)
    blocks of [M, 2u] — at W > 0, in the resolved residual dtype. The
    user-facing hs stream is excluded (the forward writes it
    regardless)."""
    if lstm_cs_window is None:
        lstm_cs_window = getattr(cfg, "lstm_cs_window", 0)
    L, M = cfg.max_length, episode_rows(cfg)
    u = cfg.lstm_hidden
    res = _residual_itemsize(cfg, lstm_residuals)
    W = min(int(lstm_cs_window), L) if lstm_cs_window else 0
    if W:
        return 2 * (-(-L // W)) * M * 2 * u * res
    return L * M * 2 * u * res


# --- collective (ICI) terms — round 7 --------------------------------------
#
# ONE home for the comms arithmetic, shared three ways: bench.py stamps
# comms_bytes_per_step into its artifact, the trainer emits kind="comms"
# telemetry per metric window, and tools/comms_ledger.py asserts the
# compiled flagship HLO against the same numbers (±15%) — the byte-diet
# claim (ISSUE 5) is tracked by arithmetic the compiler is held to, not
# prose. Terms are PAYLOAD bytes/step/device (op output shapes, the same
# convention the ledger counts); wire_bytes applies the ring algorithm
# factors.

# Partitioner resharding slack (episode-batch concat permutes + int-id
# reshards): calibrated against the compiled flagship HLO (~1.8-1.9 MB of
# collective-permute rows in COMMS_r06/r07), not derived — GSPMD's
# scheduling choice, re-checked by the ledger's band every run.
RESHARD_SLACK_BYTES = 2e6


def episode_rows(cfg: ExperimentConfig) -> int:
    """M: support + query sentence rows per batch — the sharded episode
    dim of the [L, M, word_dim] embedding activation."""
    return cfg.batch_size * (cfg.n * cfg.k + cfg.n * cfg.q)


def dense_embedding_allgather_bytes(cfg: ExperimentConfig) -> int:
    """Payload of the dense [L, M, word_dim] f32 embedding-cotangent
    all-gather at cfg's shape — the collective the compact-demb path
    eliminates, and the regression-gate threshold tools/comms_ledger.py
    and tests/test_comms.py hold the compiled HLO under (no single
    collective may reach it)."""
    return cfg.max_length * episode_rows(cfg) * cfg.word_dim * 4


def comms_components(
    cfg: ExperimentConfig,
    dp: int | None = None,
    compact: bool | None = None,
    corpus_rows: int | None = None,
    bucketed: bool | None = None,
) -> list[tuple[str, float]]:
    """[(term, payload bytes/step/device)] for a dp-sharded train step.
    Empty when nothing is sharded (dp <= 1: no collectives).

    ``dp`` defaults to cfg.dp — but cfg.dp=0 means "all devices" at the
    CLI, so mesh-holding callers must pass the resolved mesh axis size.
    ``compact`` defaults to cfg.compact_demb != "off": the dense twin
    (the --compact_demb off A/B leg) replicates the [L, M, word_dim] f32
    embedding cotangent + the int32 ids across dp instead of the compact
    [U, D] all-reduce — modeling BOTH keeps the telemetry honest during
    the exact run whose purpose is comparing the two (COMMS_r06 measured
    the dense flagship at 33.7 MB payload; this arithmetic must agree).
    ``corpus_rows``: the real distinct-row count (len(uids)) when known;
    default is the synthetic-fixture bound ``SYNTHETIC_CORPUS_ROWS``.
    ``bucketed``: the round-10 bucketed-psum arm (grad_bucketing "on",
    parallel/grad_buckets) — fwd+bwd run shard-local inside shard_map,
    so the partitioner inserts NO resharding collectives and the slack
    term drops; the grad/row terms are byte-identical (same payloads,
    explicit named psums). Defaults from cfg.grad_bucketing == "on" (the
    forced arm — "auto" resolution is backend/mesh-dependent and belongs
    to the caller)."""
    dp = cfg.dp if dp is None else dp
    if dp <= 1:
        return []
    if compact is None:
        compact = getattr(cfg, "compact_demb", "auto") != "off"
    if bucketed is None:
        bucketed = getattr(cfg, "grad_bucketing", "auto") == "on"
    f32 = 4
    rows = [
        # dp gradient all-reduce over the non-embedding params, f32.
        ("grad all-reduce (non-emb params, f32)",
         main_param_count(cfg) * f32),
    ]
    M = episode_rows(cfg)
    # The demb collective moves the TABLE-LEAF shape [U, D] — the
    # segment-sum emits (and psums) a full table-rows-sized partial
    # regardless of how few tokens the batch touched (gather_bwd in
    # parallel/sharding.py sums into num_rows = table.shape[0]).
    # touched_rows' min(M*L, corpus) bound is an HBM notion (only
    # gathered/scattered rows move there) and would understate the wire
    # term whenever M*L < corpus rows — small batch on a real 40-60k-row
    # corpus (round-7 review finding).
    u_rows = corpus_rows if corpus_rows else SYNTHETIC_CORPUS_ROWS
    if compact:
        # Compact demb: the [U, D] row-gradient all-reduce
        # (parallel/sharding.make_compact_demb_lookup).
        rows.append((
            "demb compact all-reduce ([U, D] rows, f32)",
            u_rows * cfg.word_dim * f32,
        ))
    else:
        # Dense twin: GSPMD replicates the embedding cotangent (f32
        # [L, M, word_dim] all-gather) + the s32 [M, L] ids before the
        # segment-sum; the [U, D] row gradient still all-reduces.
        rows.append((
            "demb dense all-gather ([L, M, word_dim] f32 + s32 ids)",
            cfg.max_length * M * (cfg.word_dim * f32 + f32),
        ))
        rows.append((
            "demb row all-reduce ([U, D] rows, f32)",
            u_rows * cfg.word_dim * f32,
        ))
    if not bucketed:
        rows.append((
            "resharding (permutes + id reshards, calibrated)",
            RESHARD_SLACK_BYTES,
        ))
    return rows


def comms_payload_bytes(
    cfg: ExperimentConfig,
    dp: int | None = None,
    compact: bool | None = None,
    corpus_rows: int | None = None,
    bucketed: bool | None = None,
) -> float:
    """Total collective payload bytes/step/device (ledger convention)."""
    return sum(
        b for _, b in comms_components(cfg, dp, compact, corpus_rows,
                                       bucketed)
    )


def ring_factor(kind: str, d: int) -> float:
    """Wire bytes per payload byte for ring algorithms at d participants:
    all-reduce moves 2(d-1)/d of its payload, all-gather/reduce-scatter
    (d-1)/d of the gathered/scattered size, permutes and all-to-all ~1x.
    ONE home for the algorithm factor — wire_bytes aggregates with it and
    tools/comms_ledger.py prices individual collectives with it."""
    if d <= 1:
        return 0.0
    if kind == "all-reduce":
        return 2 * (d - 1) / d
    if kind in ("all-gather", "reduce-scatter"):
        return (d - 1) / d
    return 1.0


def wire_bytes(payload_by_kind: dict[str, float], d: int) -> float:
    """Payload -> wire bytes for ring algorithms at d participants (see
    ring_factor). Keys: 'all-reduce' (incl. reduce-scatter), 'all-gather',
    everything else summed under 'other'.
    """
    ar = payload_by_kind.get("all-reduce", 0.0)
    ag = payload_by_kind.get("all-gather", 0.0)
    other = payload_by_kind.get("other", 0.0)
    return (ring_factor("all-reduce", d) * ar
            + ring_factor("all-gather", d) * ag
            + ring_factor("other", d) * other)


def comms_wire_bytes(
    cfg: ExperimentConfig,
    dp: int | None = None,
    compact: bool | None = None,
    corpus_rows: int | None = None,
) -> float:
    """Analytic wire bytes/step/device: the grad/demb-row terms are
    all-reduces, the dense twin's replication is an all-gather, and the
    resharding slack is permute-shaped (~1x)."""
    dp = cfg.dp if dp is None else dp
    if dp <= 1:
        return 0.0
    by_kind = {"all-reduce": 0.0, "all-gather": 0.0, "other": 0.0}
    for name, b in comms_components(cfg, dp, compact, corpus_rows):
        if "all-gather" in name:
            by_kind["all-gather"] += b
        elif "all-reduce" in name:
            by_kind["all-reduce"] += b
        else:
            by_kind["other"] += b
    return wire_bytes(by_kind, dp)
