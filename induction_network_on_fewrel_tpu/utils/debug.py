"""Debug-mode sanitizers (SURVEY.md §5.2).

XLA programs are data-race-free by construction, so the reference-parity
"sanitizer" story on device reduces to numeric checking: ``checkify_step``
wraps a jitted step with ``jax.experimental.checkify`` NaN/index/div checks
(debug runs only — it costs a fused-kernel boundary); ``assert_all_finite``
is a cheap post-hoc host check for metrics dicts.
"""

from __future__ import annotations

import math

import jax
from jax.experimental import checkify


def checkify_step(step_fn):
    """Wrap a step fn; returns (err, out) semantics folded into an exception.

    Usage (debug only):
        step = checkify_step(make_train_step(model, cfg))
        state, metrics = step(state, sup, qry, label)  # raises on NaN/OOB
    """
    # float + div only: index_checks currently mis-instruments the gather
    # inside optax's softmax_cross_entropy_with_integer_labels
    # (take_along_axis -> IndexError during checkify tracing), and NaN/inf
    # detection is the actual debugging use case here.
    checked = checkify.checkify(
        step_fn, errors=checkify.float_checks | checkify.div_checks
    )

    def wrapped(*args, **kw):
        err, out = checked(*args, **kw)
        checkify.check_error(err)
        return out

    return wrapped


def assert_all_finite(metrics: dict, step: int | None = None) -> None:
    bad = {
        k: float(v)
        for k, v in jax.device_get(metrics).items()
        if not math.isfinite(float(v))
    }
    if bad:
        raise FloatingPointError(f"non-finite metrics at step {step}: {bad}")
