"""Structured metrics: stdout + metrics.jsonl (SURVEY.md §5.5).

The reference prints step/loss/acc to stdout; here every record is also
appended as one JSON line so runs are machine-readable (episodes/sec/chip is
the [BJ] throughput metric of record).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path


class MetricsLogger:
    def __init__(self, out_dir: str | Path | None = None, quiet: bool = False,
                 tensorboard_dir: str | Path | None = None):
        self.quiet = quiet
        self.path: Path | None = None
        if out_dir is not None:
            out = Path(out_dir)
            out.mkdir(parents=True, exist_ok=True)
            self.path = out / "metrics.jsonl"
        # Optional TensorBoard scalars (SURVEY.md §5.5). tensorflow is a
        # heavyweight import (~6 s), so it loads only when a dir is given;
        # metrics.jsonl stays the always-on machine-readable record.
        self._tb = None
        if tensorboard_dir is not None:
            import tensorflow as tf  # deferred on purpose

            self._tb = tf.summary.create_file_writer(str(tensorboard_dir))
            self._tf = tf
        self._t0 = time.monotonic()

    def log(self, step: int, kind: str = "train", **scalars: float) -> None:
        rec = {
            "step": int(step),
            "kind": kind,
            "wall_s": round(time.monotonic() - self._t0, 3),
            **{k: float(v) for k, v in scalars.items()},
        }
        if self.path is not None:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            with self._tb.as_default():
                for k, v in scalars.items():
                    self._tf.summary.scalar(
                        f"{kind}/{k}", float(v), step=int(step)
                    )
            self._tb.flush()
        if not self.quiet:
            fields = " ".join(f"{k}={v:.4g}" for k, v in scalars.items())
            print(f"[{kind}] step={step} {fields}", file=sys.stderr, flush=True)
