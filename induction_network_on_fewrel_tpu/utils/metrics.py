"""Structured metrics: stdout + metrics.jsonl (SURVEY.md §5.5).

The reference prints step/loss/acc to stdout; here every record is also
appended as one JSON line so runs are machine-readable (episodes/sec/chip is
the [BJ] throughput metric of record).

This logger is the telemetry spine's single emission point (obs/): every
record also flows through registered hooks — the health watchdog and the
flight recorder attach themselves here, so train/val/serve paths get
watched without instrumenting each emit site. Schema (validated by
``tools/obs_report.py --check``): one JSON object per line with ``step``
(int), ``kind`` (train/val/eval/profile/serve/health/divergence/...),
``wall_s`` (float), and scalar fields; ``kind="health"`` records may carry
string fields (event/severity/message). Non-finite floats are written as
the strings "nan"/"inf"/"-inf" — bare NaN tokens are not valid strict
JSON, and the stream's contract is that ANY JSON-lines consumer (jq,
dashboards) can parse every line; hooks still receive the raw float so
the watchdog's non-finite check sees the real value.
"""

from __future__ import annotations

import json
import math
import sys
import threading
import time
from pathlib import Path
from typing import Callable

# The kinds the telemetry stream is allowed to carry — the contract
# tools/obs_report.py --check enforces. Extend here, not ad hoc.
#
# kind="serve" carries three record shapes since the fleet upgrade
# (ISSUE 7), all scalar-only so the schema contract is unchanged: the
# AGGREGATE counters record (no ``tenant``/``event`` field), one
# PER-TENANT record per registered tenant carrying ``tenant`` (str) with
# that tenant's served/rejected/shed/p50_ms/p99_ms slice, and
# CONTROL-PLANE event records (``event="snapshot_swap"`` with
# params_version/tenants/slots) marking atomic hot-swap publishes.
# tools/obs_report.py's serve section splits on those fields.
KNOWN_KINDS = frozenset({
    "train", "val", "eval", "test", "profile", "serve", "health",
    "divergence", "divergence_stop",
    # Checkpoint telemetry (round 6): one record per ring save with
    # event="ring_save", mode=full|base|delta, bytes=payload bytes, and
    # rows=changed rows for deltas — the delta-ring byte diet, observable.
    "ckpt",
    # Input-pipeline telemetry (ISSUE 4, datapipe/): per-window feed
    # records from the producer pipeline — produced/consumed counters,
    # queue depth, episodes buffered, stall/produce seconds — plus stall
    # ticks emitted while the consumer is blocked (the obs watchdog's
    # feed-stall detector reads these).
    "data",
    # Collective-traffic telemetry (ISSUE 5): one record per metric window
    # on mesh-sharded runs with the ledger arithmetic's per-step bytes
    # (utils/roofline.comms_components — the SAME formulas the compiled-
    # HLO ledger is asserted against): payload_bytes_per_step,
    # wire_bytes_per_step, wire_mb_per_step, dp. obs_report's comms
    # section reads these (headline: wire_mb_per_step).
    "comms",
    # Request-scoped tracing (ISSUE 9): one record per SAMPLED serving
    # request with trace_id (str), tenant (str), scheduler (str), and the
    # segment breakdown in ms — queue_ms (admission -> worker starts
    # stacking), pack_ms (host stack/pad), execute_ms (device program),
    # respond_ms (post-execute host work: batch accounting + per-row
    # verdict build; future delivery falls after the stamp) — whose sum
    # equals
    # total_ms, the request's measured end-to-end latency (same
    # timestamps by construction; obs_report renders the waterfall and
    # checks the sum within 5%). Control-plane actions emit the same kind
    # with op="publish" + publish_ms instead of the request segments. All
    # scalar/str fields — the schema contract is unchanged.
    "trace",
    # Prediction-quality telemetry (ISSUE 10, two record shapes, all
    # scalar/str): (a) per-tenant TRAFFIC records from serving — one per
    # tenant per stats emit with tenant (str), served, nota_rate (NOTA
    # verdict fraction), margin_p50 (top-1 class margin), entropy_p50
    # (softmax entropy of the class scores) — serving/stats.py
    # quality_snapshot; (b) DRIFT-STATE records from obs/drift.py's emit
    # with tenant (str), probe="drift", window, latched, and per feature
    # f in {nota_rate, margin, entropy}: f_base / f_cur / f_band —
    # calibration baseline vs current window vs alert band. obs_report's
    # quality section splits on the ``probe`` field.
    "quality",
    # Scenario-harness results (ISSUE 10, tools/scenarios.py): one record
    # per evaluated scenario leg with leg (str: "in_domain" |
    # "cross_domain" | "da_mixture" | "nota_calibration" | an adversarial
    # perturbation spec), accuracy, acc_ci95, and leg-specific scalars
    # (shift for cross-domain legs, best_f1/best_tau + baseline stats for
    # the NOTA calibration, the perturbation rate for adversarial legs).
    # The SCENARIOS_r*.json artifact carries the same numbers; the
    # records exist so a scenarios run is a first-class telemetry run
    # (obs_report renders a scenarios section, --check validates).
    "scenario",
    # HBM-roofline telemetry (ISSUE 6): one record per metric window on
    # BiLSTM runs with the shared step-byte arithmetic at this config's
    # residual knobs (utils/roofline.step_bytes — the SAME formulas
    # bench.py stamps and ROOFLINE_r*.json records): step_bytes, step_mb,
    # lstm_residual_bytes, lstm_cs_window, and corpus_rows when the real
    # corpus bound is in hand (token-cache runs — obs_report rebuilds the
    # component table at the same bound). The numbers model the fused-
    # kernel flagship step AT THIS CONFIG (bench convention), whatever
    # backend the local process resolved — obs_report's roofline section
    # reads them (headline: step_mb) and rebuilds the per-component table
    # from config.json.
    "roofline",
    # Step-time decomposition (ISSUE 11, obs/perf.py): one record per
    # metric window with the host-observed segments that TILE the window
    # — data_wait_ms / host_dispatch_ms / device_sync_ms / checkpoint_ms /
    # eval_ms / probe_ms / other_ms sum to window_s * 1e3 exactly
    # (segments_sum_ms restates the sum so the report can verify) — plus
    # steps, step_ms, overlapping context (compiles, compile_ms, gc_ms,
    # gc_collections), the rolling baseline_step_ms, the shared roofline
    # projection (floor_ms / device_over_floor when configured), and
    # out-of-band classification: oob (0/1) and cause (str, one of
    # obs/perf.CAUSES) on slow windows. obs_report's perf section reads
    # these (headline: segment fractions + the cause table).
    "perf",
    # Fault-domain telemetry (ISSUE 12, obs/chaos.py + the containment
    # layer): one record per INJECTED fault (action="inject" with point
    # (str, an obs/chaos.KNOWN_POINTS name), seq, and the point's context
    # fields — tenant on serving points, ckpt_kind on checkpoint points)
    # and one per CONTAINMENT action:
    # action="ckpt_quarantine" (ckpt_kind, ckpt_step, reason — a corrupt
    # slot renamed aside, never silently purged), action="breaker"
    # (tenant, from, to, failures — circuit-breaker transitions;
    # to="open" trips the once-latched breaker_open CRITICAL),
    # action="execute_error" (tenant, requests — a failed launch failing
    # ONLY its batch's futures), action="publish_rollback" (reason,
    # params_version — a refused/failed publish rolled back with every
    # tenant on its old snapshot), action="tenant_quarantine" /
    # "tenant_restore" (tenant, reason — degraded-mode routing), and
    # action="degraded_verdicts" (tenant, served — open-set-floor NOTA
    # verdicts served while quarantined). All scalar/str fields;
    # obs_report's faults section renders injections and reactions side
    # by side.
    # Durable-control-plane actions (ISSUE 15, fleet/journal.py +
    # router recovery + fleet/supervisor.py):
    # action="journal_truncated" (reason, bytes_dropped, records_kept —
    # a torn/corrupt WAL tail truncated at the bad record; everything
    # before it replays), action="recovered" (tenants, reregistered,
    # unplaceable, caught_up, params_version, journal_records,
    # snapshot_seq — one cold-start recovery summary per
    # FleetRouter.recover), action="catchup" (replica, from_version,
    # to_version — a stale replica re-driven to the journaled committed
    # generation via the zero-recompile publish),
    # action="replica_restarted" (replica, ok 0/1, attempt, reason on
    # failure — one per supervised restart attempt),
    # action="replica_restart_exhausted" (replica, attempts — the
    # bounded restart budget burned out; the replica is permanent-dead
    # and failover owns its tenants), and
    # action="supervisor_poll_error" (reason — a supervision pass
    # raised and was contained; silence here would make a broken
    # supervisor look healthy). obs_report's recovery section reads
    # these.
    "fault",
    # Fleet-tier telemetry (ISSUE 13, fleet/router.py + fleet/control.py,
    # three record shapes, all scalar/str): (a) the AGGREGATE router
    # record (no ``replica``/``event`` field) with replicas / live /
    # dead / tenants / submitted / shed (fleet-share door sheds) /
    # degraded_served (failover NOTA verdicts served at the router) /
    # replica_deaths / replaced (tenants re-registered after membership
    # or health changes — cumulative placement churn) /
    # pending_failover / inflight; (b) one PER-REPLICA record per emit
    # carrying ``replica`` (str) and ``state`` (up/draining/dead) with
    # that replica's routed count and serving counters (served / p50_ms
    # / p99_ms / batch_occupancy / steady_recompiles / queue_depth);
    # (c) EVENT records: event="fanout_publish" (publish_s, replicas,
    # params_version — the all-or-nothing fleet publish),
    # event="replica_add" and event="replace" (moved, tenants —
    # re-placement churn), event="journal_compact" (snapshot_seq,
    # tenants — the fleet journal folded its WAL into snapshot.json,
    # ISSUE 15), and event="journal_op" (op, seq — one per WAL append,
    # ISSUE 17: the journal payload itself carries no timestamp by the
    # deterministic-replay contract, so THIS record is where a control-
    # plane decision acquires a wall-clock position on the fleet
    # timeline; tools/fleet_report.py cross-checks op/seq against the
    # replayed WAL). Replica-death containment emits kind="fault"
    # action="replica_dead"/"replica_recover" next to these. The
    # PER-REPLICA shape grew fleet-rollup fields in ISSUE 17: qps
    # (served delta over the emit interval), shed, deadline_missed,
    # and breaker (str: closed/open/half_open — the router's view).
    # tools/obs_report.py's fleet section splits on replica/event.
    "fleet",
    # Cross-process hop telemetry (ISSUE 17, fleet/router.py): one
    # record per SAMPLED routed request with trace_id (str), tenant
    # (str), replica (str), and the router-side segment breakdown in ms
    # — route_ms (placement + breaker/door admission), queue_ms
    # (handle.submit: serialization + socket write + local pool queue),
    # wire_ms (round-trip residual after subtracting the replica's own
    # measured total), remote_ms (the replica-reported end-to-end
    # latency_ms for this request), respond_ms (router-side completion
    # accounting) — whose sum equals router_ms, the request's measured
    # fleet-level latency (same timestamps by construction, the PR 8
    # segments-sum-exactly discipline). hop_ms = router_ms − remote_ms
    # is the fleet tax: everything the hop added on top of the replica.
    # offset_ms is the NTP-style estimated clock offset to that replica
    # (fleet/transport.ClockSync rolling median; 0.0 for in-process
    # handles) — used by tools/fleet_report.py to align replica-side
    # absolute timestamps onto the router timeline, and gated by its
    # --check skew bound. All scalar/str — the schema contract holds.
    "hop",
    # Elasticity telemetry (ISSUE 16, fleet/autoscaler.py +
    # fleet/standby.py), three record shapes, all scalar/str: (a) one
    # TICK record per autoscaler policy evaluation (no ``event`` field)
    # with replicas / live / occupancy (mean batch fill across UP
    # replicas) / queue_depth (mean) / shed_delta (router door sheds
    # since the last tick) / burn_fast (max fast-window burn rate
    # across SLO tenants, 0 when no SLO engine) / pressure + idle (0/1
    # — this tick's classification) / high_streak + low_streak (the
    # hysteresis counters) / action (str: none / cooldown / pending /
    # scale_out / drain_in / at_max / at_min) — the replica-count
    # timeline obs_report's elasticity section renders; (b) EVENT
    # records: event="scale_out" (replica, scale_s, warm_compiles,
    # occupancy / shed_delta / burn_fast at decision time — the trigger
    # signals), event="drain_in" (replica, drain_s, moved), and
    # event="promotion" (promote_s, tenants, replicas, applied — the
    # standby took the front door after catch-up replay); (c) the
    # standby's TAIL record event="tail" (applied, lag — ops behind the
    # primary's journal at poll time). A stuck scale decision emits
    # kind="fault" action="scale_stuck" (direction, reason, waited_s,
    # budget_s) next to these — once-latched CRITICAL, re-armed by the
    # next completed scale event.
    "scale",
    # Self-healing adaptation telemetry (ISSUE 14, obs/adapt.py): one
    # record per controller action, all scalar/str with ``action`` (str),
    # ``tenant`` (str), ``state`` (the machine state after the action),
    # ``attempt`` (1-based within the current adaptation loop):
    # action="trigger" (feature, reason — a drift CRITICAL armed the
    # loop), action="train" (ok 0/1, steps, train_s — the mixture-ramp
    # fine-tune; ok=0 carries error), action="canary" (passed 0/1,
    # failures — the scenario-harness quality floors as a hard
    # pre-publish gate; failed candidates are discarded, never
    # published), action="publish" (params_version, publish_s — the
    # committed hot-swap/fan-out), action="verified" (recover_s —
    # trigger-to-back-in-band wall time, the section headline; nota_base
    # / nota_healthy / nota_band restate the in-band check),
    # action="rollback" (reason, params_version — post-publish drift
    # re-tripped inside the verification window; the prior artifact was
    # republished), and action="exhausted" (attempts — the flap damper:
    # the retry budget burned out, the tenant is quarantined and the
    # permanent adapt_exhausted CRITICAL latched). obs_report's adapt
    # section renders the loop outcome table from these.
    "adapt",
    # XLA compile forensics (ISSUE 11, obs/compile.py): one record per
    # observed backend compile with fn (str, the jitted function), shapes
    # (str, the argument shape signature), elapsed_ms, trigger (str, the
    # innermost open host span — which code path paid), phase (str:
    # warmup = first compile of a fn; recompile = a SEEN fn compiling a
    # NEW signature, the steady-state invariant breach; dup = a seen
    # (fn, signature) pair re-compiling), and trace_id when a trace was
    # active. The training twin of serving's steady_recompiles counter.
    "compile",
})


def json_sanitize(v):
    """Strict-JSON-safe scalar: non-finite floats become their repr
    strings ('nan'/'inf'/'-inf'). Shared with the flight recorder so every
    emitted artifact stays parseable by non-Python consumers."""
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    return v


class MetricsLogger:
    def __init__(self, out_dir: str | Path | None = None, quiet: bool = False,
                 tensorboard_dir: str | Path | None = None):
        self.quiet = quiet
        self.path: Path | None = None
        # Persistent append handle: reopening metrics.jsonl per record cost
        # one open/close syscall pair per log() — measurable at fused-call
        # logging rates. Opened lazily on first log so a logger constructed
        # for a dir that is never written leaves no empty file. Lock: the
        # serving batcher worker and the main thread both log through one
        # logger; the per-call open of the old code was implicitly atomic,
        # the shared handle is not.
        self._fh = None
        self._io_lock = threading.Lock()
        if out_dir is not None:
            out = Path(out_dir)
            out.mkdir(parents=True, exist_ok=True)
            self.path = out / "metrics.jsonl"
        self.hooks: list[Callable[[dict], None]] = []
        # Process identity (ISSUE 17): when set, every record carries
        # proc_role/proc_replica/proc_pid plus t_unix (absolute wall
        # clock) so a multi-process fleet's streams can be merged into
        # one causally-ordered timeline (tools/fleet_report.py).
        # Default-off: single-process runs keep their exact old shape.
        self._identity: dict[str, object] = {}
        # Optional TensorBoard scalars (SURVEY.md §5.5). tensorflow is a
        # heavyweight import (~6 s), so it loads only when a dir is given;
        # metrics.jsonl stays the always-on machine-readable record.
        self._tb = None
        if tensorboard_dir is not None:
            import tensorflow as tf  # deferred on purpose

            self._tb = tf.summary.create_file_writer(str(tensorboard_dir))
            self._tf = tf
        self._t0 = time.monotonic()

    def add_hook(self, hook: Callable[[dict], None]) -> None:
        """Register a per-record observer (watchdog, flight recorder)."""
        if hook not in self.hooks:
            self.hooks.append(hook)

    def set_identity(self, role: str, replica: str | None = None) -> None:
        """Stamp process identity on every subsequent record (ISSUE 17):
        proc_role (router/serve/standby), proc_replica when this logger
        belongs to one replica, proc_pid, and a per-record t_unix
        absolute timestamp. ``wall_s`` stays monotonic-relative (the
        in-process ordering key); t_unix is the CROSS-process key —
        comparable across streams only up to clock offset, which the
        hop records carry (fleet/transport.ClockSync)."""
        import os

        ident: dict[str, object] = {
            "proc_role": str(role), "proc_pid": os.getpid(),
        }
        if replica is not None:
            ident["proc_replica"] = str(replica)
        self._identity = ident

    def log(self, step: int, kind: str = "train", **scalars) -> None:
        rec = {
            "step": int(step),
            "kind": kind,
            "wall_s": round(time.monotonic() - self._t0, 3),
        }
        if self._identity:
            rec.update(self._identity)
            rec["t_unix"] = round(time.time(), 6)
        rec.update({k: _coerce(v) for k, v in scalars.items()})
        if self.path is not None:
            line = json.dumps(
                {k: json_sanitize(v) for k, v in rec.items()}
            ) + "\n"
            with self._io_lock:
                if self._fh is None or self._fh.closed:
                    self._fh = open(self.path, "a")
                self._fh.write(line)
                self._fh.flush()  # flush per record: crash-visible telemetry
        if self._tb is not None:
            with self._io_lock:
                with self._tb.as_default():
                    for k, v in scalars.items():
                        if isinstance(v, str):
                            continue
                        self._tf.summary.scalar(
                            f"{kind}/{k}", float(v), step=int(step)
                        )
                self._tb.flush()
        if not self.quiet:
            fields = " ".join(
                f"{k}={v}" if isinstance(v, str) else f"{k}={v:.4g}"
                for k, v in rec.items()
                if k not in ("step", "kind", "wall_s", "proc_role",
                             "proc_replica", "proc_pid", "t_unix")
            )
            print(f"[{kind}] step={step} {fields}", file=sys.stderr, flush=True)
        for hook in self.hooks:
            hook(rec)  # raw floats on purpose: NaN must reach the watchdog

    def close(self) -> None:
        """Release the file handle (and TB writer). Safe to call repeatedly.
        A log() after close transparently reopens the jsonl handle in
        append mode; the TensorBoard writer is NOT reopened — TB is a
        mirror, and the always-on record is metrics.jsonl."""
        with self._io_lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()
            if self._tb is not None:
                self._tb.close()
                self._tb = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _coerce(v):
    """float for numerics, passthrough for strings (health-event fields)."""
    if isinstance(v, str):
        return v
    return float(v)
