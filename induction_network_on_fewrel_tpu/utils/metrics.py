"""Structured metrics: stdout + metrics.jsonl (SURVEY.md §5.5).

The reference prints step/loss/acc to stdout; here every record is also
appended as one JSON line so runs are machine-readable (episodes/sec/chip is
the [BJ] throughput metric of record).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path


class MetricsLogger:
    def __init__(self, out_dir: str | Path | None = None, quiet: bool = False):
        self.quiet = quiet
        self.path: Path | None = None
        if out_dir is not None:
            out = Path(out_dir)
            out.mkdir(parents=True, exist_ok=True)
            self.path = out / "metrics.jsonl"
        self._t0 = time.monotonic()

    def log(self, step: int, kind: str = "train", **scalars: float) -> None:
        rec = {
            "step": int(step),
            "kind": kind,
            "wall_s": round(time.monotonic() - self._t0, 3),
            **{k: float(v) for k, v in scalars.items()},
        }
        if self.path is not None:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        if not self.quiet:
            fields = " ".join(f"{k}={v:.4g}" for k, v in scalars.items())
            print(f"[{kind}] step={step} {fields}", file=sys.stderr, flush=True)
