from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger  # noqa: F401
