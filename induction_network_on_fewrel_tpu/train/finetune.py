"""Targeted mixture-ramp fine-tune from a live checkpoint (ISSUE 14).

The remediation arm of the self-healing loop (obs/adapt.py): when a
tenant's traffic drifts out of the training domain, the cure
SCENARIOS_r01 proved is a mixture curriculum — keep the source corpus at
full weight while the target domain ramps in (Gao et al. 2019's FewRel
2.0 wiki+pubmed recipe, Geng et al. 2019's episode construction). This
module packages that as one BOUNDED, KILLABLE job:

* resumes the FULL train state (params + optimizer moments) from the
  live checkpoint — a fine-tune, not a retrain;
* feeds a ``MixtureSampler`` under ``MixtureSchedule.ramp`` through a
  ``PipelineFeed`` (host sampling overlaps dispatch, exactly the
  production input pipeline) into the stock ``FewShotTrainer``;
* saves through the trainer's ring path (``save_latest`` — the
  delta-ring saver where the state qualifies), so the candidate
  directory restores through the SAME integrity-checked machinery every
  other checkpoint does (``publish_checkpoint`` fan-out,
  ``InferenceEngine.from_checkpoint``);
* enforces a STEP budget and a WALL-CLOCK budget: training runs in
  chunks, the clock is checked between chunks, and a breach KILLS the
  job — the partial candidate directory is deleted (checkpoint cleanup)
  and ``AdaptTrainTimeout`` raised, which the controller counts as a
  failed attempt.

Known cost of the chunked spelling: every ``trainer.train`` call ends
with the trainer's terminal forced ring save + sync, so the default 4
chunks pay 4 boundary saves where only the last matters (~nothing at
the drill's miniature size; at flagship checkpoint size prefer a larger
``chunk`` or trade budget granularity — recorded with the round-15 chip
A/Bs in BASELINE.md).
"""

from __future__ import annotations

import shutil
import time
from pathlib import Path


class AdaptTrainTimeout(RuntimeError):
    """The fine-tune breached its wall-clock budget and was killed; the
    candidate checkpoint directory has been cleaned up."""


def mixture_finetune(
    ckpt_dir: str,
    out_dir: str,
    src_ds,
    tgt_ds,
    tok,
    *,
    steps: int,
    wall_budget_s: float,
    ramp_frac: float = 0.6,
    start_weight: float = 0.2,
    seed: int = 0,
    prefetch_depth: int = 2,
    chunk: int | None = None,
    lr: float | None = None,
    logger=None,
) -> str:
    """Fine-tune the artifact in ``ckpt_dir`` on a src+tgt mixture ramp;
    returns ``out_dir`` (a publishable checkpoint directory). ``steps``
    is the optimizer-step budget; ``wall_budget_s`` the wall-clock
    budget (checked between chunks of ``chunk`` steps — default
    steps/4); ``ramp_frac`` places the parity point of the target ramp.
    ``src_ds``/``tgt_ds`` are FewRel-schema datasets; episode geometry
    and architecture come from the checkpoint's stored config."""
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if wall_budget_s <= 0:
        raise ValueError(f"wall_budget_s must be > 0, got {wall_budget_s}")
    from induction_network_on_fewrel_tpu.datapipe.mixture import (
        MixtureSampler,
        MixtureSchedule,
    )
    from induction_network_on_fewrel_tpu.datapipe.producer import (
        PipelineFeed,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.framework import (
        FewShotTrainer,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    t0 = time.monotonic()
    cfg = CheckpointManager.load_config(ckpt_dir)
    # Runtime knobs for the fine-tune job: no val loop (the canary is
    # the quality gate), single-dispatch steps (the budget is exact),
    # the caller's input-pipeline depth. Architecture fields untouched —
    # the candidate must restore into the serving engine's model.
    cfg = cfg.replace(
        val_step=0, steps_per_call=1, prefetch_depth=prefetch_depth,
        train_iter=steps, **({"lr": lr} if lr is not None else {}),
    )
    model = build_model(cfg)
    state = init_state(
        model, cfg,
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, cfg.total_q)),
    )
    src_mngr = CheckpointManager(ckpt_dir, cfg)
    try:
        try:
            state, start_step = src_mngr.restore_best(state)
        except FileNotFoundError:
            state, start_step = src_mngr.restore_latest(state)
    finally:
        src_mngr.close()

    schedule = MixtureSchedule.ramp(
        start_weight=start_weight,
        parity_at=max(int(steps * ramp_frac), 1),
    )
    mix = MixtureSampler(
        [("src", EpisodeSampler(
            src_ds, tok, n=cfg.n, k=cfg.k, q=cfg.q,
            batch_size=cfg.batch_size, na_rate=cfg.na_rate,
            seed=seed + 1)),
         ("tgt", EpisodeSampler(
             tgt_ds, tok, n=cfg.n, k=cfg.k, q=cfg.q,
             batch_size=cfg.batch_size, na_rate=cfg.na_rate,
             seed=seed + 2))],
        schedule, seed=seed,
    )
    feed = PipelineFeed(mix, prefetch_depth=prefetch_depth)
    trainer = FewShotTrainer(
        model, cfg, feed, ckpt_dir=out_dir,
        logger=logger if logger is not None else MetricsLogger(quiet=True),
    )
    chunk = max(1, steps // 4) if chunk is None else max(1, chunk)
    done = 0
    try:
        while done < steps:
            if time.monotonic() - t0 > wall_budget_s:
                raise AdaptTrainTimeout(
                    f"fine-tune killed at {done}/{steps} steps: wall "
                    f"budget {wall_budget_s}s breached "
                    f"({time.monotonic() - t0:.1f}s elapsed); candidate "
                    f"{out_dir} cleaned up"
                )
            n = min(chunk, steps - done)
            state = trainer.train(
                state, num_iters=n, start_step=start_step + done
            )
            done += n
    except BaseException:
        # Timeout-kill + checkpoint cleanup: a partial candidate must
        # never be publishable by accident.
        trainer.close()
        shutil.rmtree(out_dir, ignore_errors=True)
        raise
    trainer.close()
    return str(Path(out_dir))
