"""Device-resident token cache: upload the tokenized dataset once, stream
only episode INDICES per step.

Profiling the flagship bench config (XPlane, v5e, 2026-07-30) showed the
device busy only ~1.3 ms of a ~4.3 ms wall step even at steps_per_call=64:
the residual cost is host batch assembly plus the token batch crossing the
tunneled host->device link (~6 MB per fused dispatch). But the dataset the
batches are drawn from is tiny and static — FewRel train_wiki tokenizes to
~16 MB — so the TPU-native layout is the same one the frozen-BERT feature
cache uses (train/feature_cache.py), one level lower:

1. ``tokenize_dataset`` — run the tokenizer over every instance once,
   yielding one flat token table ``{word i32, pos1 i16, pos2 i16, mask i8}
   [M_total, L]`` plus per-relation row counts. ``jax.device_put`` it once.
2. ``FeatureEpisodeSampler(sizes, ...)`` in index mode — identical episode
   statistics to the live sampler; per step only ``[B,N,K] + [B,TQ]`` int32
   indices cross the link (~1 KB vs ~100 KB per step).
3. The step gathers token rows ON DEVICE (``table[word][idx]`` inside jit)
   and feeds the unchanged model — same math, same shapes, same episode
   distribution; only the transport changed.

Unlike the feature cache this is encoder-agnostic (the encoder still runs,
trains, and backprops every step) and leaves the TrainState untouched, so
checkpoints are interchangeable with the live-sampler path. Excluded:
``pair`` (consumes token pairs, different input contract) and ``--adv``
(domain samplers stream unlabeled instances separately).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset


def tokenize_dataset(
    dataset: FewRelDataset, tokenizer
) -> tuple[dict[str, np.ndarray], list[int]]:
    """Tokenize every instance once -> (flat token table, per-relation rows).

    Wire dtypes match models/build.py's narrowing: pos offsets live in
    [0, 2*max_length) (int16), mask in {0,1} (int8); word ids stay int32.
    """
    toks, rel_sizes = [], []
    for rel in dataset.rel_names:
        insts = dataset.instances[rel]
        rel_sizes.append(len(insts))
        toks.extend(tokenizer(inst) for inst in insts)
    table = {
        "word": np.stack([t.word for t in toks]).astype(np.int32),
        "pos1": np.stack([t.pos1 for t in toks]).astype(np.int16),
        "pos2": np.stack([t.pos2 for t in toks]).astype(np.int16),
        "mask": np.stack([t.mask for t in toks]).astype(np.int8),
    }
    return _compact_pos_offsets(table), rel_sizes


def _compact_pos_offsets(table: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Collapse per-token position ids to per-SENTENCE offsets when exact.

    The GloVe tokenizer's ids are ``pos[l] = clip(l - head, -L, L-1) + L``
    with head clamped into [0, L), so the clip NEVER binds and
    ``pos[l] == pos[0] + l`` holds for every row — verified numerically
    here, never assumed (the BERT tokenizer's entity markers break it, in
    which case the table is returned unchanged). With the offsets form the
    embedding layer reconstructs position vectors via a tiny windowed
    one-hot matmul over the [2L, pos_dim] table (models/embedding.py)
    instead of a [tokens]-row gather — profiled: the two full-width pos
    gathers were ~9% of headline device time (tools/profile_headline.py,
    round 4)."""
    L = table["pos1"].shape[-1]
    idx = np.arange(L, dtype=np.int32)
    out = dict(table)
    for key in ("pos1", "pos2"):
        pos = table[key].astype(np.int32)
        if np.array_equal(pos, pos[:, :1] + idx):
            out[key] = pos[:, 0].astype(np.int16)  # rank-1 = offset form
    return out


def _gather(table: dict[str, Any], idx):
    # "uids" is table-level metadata (the lazy-embed corpus vocabulary,
    # lazy_embed.augment_token_table), not a per-row column — never gather
    # it by row index.
    return {k: v[idx] for k, v in table.items() if k != "uids"}


def _lazy_cached(model, cfg, mesh=None):
    """The token-cache lazy-embed body, or None when cfg doesn't use it."""
    if getattr(cfg, "embed_optimizer", "shared") != "lazy":
        return None
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        make_lazy_cached_update_body,
    )

    return make_lazy_cached_update_body(model, cfg, mesh=mesh)


def make_token_cached_train_step(model, cfg, mesh=None, state_example=None):
    """jitted (state, table dict, sup_idx, qry_idx, label) -> (state, metrics).

    The table is a jit ARGUMENT (device_put once by the caller), never a
    closure — closed-over arrays bake into the program as constants and
    blow the compile-RPC payload on tunneled backends.
    """
    import jax

    from induction_network_on_fewrel_tpu.train.steps import make_update_body

    lazy = _lazy_cached(model, cfg, mesh=mesh)
    body = (
        make_update_body(model, cfg, mesh=mesh) if lazy is None else None
    )

    def step(state, table, sup_idx, qry_idx, label):
        sup, qry = _gather(table, sup_idx), _gather(table, qry_idx)
        if lazy is not None:
            return lazy(state, (sup, qry, label, table["uids"]))
        return body(state, (sup, qry, label))

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))
    return _shard(
        step, mesh, state_example, zero_opt=getattr(cfg, "zero_opt", False)
    )


def make_token_cached_multi_train_step(model, cfg, mesh=None, state_example=None):
    """steps_per_call twin: scan S stacked index batches against one table.

    Lazy-embed mode uses the HOISTED scan (lazy_embed.make_lazy_cached_scan_fns):
    the dense-table gather/catch-up/scatter runs once per fused call instead
    of per step, with the compact corpus rows riding the scan carry —
    identical trajectory (the per-step round-trip is the identity inside the
    call), ~9% of headline device time removed.
    """
    import jax

    from induction_network_on_fewrel_tpu.train.steps import make_update_body

    if getattr(cfg, "embed_optimizer", "shared") == "lazy":
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            make_lazy_cached_scan_fns,
        )

        prologue, compact, epilogue = make_lazy_cached_scan_fns(
            model, cfg, mesh=mesh
        )

        def multi_step(state, table, sup_idx_s, qry_idx_s, label_s):
            uids = table["uids"]
            rows = prologue(state, uids)

            def scan_body(carry, xs):
                st, rw = carry
                si, qi, lab = xs
                st, rw, metrics = compact(
                    st, rw, (_gather(table, si), _gather(table, qi), lab)
                )
                return (st, rw), metrics

            (state, rows), metrics = jax.lax.scan(
                scan_body, (state, rows), (sup_idx_s, qry_idx_s, label_s)
            )
            return epilogue(state, rows, uids), metrics

        if mesh is None:
            return jax.jit(multi_step, donate_argnums=(0,))
        return _shard(
            multi_step, mesh, state_example, stacked=True,
            zero_opt=getattr(cfg, "zero_opt", False),
        )

    body = make_update_body(model, cfg, mesh=mesh)

    def multi_step(state, table, sup_idx_s, qry_idx_s, label_s):
        def scan_body(st, xs):
            si, qi, lab = xs
            sup, qry = _gather(table, si), _gather(table, qi)
            return body(st, (sup, qry, lab))

        return jax.lax.scan(scan_body, state, (sup_idx_s, qry_idx_s, label_s))

    if mesh is None:
        return jax.jit(multi_step, donate_argnums=(0,))
    return _shard(
        multi_step, mesh, state_example, stacked=True,
        zero_opt=getattr(cfg, "zero_opt", False),
    )


def make_token_cached_eval_step(model, cfg, mesh=None, state_example=None):
    import jax

    step = _eval_batch_metrics(model, cfg)

    if mesh is None:
        return jax.jit(step)
    return _shard(step, mesh, state_example, params_only=True, cfg=cfg)


def _eval_batch_metrics(model, cfg):
    """The per-batch cached eval body — ONE source for the single-dispatch
    eval step and its lax.map fused twin, so their metrics cannot drift."""
    from induction_network_on_fewrel_tpu.models.losses import episode_metrics
    from induction_network_on_fewrel_tpu.train.steps import LOSS_FNS

    def metrics(params, table, sup_idx, qry_idx, label):
        logits = model.apply(
            params, _gather(table, sup_idx), _gather(table, qry_idx)
        )
        return {
            "loss": LOSS_FNS[cfg.loss](logits, label),
            **episode_metrics(logits, label, cfg.na_rate > 0),
        }

    return metrics


def make_token_cached_multi_eval_step(model, cfg, mesh=None, state_example=None):
    """Fused token-cache eval: one dispatch scores S stacked index batches
    (see feature_cache.make_cached_multi_eval_step — same motivation)."""
    import jax

    body = _eval_batch_metrics(model, cfg)

    def multi(params, table, sup_s, qry_s, lab_s):
        return jax.lax.map(
            lambda xs: body(params, table, *xs), (sup_s, qry_s, lab_s)
        )

    if mesh is None:
        return jax.jit(multi)
    return _shard(
        multi, mesh, state_example, stacked=True, params_only=True, cfg=cfg
    )


def _shard(fn, mesh, state_example, stacked=False, params_only=False, cfg=None,
           zero_opt=False):
    """Cached-path shardings — delegated to feature_cache._shard_cached:
    state per the standard rules, the table replicated (the bare replicated
    sharding it declares for its table arg is a PREFIX pytree, so it covers
    this path's {word,pos1,pos2,mask} dict exactly as it covers a single
    feature array), index/label episode axes over 'dp'."""
    from induction_network_on_fewrel_tpu.train.feature_cache import _shard_cached

    return _shard_cached(
        fn, mesh, state_example, stacked, params_only, cfg=cfg,
        zero_opt=zero_opt,
    )
